"""On-disk columnar training snapshots: single-scan reads with memmap replay.

The training input spine streams the event table's deterministic
``(event_time_ms, event_id)``-ordered interaction scan TWICE per train
(pass 1 counts/vocab, pass 2 retention), every process in a multi-host mesh
repeats it, and repeated trains on the same app start from zero. ALX
(arxiv 2112.02194) is input-bound at scale exactly this way, and the
Spark-ML study (arxiv 1612.01437) pins most MLlib wall time on data prep,
not math. This module removes the repeated scans:

- :meth:`SnapshotStore.build` spills the ordered interaction stream ONCE
  into memory-mapped numpy column files (integer-encoded entities, epoch
  times, numeric ratings) plus first-appearance vocabularies;
- every later pass -- pass 1 counts, pass 2 retention, repeat trains,
  every process on a host -- replays the local memmap instead of SQL
  (``parallel.reader.snapshot_coo_chunks``);
- :meth:`SnapshotStore.refresh` extends an existing snapshot by scanning
  only ``event_time >= snapshot.until`` and appending: the scan order
  sorts strictly-later events after every snapshot row, so append-only
  refresh reproduces a cold bounded scan bit-for-bit. A cheap
  ``COUNT(*)`` over the covered prefix detects late-arriving or deleted
  rows and falls back to a full rebuild (exactness over cleverness).

Durability discipline matches ``data/wal.py``: generations are written to
a tmp dir, fsynced, and atomically renamed; every column file and the
vocabulary blob carry CRC32s in the manifest; a torn/truncated/corrupt
generation is rejected at load (and a valid older generation, if any, is
served instead); stale generations are GC'd after a successful commit.

On-disk layout (one key dir per scan spec, monotonically numbered
generations inside)::

    <root>/<key16>/
        gen-000001/
            manifest.json   # spec, time bound, row count, CRCs, version
            users.bin       # int64   full-stream entity codes
            items.bin       # int64   target codes; -1 = no target entity
            names.bin       # int32   event-name codes
            times.bin       # float64 epoch seconds (microsecond-exact)
            ratings.bin     # float64 JSON-number rating; NaN = absent
            vocabs.json     # {"users": [...], "items": [...], "names": [...]}
        gen-000002/...

The key hashes the scan spec (app/channel, event-name set, rating key,
target-entity filter, format version): any spec change lands in a fresh
key dir, so a stale snapshot can never serve a different scan's train.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import json
import logging
import os
import shutil
import time
import zlib
from dataclasses import dataclass, field
from typing import Iterator

import numpy as np

from predictionio_tpu.utils.metrics import global_registry

logger = logging.getLogger("pio.snapshot")

#: bump on any incompatible change to columns/manifest/vocab encoding
SNAPSHOT_FORMAT_VERSION = 1

#: modulus (ms per day) for the per-row event-time checksum shared with
#: ``sql_common.interaction_digest``: per-row values stay < 8.64e7 so a
#: 64-bit integer SUM cannot overflow (or fall back to float) in any
#: dialect at any realistic row count
TIME_DIGEST_MOD = 86_400_000

#: column name -> dtype; the fixed five-column interaction schema
COLUMN_DTYPES: dict[str, np.dtype] = {
    "users": np.dtype(np.int64),
    "items": np.dtype(np.int64),
    "names": np.dtype(np.int32),
    "times": np.dtype(np.float64),
    "ratings": np.dtype(np.float64),
}

#: duration buckets for scan/replay histograms: memmap replays land sub-
#: second, cold multi-million-row SQL scans take minutes
SCAN_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    120.0, 300.0, 600.0,
)

_REQUESTS = "pio_snapshot_requests_total"
_REQUESTS_HELP = (
    "Training-snapshot lookups by outcome (hit|miss_build|refresh_append|"
    "refresh_noop|rebuild_drift|rebuild_bound|invalid|unsupported)"
)
_SCAN_SECONDS = "pio_snapshot_scan_seconds"
_REPLAY_SECONDS = "pio_snapshot_replay_seconds"


def record_outcome(result: str) -> None:
    global_registry().inc(_REQUESTS, {"result": result}, help=_REQUESTS_HELP)


def record_scan_seconds(kind: str, seconds: float) -> None:
    global_registry().observe(
        _SCAN_SECONDS,
        seconds,
        {"kind": kind},
        buckets=SCAN_BUCKETS,
        help="SQL scan+spill duration per snapshot build/refresh",
    )


def record_replay_seconds(seconds: float) -> None:
    global_registry().observe(
        _REPLAY_SECONDS,
        seconds,
        buckets=SCAN_BUCKETS,
        help="Memmap replay duration per full pass over a snapshot",
    )


def snapshot_settings(
    runtime_conf=None,
    mode: str | None = None,
    snapshot_dir: str | None = None,
) -> tuple[str, str]:
    """Resolve ``(mode, root_dir)`` from explicit args > runtime conf >
    environment > defaults.

    ``pio train --snapshot-mode/--snapshot-dir`` lands in both the runtime
    conf (``pio.snapshot_mode``/``pio.snapshot_dir``) and the
    ``PIO_SNAPSHOT_MODE``/``PIO_SNAPSHOT_DIR`` env, so layers without a
    RuntimeContext (``PEventStore.dataset``) see the same setting. Default
    mode is ``off``: snapshots change read-freshness semantics, so they
    are strictly opt-in.
    """
    conf = runtime_conf or {}
    resolved_mode = (
        mode
        or conf.get("pio.snapshot_mode")
        or os.environ.get("PIO_SNAPSHOT_MODE")
        or "off"
    )
    if resolved_mode not in ("off", "use", "refresh"):
        raise ValueError(
            f"snapshot mode must be off|use|refresh, got {resolved_mode!r}"
        )
    root = (
        snapshot_dir
        or conf.get("pio.snapshot_dir")
        or os.environ.get("PIO_SNAPSHOT_DIR")
    )
    if not root:
        from predictionio_tpu.data.storage import base_dir

        root = os.path.join(base_dir(), "snapshots")
    return resolved_mode, root


def _now_utc() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def _ts_ms(ts: _dt.datetime) -> int:
    # THE ts_ms: manifest bounds and SQL scan bounds must agree
    # bit-for-bit, so share the definition rather than hand-copy it
    from predictionio_tpu.data.storage.sql_common import ts_ms

    return ts_ms(ts)


@dataclass(frozen=True)
class SnapshotSpec:
    """What one snapshot covers: the identity of a bounded interaction scan.

    ``event_names=None`` means the unfiltered scan; ``target_entity_type``
    keeps the scan API's three-valued filter (``...`` = any, ``None`` =
    rows without a target, a string = that type).
    """

    app_id: int
    channel_id: int | None = None
    event_names: tuple[str, ...] | None = None
    rating_key: str = "rating"
    target_entity_type: object = ...

    def canonical(self) -> dict:
        if self.target_entity_type is ...:
            target = {"filter": "any", "type": None}
        elif self.target_entity_type is None:
            target = {"filter": "none", "type": None}
        else:
            target = {"filter": "type", "type": str(self.target_entity_type)}
        return {
            "format_version": SNAPSHOT_FORMAT_VERSION,
            "app_id": int(self.app_id),
            "channel_id": None if self.channel_id is None else int(self.channel_id),
            # the scan's IN-filter is a set: orderings must share a snapshot
            "event_names": (
                None if self.event_names is None else sorted(self.event_names)
            ),
            "rating_key": self.rating_key,
            "target": target,
        }

    def key(self) -> str:
        material = json.dumps(self.canonical(), sort_keys=True)
        return hashlib.sha256(material.encode()).hexdigest()[:16]

    def scan_kwargs(self) -> dict:
        """The iter_interaction_chunks filter kwargs this spec pins."""
        kwargs: dict = {
            "channel_id": self.channel_id,
            "event_names": (
                None if self.event_names is None else list(self.event_names)
            ),
            "rating_key": self.rating_key,
        }
        if self.target_entity_type is not ...:
            kwargs["target_entity_type"] = self.target_entity_type
        return kwargs


class SnapshotInvalid(Exception):
    """A generation failed validation (torn file, CRC mismatch, bad spec)."""


class Snapshot:
    """An opened, validated snapshot generation: memmap columns + vocabs."""

    def __init__(self, path: str, manifest: dict):
        self.path = path
        self.manifest = manifest
        self._columns: dict[str, np.ndarray] = {}
        self._vocabs: dict[str, list[str]] | None = None

    def __len__(self) -> int:
        return int(self.manifest["row_count"])

    @property
    def until_time(self) -> _dt.datetime:
        """The EXCLUSIVE upper time bound, as the exact datetime the build
        scan used (re-parsed from ISO so ``ts_ms`` reproduces the same
        millisecond -- reconstructing from the stored ms via float division
        can land one ms off)."""
        return _dt.datetime.fromisoformat(self.manifest["until"])

    def column(self, name: str) -> np.ndarray:
        """Read-only memmap of one column (zero rows -> empty array)."""
        if name not in self._columns:
            dtype = COLUMN_DTYPES[name]
            if len(self) == 0:
                self._columns[name] = np.empty(0, dtype)
            else:
                self._columns[name] = np.memmap(
                    os.path.join(self.path, f"{name}.bin"),
                    dtype=dtype,
                    mode="r",
                    shape=(len(self),),
                )
        return self._columns[name]

    def vocab(self, which: str) -> list[str]:
        if self._vocabs is None:
            with open(os.path.join(self.path, "vocabs.json")) as f:
                self._vocabs = json.load(f)
        return self._vocabs[which]

    def open_columns(self) -> "Snapshot":
        """Eagerly open every column memmap. Called before a snapshot is
        handed out: open file handles survive a concurrent writer's GC
        unlinking this generation (POSIX), so replay cannot crash on a
        file that vanished between ensure() and the first chunk."""
        for c in COLUMN_DTYPES:
            self.column(c)
        return self

    def chunks(
        self, chunk_rows: int = 262_144
    ) -> Iterator[tuple[np.ndarray, ...]]:
        """Replay ``(users, items, names, times, ratings)`` array chunks."""
        cols = [self.column(c) for c in COLUMN_DTYPES]
        n = len(self)
        for lo in range(0, n, chunk_rows):
            hi = min(lo + chunk_rows, n)
            yield tuple(np.asarray(c[lo:hi]) for c in cols)


class _ColumnSpill:
    """Streams encoded column chunks to disk with running CRC32s.

    ``vocabs`` may be pre-seeded (refresh continues an existing
    vocabulary); CRCs may be pre-seeded with the copied prefix's CRCs
    (zlib.crc32 is resumable)."""

    def __init__(
        self,
        directory: str,
        vocabs: dict[str, dict[str, int]],
        crcs: dict[str, int] | None = None,
        time_digest: int = 0,
    ):
        self.dir = directory
        self.vocabs = vocabs
        self.crcs = dict(crcs or {c: 0 for c in COLUMN_DTYPES})
        self.rows = 0
        #: running sum of event_time_ms % TIME_DIGEST_MOD -- the cheap
        #: content fingerprint interaction_digest() re-derives in SQL
        self.time_digest = time_digest
        self._files = {
            c: open(os.path.join(directory, f"{c}.bin"), "ab")
            for c in COLUMN_DTYPES
        }

    def append_scan_chunk(self, ents, tgts, names, times_iso, ratings) -> None:
        n = len(ents)
        uv, iv, nv = (
            self.vocabs["users"], self.vocabs["items"], self.vocabs["names"]
        )

        def to_float(v) -> float:
            if v is None:
                return np.nan
            try:
                return float(v)  # drivers may hand numbers back as str/Decimal
            except (TypeError, ValueError):
                return np.nan

        arrays = {
            "users": np.fromiter(
                (uv.setdefault(e, len(uv)) for e in ents), np.int64, count=n
            ),
            "items": np.fromiter(
                (
                    -1 if t is None else iv.setdefault(t, len(iv))
                    for t in tgts
                ),
                np.int64,
                count=n,
            ),
            "names": np.fromiter(
                (nv.setdefault(x, len(nv)) for x in names), np.int32, count=n
            ),
            # the exact float64 the streaming reader computes per row, so
            # memmap replay is bit-identical to the live scan
            "times": np.fromiter(
                (
                    _dt.datetime.fromisoformat(s).timestamp()
                    for s in times_iso
                ),
                np.float64,
                count=n,
            ),
            "ratings": np.fromiter(
                (to_float(r) for r in ratings), np.float64, count=n
            ),
        }
        for c, arr in arrays.items():
            raw = arr.tobytes()
            self._files[c].write(raw)
            self.crcs[c] = zlib.crc32(raw, self.crcs[c])
        # (t * 1000).astype(int64) reproduces ts_ms()'s int(t*1000) per row
        # bit-for-bit (same float64 source, same multiply, same toward-zero
        # truncation), so this matches SQL's stored event_time_ms exactly.
        # fmod, not %: SQL modulo is TRUNCATED (sign of dividend) and
        # numpy's % is floored -- they disagree on pre-1970 event times
        ms = (arrays["times"] * 1000.0).astype(np.int64)
        self.time_digest += int(np.fmod(ms, TIME_DIGEST_MOD).sum())
        self.rows += n

    def close(self) -> None:
        for f in self._files.values():
            f.flush()
            os.fsync(f.fileno())
            f.close()


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_json_crc(path: str, obj) -> int:
    raw = json.dumps(obj).encode()
    with open(path, "wb") as f:
        f.write(raw)
        f.flush()
        os.fsync(f.fileno())
    return zlib.crc32(raw)


class SnapshotStore:
    """Build / load / refresh / GC snapshots for one scan spec."""

    def __init__(self, root: str, spec: SnapshotSpec):
        self.spec = spec
        self.dir = os.path.join(root, spec.key())

    # -- lookup ------------------------------------------------------------
    def _generations(self) -> list[tuple[int, str]]:
        try:
            entries = os.listdir(self.dir)
        except OSError:
            return []
        gens = []
        for name in entries:
            if name.startswith("gen-"):
                try:
                    gens.append((int(name[4:]), os.path.join(self.dir, name)))
                except ValueError:
                    continue
        return sorted(gens)

    def load(self) -> Snapshot | None:
        """Newest generation that survives validation; invalid ones are
        skipped (never deleted here -- a concurrent writer may still be
        committing) and counted."""
        for _, path in reversed(self._generations()):
            try:
                return self._validate(path)
            # OSError too: a concurrent builder's GC can unlink this
            # generation mid-validation (after the manifest/size probes) --
            # treat it as invalid and fall through to the next one rather
            # than failing the whole lookup
            except (SnapshotInvalid, OSError) as exc:
                record_outcome("invalid")
                logger.warning("rejecting snapshot %s: %s", path, exc)
        return None

    def _validate(self, gen_path: str) -> Snapshot:
        manifest_path = os.path.join(gen_path, "manifest.json")
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            raise SnapshotInvalid(f"unreadable manifest: {exc!r}")
        if manifest.get("format_version") != SNAPSHOT_FORMAT_VERSION:
            raise SnapshotInvalid(
                f"format_version {manifest.get('format_version')!r} !="
                f" {SNAPSHOT_FORMAT_VERSION}"
            )
        if manifest.get("spec") != self.spec.canonical():
            raise SnapshotInvalid(
                "manifest spec mismatch (changed event_names/rating_key/"
                "channel/target filter)"
            )
        rows = manifest.get("row_count")
        crcs = manifest.get("crc", {})
        if not isinstance(rows, int) or rows < 0:
            raise SnapshotInvalid(f"bad row_count {rows!r}")
        for c, dtype in COLUMN_DTYPES.items():
            path = os.path.join(gen_path, f"{c}.bin")
            want = rows * dtype.itemsize
            try:
                size = os.path.getsize(path)
            except OSError:
                size = -1
            if size != want:
                raise SnapshotInvalid(
                    f"column {c}: {size} bytes, want {want} (torn/truncated)"
                )
            if rows and _file_crc(path) != crcs.get(c):
                raise SnapshotInvalid(f"column {c}: CRC mismatch")
        vpath = os.path.join(gen_path, "vocabs.json")
        try:
            with open(vpath, "rb") as f:
                vraw = f.read()
        except OSError as exc:
            raise SnapshotInvalid(f"unreadable vocabs: {exc!r}")
        if zlib.crc32(vraw) != crcs.get("vocabs"):
            raise SnapshotInvalid("vocabs.json: CRC mismatch")
        vocabs = json.loads(vraw)
        for which, size in manifest.get("vocab_sizes", {}).items():
            if len(vocabs.get(which, ())) != size:
                raise SnapshotInvalid(f"vocab {which}: size mismatch")
        snap = Snapshot(gen_path, manifest)
        snap._vocabs = vocabs
        return snap.open_columns()

    # -- build / refresh ---------------------------------------------------
    def build(
        self,
        l_events,
        until_time: _dt.datetime,
        chunk_rows: int = 262_144,
        _start_snapshot: Snapshot | None = None,
    ) -> Snapshot:
        """Spill the bounded ordered scan into a new generation (ONE SQL
        round-trip). With ``_start_snapshot`` the new generation starts as
        a byte copy of it and the scan covers only ``[its until, ours)`` --
        the incremental-refresh fast path."""
        os.makedirs(self.dir, exist_ok=True)
        tmp = os.path.join(self.dir, f".tmp-{os.getpid()}-{time.monotonic_ns()}")
        os.makedirs(tmp)
        t0 = time.perf_counter()
        try:
            vocabs: dict[str, dict[str, int]] = {
                "users": {}, "items": {}, "names": {}
            }
            crcs = None
            scan_kwargs = self.spec.scan_kwargs()
            base_rows = 0
            base_digest = 0
            if _start_snapshot is not None:
                for c in COLUMN_DTYPES:
                    if len(_start_snapshot):
                        shutil.copyfile(
                            os.path.join(_start_snapshot.path, f"{c}.bin"),
                            os.path.join(tmp, f"{c}.bin"),
                        )
                crcs = {
                    c: _start_snapshot.manifest["crc"].get(c, 0)
                    for c in COLUMN_DTYPES
                }
                vocabs = {
                    which: {v: j for j, v in enumerate(_start_snapshot.vocab(which))}
                    for which in vocabs
                }
                base_rows = len(_start_snapshot)
                base_digest = int(_start_snapshot.manifest.get("time_digest", 0))
                scan_kwargs["start_time"] = _start_snapshot.until_time
            spill = _ColumnSpill(tmp, vocabs, crcs, time_digest=base_digest)
            spill.rows = base_rows
            for chunk in l_events.iter_interaction_chunks(
                app_id=self.spec.app_id,
                until_time=until_time,
                chunk_rows=chunk_rows,
                **scan_kwargs,
            ):
                spill.append_scan_chunk(*chunk)
            spill.close()
            scan_seconds = time.perf_counter() - t0
            kind = "build" if _start_snapshot is None else "refresh"
            record_scan_seconds(kind, scan_seconds)
            if _start_snapshot is not None and spill.rows == base_rows:
                # nothing new landed: keep serving the existing generation
                # (the next refresh re-scans the same empty window -- cheap)
                shutil.rmtree(tmp, ignore_errors=True)
                record_outcome("refresh_noop")
                return _start_snapshot
            vocab_lists = {
                which: list(mapping) for which, mapping in spill.vocabs.items()
            }
            vcrc = _write_json_crc(
                os.path.join(tmp, "vocabs.json"), vocab_lists
            )
            manifest = {
                "format_version": SNAPSHOT_FORMAT_VERSION,
                "spec": self.spec.canonical(),
                "until": until_time.isoformat(),
                "until_ms": _ts_ms(until_time),
                "row_count": spill.rows,
                "time_digest": spill.time_digest,
                "vocab_sizes": {w: len(v) for w, v in vocab_lists.items()},
                "crc": {**spill.crcs, "vocabs": vcrc},
                "created_at": _now_utc().isoformat(),
                "scan_seconds": round(scan_seconds, 3),
                "parent_rows": base_rows,
            }
            _write_json_crc(os.path.join(tmp, "manifest.json"), manifest)
            _fsync_dir(tmp)
            gen_path = self._commit(tmp)
            record_outcome("miss_build" if kind == "build" else "refresh_append")
            logger.info(
                "snapshot %s: %d rows (%+d) in %.2fs -> %s",
                kind, spill.rows, spill.rows - base_rows, scan_seconds,
                gen_path,
            )
            snap = Snapshot(gen_path, manifest)
            snap._vocabs = vocab_lists
            snap.open_columns()
            self.gc(keep=os.path.basename(gen_path))
            return snap
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def _commit(self, tmp: str) -> str:
        """Atomically publish ``tmp`` as the next generation. A concurrent
        builder may claim a number first; retry with the next one."""
        for _ in range(100):
            gens = self._generations()
            number = (gens[-1][0] + 1) if gens else 1
            target = os.path.join(self.dir, f"gen-{number:06d}")
            try:
                os.rename(tmp, target)
            except OSError:
                continue
            _fsync_dir(self.dir)
            return target
        raise OSError(f"could not claim a snapshot generation under {self.dir}")

    def refresh(
        self,
        l_events,
        until_time: _dt.datetime,
        chunk_rows: int = 262_144,
    ) -> Snapshot:
        """Extend the newest valid snapshot to ``until_time`` by appending
        the ``[old until, until_time)`` scan -- exact because the ordered
        stream sorts every new event after every covered one. Late-arriving
        or deleted rows inside the covered prefix (detected by a cheap
        COUNT over it) force a full rebuild instead."""
        base = self.load()
        if base is None:
            return self.build(l_events, until_time, chunk_rows)
        if _ts_ms(until_time) == base.manifest["until_ms"]:
            record_outcome("hit")
            return base
        if _ts_ms(until_time) < base.manifest["until_ms"]:
            # the cached generation covers BEYOND the requested bound (a
            # concurrent later train under the same spec): serving it
            # would replay extra rows. Refresh promises the exact bound --
            # rebuild at it (multi-process layout agreement depends on
            # every process replaying the same prefix).
            record_outcome("rebuild_bound")
            return self.build(l_events, until_time, chunk_rows)
        filters = {
            k: v
            for k, v in self.spec.scan_kwargs().items()
            if k != "rating_key"
        }
        if hasattr(l_events, "interaction_digest"):
            covered, digest = l_events.interaction_digest(
                app_id=self.spec.app_id, until_time=base.until_time, **filters
            )
            drifted = covered != len(base) or digest != int(
                base.manifest.get("time_digest", -1)
            )
        elif hasattr(l_events, "count_interactions"):
            covered = l_events.count_interactions(
                app_id=self.spec.app_id, until_time=base.until_time, **filters
            )
            drifted = covered != len(base)
        else:
            covered, drifted = len(base), False
        if drifted:
            record_outcome("rebuild_drift")
            logger.warning(
                "snapshot %s: covered prefix drifted (%d stored rows vs"
                " %d in the event table, or time checksum mismatch) --"
                " late-arriving, deleted, or altered events; rebuilding"
                " from scratch",
                base.path, len(base), covered,
            )
            return self.build(l_events, until_time, chunk_rows)
        return self.build(
            l_events, until_time, chunk_rows, _start_snapshot=base
        )

    def ensure(
        self,
        l_events,
        mode: str,
        until_time: _dt.datetime | None = None,
        chunk_rows: int = 262_144,
    ) -> Snapshot | None:
        """The one call sites use: a ready snapshot per ``mode``, or None
        when snapshots don't apply (mode off, or a backend without the
        columnar chunk scan)."""
        if mode == "off":
            return None
        if not hasattr(l_events, "iter_interaction_chunks"):
            record_outcome("unsupported")
            logger.warning(
                "snapshot mode %r requested but the event backend has no"
                " columnar chunk scan; falling back to direct reads", mode
            )
            return None
        until_time = until_time or _now_utc()
        if mode == "use":
            snap = self.load()
            if snap is not None:
                record_outcome("hit")
                return snap
            return self.build(l_events, until_time, chunk_rows)
        if mode == "refresh":
            return self.refresh(l_events, until_time, chunk_rows)
        raise ValueError(f"snapshot mode must be off|use|refresh, got {mode!r}")

    # -- GC ----------------------------------------------------------------
    def gc(self, keep: str, tmp_ttl_s: float = 3600.0) -> None:
        """Remove generations OLDER than ``keep`` plus abandoned tmp dirs
        older than ``tmp_ttl_s`` (a live concurrent builder's tmp dir is
        younger than that). Newer generations are never touched: a
        concurrent builder may have committed one after ours, and two
        racing GCs that each keep their own would otherwise delete both."""
        try:
            keep_number = int(keep[4:])
        except ValueError:
            return
        for number, path in self._generations():
            if number < keep_number:
                shutil.rmtree(path, ignore_errors=True)
        now = time.time()
        try:
            entries = os.listdir(self.dir)
        except OSError:
            return
        for name in entries:
            if name.startswith(".tmp-"):
                path = os.path.join(self.dir, name)
                try:
                    # newest mtime INSIDE the dir, not the dir's own: a
                    # live builder only appends to files created at scan
                    # start, which never bumps the directory mtime
                    newest = max(
                        [os.path.getmtime(path)]
                        + [
                            os.path.getmtime(os.path.join(path, f))
                            for f in os.listdir(path)
                        ]
                    )
                    if now - newest > tmp_ttl_s:
                        shutil.rmtree(path, ignore_errors=True)
                except OSError:
                    pass


def snapshot_block_dir(snapshot: Snapshot) -> str:
    """Default home of a generation's streamed-ALS block caches
    (``parallel.stream``). Living INSIDE the generation directory ties
    the cache's lifetime to its source data: snapshot GC reaps the cache
    with the generation, and a refreshed generation starts clean. Extra
    files here never affect generation validation -- ``_validate`` checks
    only the manifest-named column files."""
    return os.path.join(snapshot.path, "blocks")


def _file_crc(path: str, bufsize: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb", buffering=0) as f:
        while True:
            block = f.read(bufsize)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)
