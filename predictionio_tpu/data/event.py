"""Event model: the append-only record everything else is built on.

Behavioral model: reference ``data/.../storage/Event.scala`` +
``EventJson4sSupport.scala`` (apache/predictionio layout, unverified --
SURVEY.md section 2.2 #4 and Appendix A). Field set, name validation rules,
reserved ``$set/$unset/$delete`` semantics, and the JSON wire shape are kept
contract-compatible; the implementation is new.
"""

from __future__ import annotations

import datetime as _dt
import uuid
from dataclasses import dataclass, field, replace
from typing import Any, Mapping

from predictionio_tpu.data.datamap import DataMap

#: Reserved event names with entity-property mutation semantics.
SET_EVENT = "$set"
UNSET_EVENT = "$unset"
DELETE_EVENT = "$delete"
SPECIAL_EVENTS = frozenset({SET_EVENT, UNSET_EVENT, DELETE_EVENT})


class EventValidationError(ValueError):
    """Raised when an event violates the wire contract."""


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise EventValidationError(msg)


def validate_event_name(name: str) -> None:
    """Reserved-prefix rules: ``$``-events other than set/unset/delete and any
    ``pio_``-prefixed name are rejected (SURVEY.md Appendix A)."""
    _require(bool(name), "event name must not be empty")
    if name.startswith("$"):
        _require(name in SPECIAL_EVENTS, f"unsupported reserved event {name!r}")
    else:
        _require(not name.startswith("pio_"), f"event name {name!r}: prefix 'pio_' is reserved")


#: reserved entity types the framework itself writes (feedback loop)
INTERNAL_ENTITY_TYPES = frozenset({"pio_pr"})


def validate_entity(kind: str, value: str) -> None:
    _require(isinstance(value, str), f"{kind} must be a string, got {type(value).__name__}")
    _require(bool(value), f"{kind} must not be empty")
    # the pio_pr exemption is for entity *types* (feedback loop); ids keep the
    # full reserved-prefix rule
    exempt = kind in ("entityType", "targetEntityType") and value in INTERNAL_ENTITY_TYPES
    _require(
        not value.startswith("pio_") or exempt,
        f"{kind} {value!r}: prefix 'pio_' is reserved",
    )


def parse_event_time(value: str) -> _dt.datetime:
    """Parse an ISO-8601 timestamp; naive times are taken as UTC."""
    _require(isinstance(value, str), f"eventTime must be a string, got {type(value).__name__}")
    try:
        # Accept the trailing-Z form the SDKs emit.
        ts = _dt.datetime.fromisoformat(value.replace("Z", "+00:00"))
    except ValueError as exc:
        raise EventValidationError(f"cannot parse eventTime {value!r}: {exc}") from None
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=_dt.timezone.utc)
    return ts


def format_event_time(ts: _dt.datetime) -> str:
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=_dt.timezone.utc)
    return ts.isoformat(timespec="milliseconds")


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


@dataclass(frozen=True)
class Event:
    """One immutable event record (wire contract: SURVEY.md Appendix A)."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: str | None = None
    target_entity_id: str | None = None
    properties: DataMap = field(default_factory=DataMap)
    event_time: _dt.datetime = field(default_factory=_utcnow)
    event_id: str | None = None
    pr_id: str | None = None
    creation_time: _dt.datetime = field(default_factory=_utcnow)

    def __post_init__(self):
        # normalize naive datetimes to UTC so mixed-source events compare/sort
        # and serialize consistently (frozen dataclass: use object.__setattr__)
        if self.event_time.tzinfo is None:
            object.__setattr__(
                self, "event_time", self.event_time.replace(tzinfo=_dt.timezone.utc)
            )
        if self.creation_time.tzinfo is None:
            object.__setattr__(
                self, "creation_time", self.creation_time.replace(tzinfo=_dt.timezone.utc)
            )
        validate_event_name(self.event)
        validate_entity("entityType", self.entity_type)
        validate_entity("entityId", self.entity_id)
        _require(
            (self.target_entity_type is None) == (self.target_entity_id is None),
            "targetEntityType and targetEntityId must be set together",
        )
        if self.target_entity_type is not None:
            validate_entity("targetEntityType", self.target_entity_type)
            validate_entity("targetEntityId", self.target_entity_id)
        if self.event == UNSET_EVENT:
            _require(len(self.properties) > 0, "$unset event requires non-empty properties")
        if self.event == DELETE_EVENT:
            _require(
                self.target_entity_type is None,
                "$delete event must not have a target entity",
            )
        if self.event in (SET_EVENT, UNSET_EVENT):
            _require(
                self.target_entity_type is None,
                f"{self.event} event must not have a target entity",
            )

    # -- JSON wire serde ----------------------------------------------------
    @classmethod
    def from_json_obj(cls, obj: Mapping[str, Any]) -> "Event":
        _require(isinstance(obj, Mapping), "event body must be a JSON object")
        _require("event" in obj, "field 'event' is required")
        _require("entityType" in obj, "field 'entityType' is required")
        _require("entityId" in obj, "field 'entityId' is required")
        props = obj.get("properties")
        if props is None:
            props = {}
        _require(isinstance(props, Mapping), "'properties' must be a JSON object")
        event_time = (
            parse_event_time(obj["eventTime"]) if obj.get("eventTime") else _utcnow()
        )
        _require(isinstance(obj["event"], str), "'event' must be a string")
        return cls(
            event=obj["event"],
            entity_type=str(obj["entityType"]),
            entity_id=str(obj["entityId"]),
            target_entity_type=obj.get("targetEntityType"),
            target_entity_id=obj.get("targetEntityId"),
            properties=DataMap(props),
            event_time=event_time,
            event_id=obj.get("eventId"),
            pr_id=obj.get("prId"),
            **(
                {"creation_time": parse_event_time(obj["creationTime"])}
                if obj.get("creationTime")
                else {}
            ),
        )

    def to_json_obj(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "eventId": self.event_id,
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
        }
        if self.target_entity_type is not None:
            out["targetEntityType"] = self.target_entity_type
            out["targetEntityId"] = self.target_entity_id
        out["properties"] = self.properties.to_dict()
        out["eventTime"] = format_event_time(self.event_time)
        if self.pr_id is not None:
            out["prId"] = self.pr_id
        out["creationTime"] = format_event_time(self.creation_time)
        return out

    def with_id(self, event_id: str | None = None) -> "Event":
        return replace(self, event_id=event_id or uuid.uuid4().hex)
