"""Webhook connectors: map third-party payloads to Events.

Behavioral model: reference ``data/.../webhooks/{ConnectorUtil,JsonConnector,
FormConnector}.scala`` + segmentio/mailchimp connectors (apache/predictionio
layout, unverified -- SURVEY.md section 2.2 #14). Pluggable registry keyed by
the URL path segment under ``/webhooks/``.
"""

from __future__ import annotations

import abc
from typing import Any, Mapping

from predictionio_tpu.data.event import Event, EventValidationError


class ConnectorError(ValueError):
    pass


class JsonConnector(abc.ABC):
    """Maps a JSON webhook payload to an Event."""

    @abc.abstractmethod
    def to_event_json(self, payload: Mapping[str, Any]) -> Mapping[str, Any]: ...

    def to_event(self, payload: Mapping[str, Any]) -> Event:
        try:
            return Event.from_json_obj(self.to_event_json(payload))
        except EventValidationError as exc:
            raise ConnectorError(str(exc)) from exc


class FormConnector(abc.ABC):
    """Maps form-encoded webhook fields to an Event."""

    @abc.abstractmethod
    def to_event_json(self, form: Mapping[str, str]) -> Mapping[str, Any]: ...

    def to_event(self, form: Mapping[str, str]) -> Event:
        try:
            return Event.from_json_obj(self.to_event_json(form))
        except EventValidationError as exc:
            raise ConnectorError(str(exc)) from exc


class ExampleJsonConnector(JsonConnector):
    """Reference-style example connector (exampleJson parity role)."""

    def to_event_json(self, payload):
        for field in ("type", "userId"):
            if field not in payload:
                raise ConnectorError(f"webhook payload missing {field!r}")
        return {
            "event": payload["type"],
            "entityType": "user",
            "entityId": str(payload["userId"]),
            "properties": payload.get("properties", {}),
            **({"eventTime": payload["timestamp"]} if "timestamp" in payload else {}),
        }


class SegmentIOConnector(JsonConnector):
    """segment.com track-call mapping (SegmentIOConnector parity role)."""

    def to_event_json(self, payload):
        if payload.get("type") != "track":
            raise ConnectorError("segmentio connector only accepts 'track' calls")
        user = payload.get("userId") or payload.get("anonymousId")
        if not user:
            raise ConnectorError("segmentio payload has no userId/anonymousId")
        if not payload.get("event"):
            raise ConnectorError("segmentio payload missing 'event'")
        out = {
            "event": payload["event"],
            "entityType": "user",
            "entityId": str(user),
            "properties": payload.get("properties", {}),
        }
        if payload.get("timestamp"):
            out["eventTime"] = payload["timestamp"]
        return out


class ExampleFormConnector(FormConnector):
    def to_event_json(self, form):
        for field in ("type", "userId"):
            if field not in form:
                raise ConnectorError(f"webhook form missing {field!r}")
        return {
            "event": form["type"],
            "entityType": "user",
            "entityId": form["userId"],
            "properties": {
                k: v for k, v in form.items() if k not in ("type", "userId")
            },
        }


class MailChimpConnector(FormConnector):
    """MailChimp webhook mapping (MailChimpConnector parity role).

    MailChimp posts form-encoded fields: ``type`` (subscribe / unsubscribe /
    profile / upemail / cleaned / campaign), ``fired_at``, and bracketed
    ``data[...]`` fields. Subscriber events map to entityType=user (the
    subscriber id) targeting the list; campaign events map the campaign
    targeting the list.
    """

    _SUBSCRIBER_TYPES = ("subscribe", "unsubscribe", "profile", "upemail", "cleaned")

    def to_event_json(self, form):
        mc_type = form.get("type")
        if not mc_type:
            raise ConnectorError("mailchimp form missing 'type'")
        data = {
            k[len("data["):-1]: v
            for k, v in form.items()
            if k.startswith("data[") and k.endswith("]") and "][" not in k
        }
        properties = dict(data)

        if mc_type in self._SUBSCRIBER_TYPES:
            # upemail payloads carry new_id/new_email instead of id/email
            entity_id = (
                data.get("id")
                or data.get("new_id")
                or data.get("email")
                or data.get("new_email")
            )
            if not entity_id:
                raise ConnectorError(
                    f"mailchimp {mc_type!r} form missing data[id]/data[email]"
                )
            out = {
                "event": mc_type,
                "entityType": "user",
                "entityId": str(entity_id),
                "properties": properties,
            }
        elif mc_type == "campaign":
            if not data.get("id"):
                raise ConnectorError("mailchimp campaign form missing data[id]")
            out = {
                "event": mc_type,
                "entityType": "campaign",
                "entityId": str(data["id"]),
                "properties": properties,
            }
        else:
            raise ConnectorError(f"mailchimp webhook type {mc_type!r} not supported")

        if data.get("list_id"):
            out["targetEntityType"] = "list"
            out["targetEntityId"] = str(data["list_id"])
        if form.get("fired_at"):
            # MailChimp timestamps are naive UTC "YYYY-MM-DD HH:MM:SS"
            out["eventTime"] = form["fired_at"].replace(" ", "T") + "+00:00"
        return out


#: path segment under /webhooks/ -> connector instance
JSON_CONNECTORS: dict[str, JsonConnector] = {
    "example": ExampleJsonConnector(),
    "segmentio": SegmentIOConnector(),
}
FORM_CONNECTORS: dict[str, FormConnector] = {
    "exampleform": ExampleFormConnector(),
    "mailchimp": MailChimpConnector(),
}


def register_json_connector(name: str, connector: JsonConnector) -> None:
    JSON_CONNECTORS[name] = connector


def register_form_connector(name: str, connector: FormConnector) -> None:
    FORM_CONNECTORS[name] = connector
