"""Durable group-commit ingestion pipeline for the Event Server.

Per-record storage commits are the canonical ingestion bottleneck (each
``POST /events.json`` paying one transaction); the pipeline replaces them
with the classic WAL + group-commit design:

1. request threads park on a bounded queue (full queue -> 429 backpressure
   via :class:`IngestOverload`, instead of unbounded thread pile-up);
2. a single background writer drains the queue in batches bounded by
   ``max_batch`` / ``group_commit_ms``, frames the batch into the WAL
   (``data/wal.py``) and makes it durable with ONE fsync;
3. requests are acknowledged at that point -- durability comes from the
   WAL, not the store;
4. the batch is flushed into the event store through
   ``LEvents.insert_batch`` (single transaction / ``executemany`` on the
   SQL backends), after which the WAL checkpoint advances.

A crash anywhere between ack and checkpoint is recovered by
:func:`replay_wal_into_storage` at startup: event ids are assigned BEFORE
the WAL append, and replay inserts with ``on_duplicate="ignore"``, so the
cycle is exactly-once -- nothing acked is lost, nothing is double-applied.
(Process crashes are covered unconditionally; surviving host power loss
additionally requires the event store's own commits to be durable --
postgres/mysql defaults, or sqlite with ``SYNCHRONOUS=FULL`` -- because
the checkpoint advances once the store COMMITS, not once it fsyncs.)

With ``wal_partitions`` P > 1, :class:`PartitionedIngestPipeline` runs P
of these single-writer pipelines side by side, one per WAL partition
(``data/wal.PartitionedWal``), routing each event by the stable entity
hash shared with the serving tier (``utils/stablehash``). Per-entity
ordering holds (one entity -> one partition -> one writer thread) while
the P fsync streams proceed in parallel -- the group-commit latency stops
being a serial bottleneck. Every durability invariant above applies
per partition unchanged; there is deliberately NO cross-partition
protocol to reason about.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.wal import PartitionedWal, WriteAheadLog
from predictionio_tpu.obs.trace import NULL_TRACER, current_context
from predictionio_tpu.utils.stablehash import stable_bucket

logger = logging.getLogger("pio.ingest")

#: batch-size histogram buckets (events per group commit)
BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0)


@dataclass
class IngestConfig:
    """CLI/server-facing knobs (``pio eventserver --ingest-*``)."""

    mode: str = "sync"            # sync | wal
    queue_size: int = 2048
    group_commit_ms: float = 5.0
    max_batch: int = 256
    fsync_policy: str = "always"  # always | interval | never
    wal_dir: str | None = None    # default: $PIO_FS_BASEDIR/wal
    segment_bytes: int = 64 << 20
    wal_partitions: int = 1       # hash-sharded durability streams

    def resolved_wal_dir(self) -> str:
        if self.wal_dir:
            return self.wal_dir
        import os

        from predictionio_tpu.data.storage import base_dir

        return os.path.join(base_dir(), "wal")


class IngestOverload(Exception):
    """Bounded ingest queue is full; callers map this to HTTP 429."""

    def __init__(self, retry_after_s: float = 1.0):
        super().__init__("ingestion queue full")
        self.retry_after_s = retry_after_s


@dataclass
class _Pending:
    event: Event
    app_id: int
    channel_id: int | None
    future: Future = field(default_factory=Future)
    #: (trace_id, span_id) of the submitting request, for span fan-out
    trace_ctx: tuple | None = None
    submitted: float = field(default_factory=time.perf_counter)


def _wal_payload(
    event: Event, app_id: int, channel_id: int | None,
    trace_id: str | None = None,
) -> bytes:
    obj = {"e": event.to_json_obj(), "a": app_id, "c": channel_id}
    if trace_id:
        # the trace rides the durable record: a post-crash replay can
        # attach its span to the ORIGINAL ingest trace
        obj["t"] = trace_id
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")


def _wal_parse(payload: bytes) -> tuple[Event, int, int | None, str | None]:
    obj = json.loads(payload.decode("utf-8"))
    return Event.from_json_obj(obj["e"]), obj["a"], obj["c"], obj.get("t")


#: public names for the frame codec: the continuous-learning WAL tail
#: (``online.follower``) parses the same records from another process
wal_payload = _wal_payload
wal_parse = _wal_parse


class IngestPipeline:
    """Single-writer group-commit pipeline in front of ``LEvents``.

    ``l_events`` is a zero-arg callable returning the DAO (resolved per
    flush so tests/env changes that reset the storage registry keep
    working). With ``wal=None`` the pipeline still group-commits but acks
    only after the storage flush (no durability layer to ack from).
    """

    def __init__(
        self,
        wal: WriteAheadLog | None,
        l_events=None,
        queue_size: int = 2048,
        group_commit_ms: float = 5.0,
        max_batch: int = 256,
        metrics=None,
        tracer=None,
        part: int | None = None,
    ):
        if l_events is None:
            from predictionio_tpu.data import storage as storage_registry

            l_events = storage_registry.get_l_events
        self.wal = wal
        self._l_events = l_events
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._queue: queue.Queue[_Pending] = queue.Queue(maxsize=queue_size)
        self.group_commit_s = group_commit_ms / 1000.0
        self.max_batch = max_batch
        self.metrics = metrics
        # partition index when owned by a PartitionedIngestPipeline: names
        # the writer thread and labels this writer's commit metrics with
        # {part=}; None = standalone single-stream pipeline (no labels, the
        # pre-partitioning exposition unchanged)
        self.part = part
        self._part_labels = None if part is None else {"part": str(part)}
        self._stopping = threading.Event()
        # serializes the stopping-check-then-enqueue in submit() against
        # stop()'s flag set: once the flag is visible, no further enqueue can
        # land, so the writer's final queue-empty check is race-free and no
        # future is ever stranded unresolved
        self._submit_gate = threading.Lock()
        self._thread = threading.Thread(
            target=self._writer_loop,
            name="pio-ingest-writer" if part is None
            else f"pio-ingest-writer-p{part}",
            daemon=True,
        )
        self.retry_after_s = max(1.0, group_commit_ms / 1000.0)
        self.storage_errors = 0
        # WAL-acked batches whose storage flush failed, oldest first as
        # (items, last_seqno). The writer re-flushes them in order and the
        # checkpoint NEVER advances past them -- otherwise a later healthy
        # batch's checkpoint would strand (then GC) acked records. Bounded:
        # past _retry_cap events, submit() applies backpressure.
        self._retry_batches: list[tuple[list, int]] = []
        self._retry_events = 0
        self._retry_cap = max(queue_size, 1024)
        self._last_retry = 0.0

    # -- request side ---------------------------------------------------------
    def start(self) -> "IngestPipeline":
        self._thread.start()
        return self

    def submit(self, event: Event, app_id: int, channel_id: int | None) -> Future:
        """Enqueue one event; the returned future resolves to its eventId
        once the record is durable. Raises :class:`IngestOverload` when the
        queue is full (the backpressure contract)."""
        if self._retry_events > self._retry_cap:
            # storage has been down long enough to back up the retry
            # backlog: stop acking new work instead of buffering unboundedly
            raise IngestOverload(self.retry_after_s)
        # the id is assigned BEFORE the WAL append so replay after a crash
        # re-applies the same identity (exactly-once via duplicate skip)
        pending = _Pending(
            event if event.event_id else event.with_id(), app_id, channel_id
        )
        if self.tracer.enabled:
            pending.trace_ctx = current_context()
        with self._submit_gate:
            if self._stopping.is_set():
                raise IngestOverload(self.retry_after_s)
            try:
                self._queue.put_nowait(pending)
            except queue.Full:
                raise IngestOverload(self.retry_after_s) from None
        return pending.future

    def depth(self) -> int:
        return self._queue.qsize()

    # -- writer side ----------------------------------------------------------
    def _collect_batch(self) -> list[_Pending]:
        """Block for the first item, then gather until the group-commit
        deadline or the batch cap. During shutdown, drain without waiting."""
        try:
            first = self._queue.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        deadline = time.monotonic() + self.group_commit_s
        while len(batch) < self.max_batch:
            remaining = deadline - time.monotonic()
            if self._stopping.is_set():
                remaining = 0.0
            try:
                if remaining > 0:
                    batch.append(self._queue.get(timeout=remaining))
                else:
                    batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return batch

    def _writer_loop(self) -> None:
        while True:
            batch = self._collect_batch()
            if not batch:
                self._flush_retries()
                if self._stopping.is_set() and self._queue.empty():
                    self._flush_retries(force=True)  # last chance pre-exit;
                    # anything still parked survives in the WAL for replay
                    return
                continue
            try:
                self._commit(batch)
            except Exception as exc:  # a poisoned batch must not kill the writer
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(exc)

    def _flush_retries(self, force: bool = False) -> None:
        """Re-flush parked batches IN ORDER, advancing the checkpoint as each
        lands; stop at the first failure (ordering preserves the contiguous-
        prefix invariant the checkpoint depends on)."""
        if not self._retry_batches:
            return
        if not force and time.monotonic() - self._last_retry < 0.25:
            return
        self._last_retry = time.monotonic()
        while self._retry_batches:
            items, last_seqno = self._retry_batches[0]
            try:
                self._l_events().insert_batch(items, on_duplicate="ignore")
            except Exception:
                return
            self._retry_batches.pop(0)
            self._retry_events -= len(items)
            if self.wal is not None:
                self.wal.checkpoint(last_seqno)

    def _commit(self, batch: list[_Pending]) -> None:
        # the writer thread's own root span: every group commit is one
        # trace (op "ingest.commit" -- the --slow-commit-ms target), and
        # its WAL/storage stages fan out to each request's trace too
        with self.tracer.span(
            "ingest.commit", attrs={"batch_size": len(batch)}
        ) as commit_span:
            self._commit_traced(batch, commit_span)

    def _commit_traced(self, batch: list[_Pending], commit_span) -> None:
        t0 = time.perf_counter()
        last_seqno = None
        if self.wal is not None:
            for p in batch:
                last_seqno = self.wal.append(
                    _wal_payload(
                        p.event, p.app_id, p.channel_id,
                        p.trace_ctx[0] if p.trace_ctx else None,
                    )
                )
            sync0 = time.perf_counter()
            self.wal.sync()
            sync1 = time.perf_counter()
            # span-list refs captured while the request roots are still
            # guaranteed open (their threads are parked on the futures);
            # the fan-out itself runs only after every ack below
            traced = [
                (p.trace_ctx, p.submitted,
                 self.tracer.live_spans(p.trace_ctx[0]))
                for p in batch if p.trace_ctx is not None
            ] if self.tracer.enabled else []
            # ack at the durability point: the WAL holds the records even if
            # the storage flush below fails or the process dies
            for p in batch:
                p.future.set_result(p.event.event_id)
            self._trace_fanout(traced, len(batch), t0, sync0, sync1,
                               commit_span)
        items = [(p.event, p.app_id, p.channel_id) for p in batch]
        if self.wal is None:
            # no durability layer: ack only after the store has the events,
            # and surface flush errors to the parked request threads
            with self.tracer.span("storage.flush", attrs={"events": len(items)}):
                self._l_events().insert_batch(items)
            for p in batch:
                p.future.set_result(p.event.event_id)
            self._observe(batch, time.perf_counter() - t0)
            return
        # older failed batches flush first; while any remain, this batch must
        # park behind them -- checkpointing it now would strand (and GC) the
        # acked records still awaiting their flush
        self._flush_retries(force=True)
        if self._retry_batches:
            self._park(items, last_seqno, "storage still unavailable")
        else:
            try:
                # "ignore", not "error": ids are assigned pre-WAL precisely so
                # duplicate application is a no-op. A client-supplied eventId
                # that already exists dedupes alone instead of aborting the
                # whole multi-tenant transaction (and it makes crash replay
                # and client retries idempotent).
                with self.tracer.span(
                    "storage.flush", attrs={"events": len(items)}
                ):
                    self._l_events().insert_batch(items, on_duplicate="ignore")
                self.wal.checkpoint(last_seqno)
            except Exception as exc:
                self._park(items, last_seqno, repr(exc))
        self._observe(batch, time.perf_counter() - t0)

    def _trace_fanout(
        self, traced: list, n_records: int, t0: float, sync0: float,
        sync1: float, commit_span,
    ) -> None:
        """Record per-request queue-wait plus SHARED wal.append/wal.fsync
        spans (one span id across the whole batch) into every traced
        request's trace, and the same stages into the writer's commit
        trace. Runs AFTER the durability acks (tracing must never delay
        an ack; the span lists in ``traced`` were captured while the
        roots were still open), and each physical WAL stage bridges into
        the span histogram exactly once per commit -- not once per
        coalesced request."""
        tracer = self.tracer
        if not tracer.enabled:
            return
        try:
            extra = None
            if commit_span.trace_id is not None:
                extra = (commit_span.trace_id, commit_span.span_id,
                         tracer.live_spans(commit_span.trace_id))
            tracer.record_fanout(
                traced,
                [
                    ("wal.append", t0, sync0, {"records": n_records}),
                    ("wal.fsync", sync0, sync1),
                ],
                queue_op="ingest.queue_wait",
                bridge_queue=True,
                extra=extra,
            )
        except Exception:
            logger.warning("ingest trace recording failed", exc_info=True)

    def _park(self, items: list, last_seqno: int, reason: str) -> None:
        self._retry_batches.append((items, last_seqno))
        self._retry_events += len(items)
        self.storage_errors += 1
        logger.error(
            "storage flush failed for %d acked event(s); parked for"
            " in-process retry (WAL-durable): %s",
            len(items),
            reason,
        )

    def _observe(self, batch: list[_Pending], seconds: float) -> None:
        if self.metrics is None:
            return
        self.metrics.inc(
            "pio_ingest_events_total",
            labels=self._part_labels,
            amount=float(len(batch)),
            help="Events committed through the ingest pipeline",
        )
        self.metrics.observe(
            "pio_ingest_commit_seconds",
            seconds,
            labels=self._part_labels,
            help="Group-commit latency (WAL sync + storage flush)",
        )
        self.metrics.observe(
            "pio_ingest_batch_size",
            float(len(batch)),
            labels=self._part_labels,
            buckets=BATCH_BUCKETS,
            help="Events per group commit",
        )
        if self.storage_errors:
            self.metrics.set_counter(
                "pio_ingest_storage_errors_total",
                float(self.storage_errors),
                labels=self._part_labels,
                help="Batches whose storage flush failed (recovered via WAL replay)",
            )

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the writer. With ``drain`` (default) every queued event is
        committed first -- the graceful-shutdown contract."""
        with self._submit_gate:
            self._stopping.set()
        if not drain:
            # reject queued work so request threads don't hang on futures
            self._reject_queued()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
        # belt-and-braces for the join-timeout path (a wedged writer leaves
        # the queue populated); the submit gate guarantees nothing NEW lands
        # after the flag, so this cannot race fresh enqueues
        self._reject_queued()

    def _reject_queued(self) -> None:
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                return
            if not p.future.done():
                p.future.set_exception(IngestOverload(self.retry_after_s))


def replay_wal_into_storage(
    wal: WriteAheadLog, l_events=None, batch_size: int = 500, tracer=None
) -> int:
    """Re-apply every un-checkpointed WAL record to the event store;
    returns the number of records examined. Duplicate records (crash
    between storage flush and checkpoint) are skipped by the store
    (``on_duplicate="ignore"``), making replay idempotent.

    WAL records carry their originating trace id: with a ``tracer``, each
    distinct replayed trace gains a ``wal.replay`` span, so the original
    ingest trace shows its post-crash completion instead of dead-ending
    at the ack."""
    if l_events is None:
        from predictionio_tpu.data import storage as storage_registry

        l_events = storage_registry.get_l_events
    tracer = tracer if tracer is not None else NULL_TRACER
    count = 0
    last_seqno = 0
    pending: list[tuple[Event, int, int | None]] = []
    replayed_traces: set[str] = set()
    t_start = time.perf_counter()

    def flush() -> None:
        if pending:
            l_events().insert_batch(pending, on_duplicate="ignore")
            pending.clear()

    for seqno, payload in wal.replay():
        event, app_id, channel_id, trace_id = _wal_parse(payload)
        pending.append((event, app_id, channel_id))
        if trace_id and tracer.enabled:
            replayed_traces.add(trace_id)
        last_seqno = seqno
        count += 1
        if len(pending) >= batch_size:
            flush()
    flush()
    if last_seqno:
        wal.checkpoint(last_seqno)
    t_end = time.perf_counter()
    for trace_id in replayed_traces:
        tracer.record_span(
            trace_id, "wal.replay", t_start, t_end,
            attrs={"records_total": count},
        )
    return count


def partition_of(event: Event, partitions: int) -> int:
    """The WAL partition that owns ``event`` -- the ONE routing rule.

    Buckets by ``entity_id`` with the exact hash the serving fabric
    shards user factors by (``serving/shardmap.shard_of`` is the same
    function): every record an entity ever writes lands in one
    partition, so per-entity ordering is preserved by that partition's
    single writer thread, and the ingest stream for an entity lives
    where the serving tier expects its state.
    """
    return stable_bucket(event.entity_id, partitions)


def replay_partitioned_wal(
    wal: PartitionedWal, l_events=None, batch_size: int = 500, tracer=None
) -> int:
    """Startup replay over every partition; returns total records
    examined. Each partition replays against its OWN checkpoint and
    advances it independently (exactly-once per partition, the
    single-log contract of :func:`replay_wal_into_storage` applied P
    times); records cannot cross partitions because replay never
    re-routes -- it re-applies each partition's log verbatim."""
    return sum(
        replay_wal_into_storage(
            part, l_events=l_events, batch_size=batch_size, tracer=tracer
        )
        for part in wal.parts
    )


class PartitionedIngestPipeline:
    """P single-writer :class:`IngestPipeline` streams behind one submit.

    Each partition owns a complete pipeline -- bounded queue, writer
    thread, WAL stream, retry parking -- so the fsync/storage-flush
    stages of different partitions overlap freely; the only shared code
    path is the stateless hash in :func:`partition_of`. The per-partition
    queues split the configured ``queue_size`` so total buffered work
    (and thus worst-case replay) stays bounded by the same knob as the
    single-stream pipeline.
    """

    def __init__(
        self,
        wal: PartitionedWal,
        l_events=None,
        queue_size: int = 2048,
        group_commit_ms: float = 5.0,
        max_batch: int = 256,
        metrics=None,
        tracer=None,
    ):
        self.wal = wal
        self.partitions = wal.partitions
        per_part_queue = max(64, queue_size // self.partitions)
        # P=1 passes part=None: metrics stay unlabeled and the writer
        # thread keeps its pre-partitioning name -- the degenerate case is
        # observably identical to the original single-stream pipeline
        self.pipes: list[IngestPipeline] = [
            IngestPipeline(
                wal.part(k),
                l_events=l_events,
                queue_size=per_part_queue,
                group_commit_ms=group_commit_ms,
                max_batch=max_batch,
                metrics=metrics,
                tracer=tracer,
                part=None if self.partitions == 1 else k,
            )
            for k in range(self.partitions)
        ]

    # -- request side -------------------------------------------------------
    def start(self) -> "PartitionedIngestPipeline":
        for pipe in self.pipes:
            pipe.start()
        return self

    def submit(self, event: Event, app_id: int, channel_id: int | None) -> Future:
        return self.pipes[partition_of(event, self.partitions)].submit(
            event, app_id, channel_id
        )

    def depth(self) -> int:
        return sum(pipe.depth() for pipe in self.pipes)

    def depth_of(self, part: int) -> int:
        return self.pipes[part].depth()

    @property
    def retry_after_s(self) -> float:
        return max(pipe.retry_after_s for pipe in self.pipes)

    @property
    def storage_errors(self) -> int:
        return sum(pipe.storage_errors for pipe in self.pipes)

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop every partition writer CONCURRENTLY: a drain is dominated
        by fsync + storage-flush latency, and serializing P drains would
        multiply shutdown time by exactly the factor the partitions were
        added to divide."""
        stoppers = [
            threading.Thread(
                target=pipe.stop, kwargs={"drain": drain, "timeout": timeout}
            )
            for pipe in self.pipes
        ]
        for t in stoppers:
            t.start()
        for t in stoppers:
            t.join(timeout=timeout + 5.0)
