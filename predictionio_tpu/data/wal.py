"""Segmented append-only write-ahead log for event ingestion.

The Spark-era reference delegated ingestion durability to external stores
(HBase WALs, ES translogs); the native rebuild needs its own. This WAL is
the durability point of the group-commit pipeline (``data/ingest.py``): a
``POST /events.json`` is acknowledged once its record is framed into the
current segment and the segment is synced per the fsync policy, and the
storage flush happens off the request path. On startup, the tail of the
log past the last storage checkpoint is replayed into the event store.

On-disk layout (one directory per log)::

    wal-00000000000000000001.log   segment files, named by FIRST seqno
    wal-00000000000000004096.log
    wal.ckpt                       last seqno known flushed to storage

Record frame (little-endian): ``uint32 payload_len | uint32 crc32 |
uint64 seqno | payload``, where the CRC covers the seqno bytes plus the
payload. A torn tail (partial frame, bad CRC, or an impossible length from
a crash mid-append) terminates the scan of that segment only; every
restart opens a fresh segment -- or, when the crash tore the very first
frame (so the restart re-derives the same segment name), truncates the
torn garbage first -- so intact records are never hidden behind a torn
frame.

Fsync policy trade-off (``always`` | ``interval`` | ``never``):

- ``always``  -- fsync on every :meth:`sync` (one per group commit, NOT
  one per record: the pipeline amortizes it over the batch);
- ``interval``-- fsync at most once per ``fsync_interval_ms``; bounds the
  post-crash loss window to that interval;
- ``never``   -- OS page cache only; survives process death, not host
  death.

Partitioned layout (``wal-partitions P`` with P > 1) shards the log by
entity hash into P fully independent sub-logs, each with its own seqno
space, segment files, checkpoint, and fsync stream::

    wal.parts                      partition count (the layout marker)
    part-00000/wal-...log          partition 0: a complete log as above
    part-00000/wal.ckpt
    part-00001/...

P = 1 is the degenerate case: no marker, no subdirectories -- the flat
single-log layout above, byte-for-byte what earlier releases wrote, so
old logs replay unchanged. :func:`resolve_partitions` adopts whatever
layout is on disk over the requested count (a WAL's partition count is
fixed at birth; re-routing a live log would strand records).
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib

logger = logging.getLogger("pio.wal")

#: frame header: payload length, crc32(seqno_bytes + payload), seqno
_FRAME = struct.Struct("<IIQ")

#: sanity ceiling on a single record; a longer length field means the
#: header bytes are garbage from a torn write, not a real record
MAX_RECORD_BYTES = 64 << 20

FSYNC_POLICIES = ("always", "interval", "never")

_SEGMENT_PREFIX = "wal-"
_SEGMENT_SUFFIX = ".log"
_CHECKPOINT_FILE = "wal.ckpt"
_PARTS_FILE = "wal.parts"
_PART_DIR_PREFIX = "part-"


def _part_dir_name(index: int) -> str:
    return f"{_PART_DIR_PREFIX}{index:05d}"


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _segment_name(first_seqno: int) -> str:
    return f"{_SEGMENT_PREFIX}{first_seqno:020d}{_SEGMENT_SUFFIX}"


def _segment_first_seqno(name: str) -> int | None:
    if not (name.startswith(_SEGMENT_PREFIX) and name.endswith(_SEGMENT_SUFFIX)):
        return None
    digits = name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]
    return int(digits) if digits.isdigit() else None


def _scan_segment(path: str):
    """Yield ``(seqno, payload)`` for every intact frame; stop at the first
    torn or corrupt one (crash mid-append leaves at most one)."""
    for _, seqno, payload in _scan_frames(path):
        yield seqno, payload


def _scan_frames(path: str):
    """Like :func:`_scan_segment` but also yields each frame's end offset,
    so callers can truncate a torn tail."""
    offset = 0
    with open(path, "rb") as f:
        while True:
            header = f.read(_FRAME.size)
            if len(header) < _FRAME.size:
                return  # clean EOF or torn header
            length, crc, seqno = _FRAME.unpack(header)
            if length > MAX_RECORD_BYTES:
                return  # garbage length: torn frame
            payload = f.read(length)
            if len(payload) < length:
                return  # torn payload
            if zlib.crc32(header[8:] + payload) != crc:
                return  # bit rot / torn rewrite
            offset += _FRAME.size + length
            yield offset, seqno, payload


def _valid_prefix_length(path: str) -> int:
    """Byte length of the intact-frame prefix (0 for a fully torn file)."""
    end = 0
    for end, _, _ in _scan_frames(path):
        pass
    return end


def read_checkpoint(directory: str) -> int:
    """Last seqno known flushed to storage, read straight off disk (0 when
    absent/unreadable). The continuous-learning follower polls this from a
    DIFFERENT process than the ingest writer: a record is only safe to act
    on once it is in the event store (the ack point is the WAL, but the
    snapshot refresh scans SQL), so the follower bounds its tail at the
    storage high-water mark, not at the append head."""
    try:
        with open(os.path.join(directory, _CHECKPOINT_FILE)) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def oldest_seqno(directory: str) -> int | None:
    """First seqno of the oldest retained segment (None = empty log). A
    cross-process tail whose cursor trails this has a GC gap: records it
    never saw were collected after their storage flush, so it must
    resynchronize from the event store instead of the log."""
    firsts = []
    try:
        entries = os.listdir(directory)
    except OSError:
        return None
    for name in entries:
        first = _segment_first_seqno(name)
        if first is not None:
            firsts.append(first)
    return min(firsts) if firsts else None


def iter_log_records(
    directory: str, after_seqno: int = 0, upto_seqno: int | None = None
):
    """Yield ``(seqno, payload)`` for intact records with ``after_seqno <
    seqno <= upto_seqno`` in seqno order, reading the segment files
    directly (no :class:`WriteAheadLog` instance, no locks -- safe from a
    follower process while the owning writer keeps appending: frames are
    published by a single sequential write and the CRC scan stops at the
    first torn tail). Segments whose entire range is below ``after_seqno``
    are skipped via the layout invariant (a segment's name is its first
    record's seqno)."""
    names = []
    try:
        entries = os.listdir(directory)
    except OSError:
        return
    for name in entries:
        if _segment_first_seqno(name) is not None:
            names.append(name)
    names.sort()
    firsts = [_segment_first_seqno(n) for n in names]
    for i, name in enumerate(names):
        # every record in segment i has seqno < firsts[i + 1]
        if i + 1 < len(names) and firsts[i + 1] - 1 <= after_seqno:
            continue
        if upto_seqno is not None and firsts[i] > upto_seqno:
            return
        for seqno, payload in _scan_segment(os.path.join(directory, name)):
            if seqno <= after_seqno:
                continue
            if upto_seqno is not None and seqno > upto_seqno:
                return
            yield seqno, payload


def _flat_log_exists(directory: str) -> bool:
    """True when ``directory`` holds a single-partition log: segment files
    or a checkpoint directly at the root (the pre-partitioning layout)."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return False
    for name in entries:
        if name == _CHECKPOINT_FILE or _segment_first_seqno(name) is not None:
            return True
    return False


def _marker_partitions(directory: str) -> int | None:
    """The ``wal.parts`` marker's count, or None when absent/unreadable."""
    try:
        with open(os.path.join(directory, _PARTS_FILE)) as f:
            on_disk = int(f.read().strip())
    except (OSError, ValueError):
        return None
    return on_disk if on_disk >= 1 else None


def resolve_partitions(directory: str, requested: int = 1) -> int:
    """The partition count a log at ``directory`` MUST be opened with.

    A WAL's partition count is fixed at birth: the entity->partition hash
    only recovers per-entity ordering if every record an entity ever
    wrote lives in one partition, so re-routing a live log would strand
    (or worse, reorder) records. On-disk evidence therefore wins over the
    requested count, with a warning on mismatch so the operator knows the
    flag was ignored rather than silently honored:

    1. a ``wal.parts`` marker pins the count it records;
    2. else a flat single-partition log at the root pins 1 (move the old
       log aside to re-partition);
    3. else (empty/new directory) the requested count stands.
    """
    if requested < 1:
        raise ValueError(f"wal partitions must be >= 1, got {requested}")
    on_disk = _marker_partitions(directory)
    if on_disk is not None:
        if on_disk != requested:
            logger.warning(
                "wal %s is partitioned P=%d on disk; ignoring requested "
                "P=%d (partition count is fixed at log creation)",
                directory, on_disk, requested,
            )
        return on_disk
    if _flat_log_exists(directory):
        if requested > 1:
            logger.warning(
                "wal %s holds an existing single-partition log; ignoring "
                "requested P=%d (move the old log aside to re-partition)",
                directory, requested,
            )
        return 1
    return requested


def partition_count(directory: str) -> int:
    """Partition count of the log at ``directory``, read straight off disk
    (1 when unmarked -- the flat layout). Cross-process safe: followers
    call this to discover how many tails to run. A pure read: unlike
    :func:`resolve_partitions` it never warns, because there is no
    requested count to mismatch."""
    return _marker_partitions(directory) or 1


def partition_dirs(directory: str, partitions: int | None = None) -> list[str]:
    """The per-partition log directories, in partition order. For the flat
    P=1 layout this is ``[directory]`` itself -- every consumer that maps
    over partitions handles old logs with zero special-casing."""
    n = partition_count(directory) if partitions is None else partitions
    if n <= 1:
        return [directory]
    return [os.path.join(directory, _part_dir_name(k)) for k in range(n)]


class WriteAheadLog:
    """Thread-safe via an internal lock; the ingest pipeline is the single
    writer in practice, but replay/checkpoint may come from other threads."""

    def __init__(
        self,
        directory: str,
        segment_bytes: int = 64 << 20,
        fsync_policy: str = "always",
        fsync_interval_ms: float = 100.0,
    ):
        if fsync_policy not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync_policy must be one of {FSYNC_POLICIES}, got {fsync_policy!r}"
            )
        self.directory = directory
        self.segment_bytes = segment_bytes
        self.fsync_policy = fsync_policy
        self.fsync_interval_s = fsync_interval_ms / 1000.0
        self._lock = threading.Lock()
        self._last_fsync = 0.0
        #: observability counters (read without the lock: monotonic ints /
        #: a last-written float, mirrored into /metrics at scrape time)
        self.append_count = 0
        self.fsync_count = 0
        self.last_fsync_s = 0.0
        # collectible segments only appear on rotation (and at startup,
        # where prior-run segments may be replay-covered): gate GC on that
        # instead of paying a directory listing per group commit
        self._rotated_since_gc = True
        os.makedirs(directory, exist_ok=True)
        # the checkpoint is read once and cached: it only ever advances
        # through this instance, and a stale on-disk value is safe by design
        self._committed = self._read_checkpoint()
        # recover the seqno cursor: one past the last intact record anywhere
        # in the log (the checkpoint can trail behind after a crash)
        last = self._committed
        for path in self._segments():
            for seqno, _ in _scan_segment(path):
                if seqno > last:
                    last = seqno
        self._next_seqno = last + 1
        # always a fresh segment: appending after a torn frame would make the
        # torn bytes look like a mid-file corruption and hide the new records
        self._file = None
        self._segment_size = 0
        self._open_segment()

    # -- segments -----------------------------------------------------------
    def _segments(self) -> list[str]:
        names = [
            n
            for n in os.listdir(self.directory)
            if _segment_first_seqno(n) is not None
        ]
        names.sort()  # zero-padded first-seqno names sort chronologically
        return [os.path.join(self.directory, n) for n in names]

    def _open_segment(self) -> None:
        if self._file is not None:
            self._file.flush()
            if self.fsync_policy != "never":
                os.fsync(self._file.fileno())
            self._file.close()
        path = os.path.join(self.directory, _segment_name(self._next_seqno))
        # name collision means the existing file holds NO intact records
        # (any intact record would have advanced the seqno scan past this
        # name): a torn first frame from a crash mid-append. Appending after
        # torn bytes would hide the new records from replay -- truncate the
        # garbage away first.
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if size:
            valid = _valid_prefix_length(path)
            if valid < size:
                with open(path, "r+b") as f:
                    f.truncate(valid)
        self._file = open(path, "ab")
        self._segment_size = self._file.tell()
        self._rotated_since_gc = True

    # -- write path ----------------------------------------------------------
    def append(self, payload: bytes) -> int:
        """Frame and buffer one record; returns its seqno. Durability comes
        from the following :meth:`sync` (the group-commit boundary)."""
        with self._lock:
            frame_len = _FRAME.size + len(payload)
            # rotate BEFORE taking the seqno so the fresh segment's name
            # equals its first record's seqno (the layout invariant _gc and
            # replay lower-bounding rely on)
            if self._segment_size + frame_len > self.segment_bytes and self._segment_size:
                self._open_segment()
            seqno = self._next_seqno
            self._next_seqno += 1
            seq_bytes = struct.pack("<Q", seqno)
            frame = (
                _FRAME.pack(len(payload), zlib.crc32(seq_bytes + payload), seqno)
                + payload
            )
            self._file.write(frame)
            self._segment_size += frame_len
            self.append_count += 1
            return seqno

    def sync(self) -> None:
        """Make buffered records durable per the fsync policy.

        The fsync runs OUTSIDE the writer lock (``pio check`` C002):
        holding it across the disk flush would park every concurrent
        ``append`` behind disk latency once per group commit -- the lock
        protects in-memory framing state, not the disk. The fd is dup'd
        under the lock so a rotation closing the segment concurrently
        cannot invalidate it mid-fsync (fsync on a dup flushes the same
        open file description), and records appended after the dup only
        ever gain durability early."""
        with self._lock:
            self._file.flush()
            if self.fsync_policy == "never":
                return
            if self.fsync_policy == "interval":
                if time.monotonic() - self._last_fsync < self.fsync_interval_s:
                    return
            fd = os.dup(self._file.fileno())
        t0 = time.monotonic()
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        self.fsync_count += 1
        self.last_fsync_s = time.monotonic() - t0
        # only a SUCCESSFUL fsync consumes the interval slot -- if it
        # raised, the caller's retry must actually hit the disk instead of
        # short-circuiting on a pre-advanced timestamp (benign unlocked
        # write: worst case between racing syncs is one extra fsync)
        if self.fsync_policy == "interval":
            self._last_fsync = time.monotonic()

    # -- checkpoint / replay --------------------------------------------------
    def _read_checkpoint(self) -> int:
        # ONE definition of the checkpoint file format (the follower's
        # cross-process read shares it)
        return read_checkpoint(self.directory)

    def committed(self) -> int:
        """Last seqno known flushed to storage (0 = nothing)."""
        return self._committed

    def checkpoint(self, seqno: int) -> None:
        """Advance the storage high-water mark; periodically drop fully-
        covered segments. This runs once per group commit, so it stays
        cheap: no fsync (the checkpoint is an optimization hint -- a stale
        or torn one after a crash only means extra idempotent replay, never
        loss) and segment GC is amortized."""
        with self._lock:
            if seqno <= self._committed:
                return
            self._committed = seqno
            path = os.path.join(self.directory, _CHECKPOINT_FILE)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(seqno))
            os.replace(tmp, path)
            if self._rotated_since_gc:
                self._rotated_since_gc = False
                self._gc(seqno)

    def _gc(self, committed: int) -> None:
        segments = self._segments()
        current = os.path.join(
            self.directory, os.path.basename(self._file.name)
        )
        for path, next_path in zip(segments, segments[1:]):
            if path == current:
                continue
            next_first = _segment_first_seqno(os.path.basename(next_path))
            # every record in `path` has seqno < next_first; fully committed
            # segments are dead weight
            if next_first is not None and next_first - 1 <= committed:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def replay(self):
        """Yield ``(seqno, payload)`` for every record past the checkpoint,
        in seqno order. Safe against torn tails; duplicate delivery is
        possible (crash between storage flush and checkpoint), so consumers
        must apply records idempotently."""
        committed = self.committed()
        for path in self._segments():
            for seqno, payload in _scan_segment(path):
                if seqno > committed:
                    yield seqno, payload

    def pending(self) -> int:
        """Count of un-checkpointed records on disk (replay cost estimate)."""
        return sum(1 for _ in self.replay())

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                if self.fsync_policy != "never":
                    os.fsync(self._file.fileno())
                self._file.close()
                self._file = None


class PartitionedWal:
    """P independent :class:`WriteAheadLog` streams under one root.

    Each partition is a COMPLETE log -- own seqno space, own segments,
    own checkpoint, own group-commit fsync stream -- so P writer threads
    fsync in parallel with zero shared write state, and replay/durability
    invariants (R003: fsync before cursor) hold per partition with no
    cross-partition protocol at all. Routing (which entity goes to which
    partition) is the caller's job via ``utils.stablehash``; this class
    only owns the layout.

    P = 1 opens one inner log rooted at ``directory`` itself: the on-disk
    bytes are identical to a plain :class:`WriteAheadLog`, old flat logs
    replay unchanged, and no marker file is written. P > 1 stamps
    ``wal.parts`` FIRST (fsync'd: the marker is the layout's source of
    truth for every later open and for cross-process followers -- a crash
    between subdir creation and an unmarked marker must not make the same
    directory resolve to P=1 on restart).
    """

    def __init__(
        self,
        directory: str,
        partitions: int = 1,
        segment_bytes: int = 64 << 20,
        fsync_policy: str = "always",
        fsync_interval_ms: float = 100.0,
    ):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.partitions = resolve_partitions(directory, partitions)
        if self.partitions > 1:
            self._write_marker(self.partitions)
        self.parts: list[WriteAheadLog] = [
            WriteAheadLog(
                part_dir,
                segment_bytes=segment_bytes,
                fsync_policy=fsync_policy,
                fsync_interval_ms=fsync_interval_ms,
            )
            for part_dir in partition_dirs(directory, self.partitions)
        ]

    def _write_marker(self, partitions: int) -> None:
        path = os.path.join(self.directory, _PARTS_FILE)
        try:
            with open(path) as f:
                if int(f.read().strip()) == partitions:
                    return
        except (OSError, ValueError):
            pass
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(partitions))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # the marker is the layout's source of truth: without a directory
        # fsync the new entry itself can vanish at a power cut, and a
        # restarted reader would resolve a different partition count
        _fsync_dir(self.directory)

    def part(self, index: int) -> WriteAheadLog:
        return self.parts[index]

    def part_dirs(self) -> list[str]:
        return partition_dirs(self.directory, self.partitions)

    # -- aggregate observability (mirrors WriteAheadLog's counters so the
    # -- event server's scrape hook works against either) -------------------
    @property
    def append_count(self) -> int:
        return sum(p.append_count for p in self.parts)

    @property
    def fsync_count(self) -> int:
        return sum(p.fsync_count for p in self.parts)

    @property
    def last_fsync_s(self) -> float:
        return max((p.last_fsync_s for p in self.parts), default=0.0)

    def pending(self) -> int:
        return sum(p.pending() for p in self.parts)

    def close(self) -> None:
        for p in self.parts:
            p.close()
