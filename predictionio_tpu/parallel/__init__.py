"""Parallel execution over the device mesh: the Spark-substrate replacement.

Reference parallelism accounting (SURVEY.md section 2.6/2.7): the reference's
only real strategy is RDD data parallelism over Spark's Netty shuffle, plus
MLlib ALS's internal block model-parallelism. Here:

- data parallelism  -> batch-dim sharding over the ``data`` mesh axis (pjit)
- ALS block model-parallelism -> factors sharded over the mesh with XLA
  collectives for block exchange (``parallel.als``, design anchor: ALX,
  arxiv 2112.02194)
- broadcast          -> replicated sharding (NamedSharding with None spec)
- driver-local       -> mesh of 1
- Spark Netty shuffle / driver RPC -> XLA collectives over ICI/DCN via
  ``jax.distributed`` (``workflow.context`` initializes multi-host)
"""

from predictionio_tpu.parallel.distributed import (
    build_mesh,
    host_local_batch,
    init_distributed,
)
from predictionio_tpu.parallel.mesh import (
    local_mesh,
    replicated,
    row_sharded,
    shard_rows,
)
from predictionio_tpu.parallel.ring_attention import plain_attention, ring_attention
from predictionio_tpu.parallel.ulysses import ulysses_attention

__all__ = [
    "build_mesh",
    "host_local_batch",
    "init_distributed",
    "local_mesh",
    "replicated",
    "row_sharded",
    "shard_rows",
    "plain_attention",
    "ring_attention",
    "ulysses_attention",
]
