"""Sharded host-side event reader for the ALS/cooccurrence data path.

SURVEY.md section 2.6 names the TPU-native equivalent of Spark's
partitioned event scan a "host-side sharded event reader". The default
``build_als_data`` path has every process load and pack the FULL edge set
(each reads the same event store) -- correct, but at ALX-scale catalogs it
is the first thing to OOM a host. This module is the scaling path:

1. every process streams the SAME deterministically-ordered COO chunk
   stream (bounded memory per chunk -- e.g. the SQL backends'
   ``iter_interaction_chunks`` keyset-stable scan);
2. pass 1 accumulates per-entity interaction counts only (O(entities));
3. both sides' bucket plans are computed from the counts -- deterministic,
   so every process derives the SAME layout without communicating;
4. pass 2 RETAINS only the edges whose row lands in this process's
   data-axis shard of each side (~edges/processes + skew, the
   memory-scaling claim the tests instrument via ``retained_edges``);
5. the local rows pack into per-bucket blocks (forced to the global
   padded length) and ``als_fit`` assembles them with
   ``jax.make_array_from_process_local_data`` -- no host ever
   materializes a global array of edge extent.

The reference analogue is HBase's ``TableInputFormat`` splits feeding
Spark executors (SURVEY section 3.1): partition-local reads, global
layout by plan, not by shuffle.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator

import numpy as np

from predictionio_tpu.parallel.als import (
    ALSConfig,
    ALSData,
    BucketedCSR,
    _BucketPlan,
    _plan_buckets,
)
from predictionio_tpu.ops.ragged import pack_padded_csr, round_up

#: a chunk is (users, items, values, times-or-None), integer-encoded
Chunk = tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray | None]
#: zero-arg callable producing a fresh pass over the stream
ChunkSource = Callable[[], Iterable[Chunk]]


class IncrementalEncoder:
    """First-appearance string->int vocabulary, stable across passes.

    Every process consumes the same ordered stream, so ids agree across
    processes AND across the two passes (setdefault is idempotent).
    """

    def __init__(self) -> None:
        self.vocab: dict[str, int] = {}

    def encode(self, values) -> np.ndarray:
        v = self.vocab
        return np.fromiter(
            (v.setdefault(x, len(v)) for x in values),
            dtype=np.int64,
            count=len(values),
        )

    @property
    def ids(self) -> list[str]:
        return list(self.vocab)


def store_coo_chunks(
    l_events,
    app_id: int,
    channel_id: int | None = None,
    event_names: list[str] | None = None,
    rating_key: str = "rating",
    chunk_rows: int = 262_144,
    default_value: float = 1.0,
    event_values: dict[str, float] | None = None,
    until_time: _dt.datetime | None = None,
) -> tuple[ChunkSource, IncrementalEncoder, IncrementalEncoder]:
    """COO chunk source over a backend's columnar chunked scan.

    Returns ``(source, user_encoder, item_encoder)``; the encoders fill in
    stream order during the first pass and are the id<->index mapping the
    serving model needs. Rows with no numeric rating carry
    ``default_value`` (implicit-feedback events like "view"/"buy").
    ``event_values`` maps EVENT TYPE -> value instead (the e-commerce
    buy-weighted confidence scheme), ignoring per-row ratings entirely.
    Requires the backend to expose ``iter_interaction_chunks`` (the SQL
    family does); others can stream through any adapter that yields the
    same five columns.

    ``until_time`` bounds every pass to an identical event prefix. The
    event server accepts writes DURING ``pio train``, so without a bound
    pass 2 can see entities pass 1 never counted (an ``IndexError`` deep
    in the slot map), and in multi-host, processes scanning at different
    wall times would derive divergent layouts. Callers capture it once
    when the training handle is created and thread it through.
    """
    users_enc, items_enc = IncrementalEncoder(), IncrementalEncoder()

    def source() -> Iterator[Chunk]:
        for ents, tgts, names, times_iso, ratings in l_events.iter_interaction_chunks(
            app_id=app_id,
            channel_id=channel_id,
            event_names=event_names,
            rating_key=rating_key,
            chunk_rows=chunk_rows,
            until_time=until_time,
        ):
            keep = [i for i, t in enumerate(tgts) if t is not None]
            uu = users_enc.encode([ents[i] for i in keep])
            ii = items_enc.encode([tgts[i] for i in keep])
            def value_of(i):
                if event_values is not None:
                    return event_values.get(names[i], default_value)
                return default_value if ratings[i] is None else float(ratings[i])

            vals = np.fromiter(
                (value_of(i) for i in keep), dtype=np.float32, count=len(keep)
            )
            tt = np.fromiter(
                (
                    _dt.datetime.fromisoformat(times_iso[i]).timestamp()
                    for i in keep
                ),
                dtype=np.float64,
                count=len(keep),
            )
            yield uu, ii, vals, tt

    return source, users_enc, items_enc


def store_multi_event_chunks(
    l_events,
    app_id: int,
    event_names: list[str],
    channel_id: int | None = None,
    rating_key: str = "rating",
    chunk_rows: int = 262_144,
    default_value: float = 1.0,
    until_time: _dt.datetime | None = None,
) -> tuple[dict[str, ChunkSource], IncrementalEncoder, IncrementalEncoder]:
    """Per-event-type COO chunk sources over ONE shared entity universe.

    The Universal Recommender's cross-occurrence needs every event type's
    CSR row-indexed by the same user universe. Each returned source
    replays the SAME full multi-type scan and encodes EVERY row through
    the shared encoders (so ids are identical no matter which type's
    source runs first, or how often), emitting only its own type's rows.
    A per-type two-pass build therefore costs 2 * len(event_names) scans
    -- streaming-bounded memory is the trade. ``until_time`` bounds every
    scan to one identical prefix (see ``store_coo_chunks``): with
    2 * len(event_names) passes the mid-train-write window is widest here.
    """
    users_enc, items_enc = IncrementalEncoder(), IncrementalEncoder()

    def source_for(wanted: str) -> ChunkSource:
        def source() -> Iterator[Chunk]:
            for ents, tgts, names, times_iso, _ratings in (
                l_events.iter_interaction_chunks(
                    app_id=app_id,
                    channel_id=channel_id,
                    event_names=event_names,
                    rating_key=rating_key,
                    chunk_rows=chunk_rows,
                    until_time=until_time,
                )
            ):
                keep = [k for k, t in enumerate(tgts) if t is not None]
                uu = users_enc.encode([ents[k] for k in keep])
                ii = items_enc.encode([tgts[k] for k in keep])
                sel = np.fromiter(
                    (names[k] == wanted for k in keep),
                    dtype=bool,
                    count=len(keep),
                )
                if not sel.any():
                    continue
                tt = np.fromiter(
                    (
                        _dt.datetime.fromisoformat(times_iso[k]).timestamp()
                        for k, s in zip(keep, sel)
                        if s
                    ),
                    dtype=np.float64,
                    count=int(sel.sum()),
                )
                yield (
                    uu[sel], ii[sel],
                    np.full(int(sel.sum()), default_value, np.float32),
                    tt,
                )

        return source

    return {n: source_for(n) for n in event_names}, users_enc, items_enc


def _kept_user_remap(snapshot) -> tuple[np.ndarray, list[str]]:
    """Remap snapshot user codes to the ids the LIVE scan would assign.

    The snapshot encodes users by first appearance over ALL rows (the
    ``EventDataset`` contract); the COO readers encode by first appearance
    over rows WITH a target entity only. A user appearing first in a
    targetless row would get a different id, so replay re-derives the
    kept-rows-only first-appearance order vectorially and the streamed
    and snapshot-served builds stay bit-identical.
    Returns ``(remap, kept_vocab)`` with ``remap[old_code] -> new id``
    (-1 for users never kept).
    """
    kept_users = np.asarray(snapshot.column("users"))[
        np.asarray(snapshot.column("items")) >= 0
    ]
    uniq, first_idx = np.unique(kept_users, return_index=True)
    old_in_order = uniq[np.argsort(first_idx, kind="stable")]
    full_vocab = snapshot.vocab("users")
    remap = np.full(len(full_vocab), -1, dtype=np.int64)
    remap[old_in_order] = np.arange(old_in_order.size)
    return remap, [full_vocab[int(o)] for o in old_in_order]


def _prefilled(vocab: list[str]) -> IncrementalEncoder:
    enc = IncrementalEncoder()
    enc.vocab = {v: j for j, v in enumerate(vocab)}
    return enc


def snapshot_coo_chunks(
    snapshot,
    chunk_rows: int = 262_144,
    default_value: float = 1.0,
    event_values: dict[str, float] | None = None,
) -> tuple[ChunkSource, IncrementalEncoder, IncrementalEncoder]:
    """``store_coo_chunks``, served from a columnar snapshot's memmaps.

    Same contract, zero SQL: every pass replays the spilled column files
    with vectorized decode (value mapping via array lookup instead of a
    per-row python loop), and the returned encoders come back PRE-FILLED
    with the exact vocabularies the live scan would have produced --
    chunks, ids, values, and times are bit-identical to the streamed
    build over the same bounded prefix.
    """
    import time as _time

    from predictionio_tpu.data.snapshot import record_replay_seconds

    remap, kept_users = _kept_user_remap(snapshot)
    users_enc = _prefilled(kept_users)
    items_enc = _prefilled(snapshot.vocab("items"))
    if event_values is not None:
        name_vals = np.fromiter(
            (
                event_values.get(nm, default_value)
                for nm in snapshot.vocab("names")
            ),
            dtype=np.float32,
            count=len(snapshot.vocab("names")),
        )

    def source() -> Iterator[Chunk]:
        t0 = _time.perf_counter()
        for uu_raw, ii_raw, nn_raw, tt_raw, rr_raw in snapshot.chunks(chunk_rows):
            sel = ii_raw >= 0
            uu = remap[uu_raw[sel]]
            ii = ii_raw[sel]
            if event_values is not None:
                vals = name_vals[nn_raw[sel]]
            else:
                rr = rr_raw[sel]
                vals = np.where(np.isnan(rr), default_value, rr).astype(
                    np.float32
                )
            yield uu, ii, vals, tt_raw[sel]
        record_replay_seconds(_time.perf_counter() - t0)

    return source, users_enc, items_enc


def snapshot_multi_event_chunks(
    snapshot,
    event_names: list[str],
    chunk_rows: int = 262_144,
    default_value: float = 1.0,
) -> tuple[dict[str, ChunkSource], IncrementalEncoder, IncrementalEncoder]:
    """``store_multi_event_chunks``, served from a snapshot's memmaps.

    The shared entity universe comes back pre-filled (it is fixed by the
    spilled stream), so the ``universe_pass`` priming scan and all
    2 * len(event_names) per-type SQL scans collapse into cheap memmap
    replays.
    """
    import time as _time

    from predictionio_tpu.data.snapshot import record_replay_seconds

    remap, kept_users = _kept_user_remap(snapshot)
    users_enc = _prefilled(kept_users)
    items_enc = _prefilled(snapshot.vocab("items"))
    code_of = {nm: c for c, nm in enumerate(snapshot.vocab("names"))}

    def source_for(wanted: str) -> ChunkSource:
        code = code_of.get(wanted, -1)

        def source() -> Iterator[Chunk]:
            t0 = _time.perf_counter()
            for uu_raw, ii_raw, nn_raw, tt_raw, _rr in snapshot.chunks(
                chunk_rows
            ):
                sel = (ii_raw >= 0) & (nn_raw == code)
                if not sel.any():
                    continue
                yield (
                    remap[uu_raw[sel]],
                    ii_raw[sel],
                    np.full(int(sel.sum()), default_value, np.float32),
                    tt_raw[sel],
                )
            record_replay_seconds(_time.perf_counter() - t0)

        return source

    return {n: source_for(n) for n in event_names}, users_enc, items_enc


def snapshot_streamed_als_data(
    snapshot,
    config: ALSConfig,
    cache_dir: str | None = None,
    mesh=None,
    model_shards: int = 1,
    chunk_rows: int = 262_144,
    default_value: float = 1.0,
    event_values: dict[str, float] | None = None,
    block_rows: int | None = None,
    block_bytes: int | None = None,
) -> tuple[IncrementalEncoder, IncrementalEncoder, object]:
    """Streamed-epoch block store fed straight from a columnar snapshot.

    The PR-3 memmap columns are exactly the right on-disk feed for ALX
    device-resident epochs: the two build passes (counts, spill) replay
    the local memmaps instead of SQL, and the packed blocks land under
    the snapshot GENERATION directory by default (``data.snapshot.
    snapshot_block_dir``), so snapshot GC reaps a stale block cache with
    its generation and a refreshed generation re-packs. Returns
    ``(users_enc, items_enc, StreamedALSData)`` with the encoders
    pre-filled exactly like :func:`snapshot_coo_chunks` -- feed the data
    to ``parallel.als.als_fit_streamed``.
    """
    from predictionio_tpu.data.snapshot import snapshot_block_dir
    from predictionio_tpu.parallel.stream import (
        DEFAULT_BLOCK_BYTES,
        build_streamed_als_data,
    )

    source, users_enc, items_enc = snapshot_coo_chunks(
        snapshot, chunk_rows, default_value, event_values
    )
    data = build_streamed_als_data(
        source,
        len(users_enc.vocab),
        len(items_enc.vocab),
        config,
        cache_dir or snapshot_block_dir(snapshot),
        num_shards=int(mesh.shape["data"]) if mesh is not None else 1,
        model_shards=model_shards,
        block_rows=block_rows,
        block_bytes=block_bytes or DEFAULT_BLOCK_BYTES,
    )
    return users_enc, items_enc, data


def universe_pass(sources: dict[str, ChunkSource]) -> None:
    """Drive one full scan through the shared encoders so the entity
    universe (len(encoder.ids)) is known before any per-type build.

    Any single source suffices: every source encodes ALL types' rows
    through the shared encoders regardless of which type it emits.
    """
    for _ in next(iter(sources.values()))():
        pass


def _local_row_range(sharding, nrows: int) -> tuple[int, int]:
    """This process's contiguous [lo, hi) slice of a row-sharded dim."""
    spans = {
        (sl[0].start or 0, nrows if sl[0].stop is None else sl[0].stop)
        for sl in sharding.addressable_devices_indices_map((nrows,)).values()
    }  # a set: devices along replicated axes (model) share the same slice
    lo = min(s for s, _ in spans)
    hi = max(e for _, e in spans)
    if hi - lo != sum(e - s for s, e in spans):
        raise ValueError(
            "this process's shards of the data axis are not contiguous; "
            "the sharded reader requires a process-contiguous device order "
            "(build_mesh's default)"
        )
    return lo, hi


@dataclass
class _SideAccumulator:
    """Pass-2 retention state for one orientation."""

    plan: _BucketPlan
    ranges: list[tuple[int, int]]  # local [lo, hi) per bucket, global slots
    rows: list[list[np.ndarray]]
    cols: list[list[np.ndarray]]
    vals: list[list[np.ndarray]]
    times: list[list[np.ndarray]]
    retained: int = 0

    def take(self, row_slots, col_slots, vals, times) -> None:
        for b, (lo, hi) in enumerate(self.ranges):
            off = self.plan.offsets[b]
            sel = (row_slots >= off + lo) & (row_slots < off + hi)
            if not sel.any():
                continue
            self.rows[b].append(row_slots[sel] - off - lo)
            self.cols[b].append(col_slots[sel])
            self.vals[b].append(vals[sel])
            if times is not None:
                self.times[b].append(times[sel])
            self.retained += int(sel.sum())


def _grow_bincount(cnt: np.ndarray, ids: np.ndarray) -> np.ndarray:
    """Accumulate a bincount whose extent grows with the observed ids."""
    if ids.size == 0:
        return cnt
    add = np.bincount(ids, minlength=cnt.size)
    if add.size > cnt.size:
        cnt = np.pad(cnt, (0, add.size - cnt.size))
        return cnt + add
    cnt[: add.size] += add
    return cnt


def build_als_data_sharded(
    chunks: ChunkSource,
    num_users: int | None,
    num_items: int | None,
    config: ALSConfig,
    mesh,
    model_shards: int = 1,
) -> ALSData:
    """Two-pass, retention-bounded ALSData for (multi-process) ``mesh``.

    Equivalent layout to ``build_als_data`` (same bucket plans, same slot
    maps, same padded lengths) but each process keeps only the edges its
    data-axis shard needs, per side. Feed the result straight to
    ``als_fit``; the ``global_rows`` marker routes device placement
    through make_array_from_process_local_data.

    ``num_users``/``num_items`` may be None: the store-backed path cannot
    know the distinct-entity counts before the first scan (the encoders
    fill in during it), so pass 1 grows the count arrays with the stream
    and the entity universe becomes whatever the stream contained. When
    given, they are lower-bounded by the stream (ids beyond them grow the
    arrays rather than crashing the bincount).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    d = mesh.shape["data"]
    rm = 8 * d * max(model_shards, 1)
    nb = max(int(config.buckets), 1)
    row_sharding = NamedSharding(mesh, PartitionSpec("data"))

    # -- pass 1: per-entity counts (O(entities) memory) --------------------
    cnt_u = np.zeros(num_users or 0, dtype=np.int64)
    cnt_i = np.zeros(num_items or 0, dtype=np.int64)
    for uu, ii, _vv, _tt in chunks():
        cnt_u = _grow_bincount(cnt_u, uu)
        cnt_i = _grow_bincount(cnt_i, ii)
    plan_u = _plan_buckets(cnt_u, config.max_len, nb, rm)
    plan_i = _plan_buckets(cnt_i, config.max_len, nb, rm)

    def side_acc(plan: _BucketPlan) -> _SideAccumulator:
        ranges = [
            _local_row_range(row_sharding, rows) for rows in plan.padded_rows
        ]
        k = len(plan.sizes)
        return _SideAccumulator(
            plan=plan,
            ranges=ranges,
            rows=[[] for _ in range(k)],
            cols=[[] for _ in range(k)],
            vals=[[] for _ in range(k)],
            times=[[] for _ in range(k)],
        )

    acc_u = side_acc(plan_u)
    acc_i = side_acc(plan_i)

    # -- pass 2: retain this process's rows only ---------------------------
    for uu, ii, vv, tt in chunks():
        u_slots = plan_u.slot_of[uu]
        i_slots = plan_i.slot_of[ii]
        acc_u.take(u_slots, i_slots, vv, tt)
        acc_i.take(i_slots, u_slots, vv, tt)

    def pack_side(acc: _SideAccumulator, opp_plan: _BucketPlan) -> BucketedCSR:
        blocks = []
        for b, (lo, hi) in enumerate(acc.ranges):
            cat = lambda parts, dt: (
                np.concatenate(parts) if parts else np.empty(0, dt)
            )
            times_b = cat(acc.times[b], np.float64) if acc.times[b] else None
            blocks.append(
                pack_padded_csr(
                    cat(acc.rows[b], np.int64),
                    cat(acc.cols[b], np.int64),
                    cat(acc.vals[b], np.float32),
                    num_rows=hi - lo,
                    num_cols=opp_plan.total_slots,
                    max_len=config.max_len,
                    times=times_b,
                    row_multiple=8,
                    pad_len=acc.plan.lengths[b],
                )
            )
        return BucketedCSR(
            blocks=tuple(blocks),
            slot_of=acc.plan.slot_of,
            num_rows=int(acc.plan.slot_of.shape[0]),
            total_slots=acc.plan.total_slots,
            global_rows=tuple(acc.plan.padded_rows),
            retained_edges=acc.retained,
        )

    return ALSData(
        by_row=pack_side(acc_u, plan_i), by_col=pack_side(acc_i, plan_u)
    )


@dataclass
class ShardedPaddedCSR:
    """Process-local slice of a row-sharded PaddedCSR (+ global extent).

    The cooccurrence analogue of the bucketed ALS reader output: ``local``
    holds ONLY this process's user rows ``[row_lo, row_hi)`` of a global
    ``[global_rows, L]`` layout (plain user-id row order -- cooccurrence
    needs no length bucketing), and the ops layer assembles the device
    array via make_array_from_process_local_data. Duck-types the
    ``num_rows``/``num_cols`` surface the cooccurrence entry points check.
    """

    local: PaddedCSR
    global_rows: int
    row_lo: int
    row_hi: int
    num_rows: int   # real (global) user rows
    num_cols: int
    retained_edges: int
    #: GLOBAL edge count from the counts pass (identical on every
    #: process). Emptiness decisions MUST use this, never retained_edges:
    #: a per-process test diverges SPMD control flow around the
    #: collectives when one process's shard happens to hold no edges.
    global_edges: int = 0

    @property
    def max_len(self) -> int:
        return self.local.indices.shape[1]


def cooc_global_rows(num_users: int, mesh, chunk: int) -> int:
    """The global padded row count the sharded cooccurrence layout uses.

    Mirrors ``ops.cooccurrence._run_cooc``'s chunking: every device scans
    the same number of fixed-size ``chunk`` row blocks, so rows =
    data * ceil(per_device / chunk_eff) * chunk_eff. Builder and runner
    must agree, so this is THE shared definition.
    """
    data_size = int(mesh.shape["data"])
    phys = max(round_up(num_users, 8), 8)
    per_device = -(-phys // data_size)
    chunk_eff = max(1, min(chunk, per_device))
    return data_size * (-(-per_device // chunk_eff)) * chunk_eff


def build_cooc_csr_sharded(
    chunks: ChunkSource,
    num_users: int | None,
    num_items: int | None,
    mesh,
    max_len: int | None = None,
    chunk: int = 4096,
) -> ShardedPaddedCSR:
    """Retention-bounded user-rows CSR for the cooccurrence/UR pipeline.

    Two passes like ``build_als_data_sharded``: counts first (so every
    process derives the same padded length), then retain only the edges
    whose user row falls in this process's data-axis shard. ``chunk``
    must match the ``chunk`` later passed to the cooccurrence entry
    points (it shapes the global row padding; the runner validates).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    cnt_u = np.zeros(num_users or 0, dtype=np.int64)
    n_items = num_items or 0
    for uu, ii, _vv, _tt in chunks():
        cnt_u = _grow_bincount(cnt_u, uu)
        if ii.size:
            n_items = max(n_items, int(ii.max()) + 1)
    n_users = cnt_u.size
    if n_users == 0:
        raise ValueError(
            "no interactions in the stream and no entity counts given -- "
            "check appName/eventNames (an empty event store cannot build "
            "a cooccurrence model)"
        )
    capped = int(min(cnt_u.max(), max_len)) if max_len else int(cnt_u.max())
    pad_len = max(round_up(capped, 8), 8)

    rows = cooc_global_rows(n_users, mesh, chunk)
    row_sharding = NamedSharding(mesh, PartitionSpec("data"))
    lo, hi = _local_row_range(row_sharding, rows)

    keep_r: list[np.ndarray] = []
    keep_c: list[np.ndarray] = []
    keep_v: list[np.ndarray] = []
    keep_t: list[np.ndarray] = []
    retained = 0
    for uu, ii, vv, tt in chunks():
        sel = (uu >= lo) & (uu < hi)
        if not sel.any():
            continue
        keep_r.append(uu[sel] - lo)
        keep_c.append(ii[sel])
        keep_v.append(vv[sel])
        if tt is not None:
            keep_t.append(tt[sel])
        retained += int(sel.sum())

    cat = lambda parts, dt: np.concatenate(parts) if parts else np.empty(0, dt)
    local = pack_padded_csr(
        cat(keep_r, np.int64),
        cat(keep_c, np.int64),
        cat(keep_v, np.float32),
        num_rows=hi - lo,
        num_cols=n_items,
        max_len=max_len,
        times=cat(keep_t, np.float64) if keep_t else None,
        # the local block must match the shard span EXACTLY: rounding it
        # up would hand make_array_from_process_local_data a buffer
        # larger than this process's addressable rows (the cooc layout's
        # chunk-based spans are not 8-aligned, and the plain-XLA cooc
        # path has no leading-dim alignment requirement)
        row_multiple=1,
        pad_len=pad_len,
    )
    return ShardedPaddedCSR(
        local=local,
        global_rows=rows,
        row_lo=lo,
        row_hi=hi,
        num_rows=n_users,
        num_cols=n_items,
        retained_edges=retained,
        global_edges=int(cnt_u.sum()),
    )


def distinct_user_counts_sharded(s: ShardedPaddedCSR) -> np.ndarray:
    """Global per-item distinct-user counts from process-local rows.

    User rows partition across processes, so per-item distinct counts are
    additive: local counts + a cross-process sum reproduce
    ``ops.cooccurrence.distinct_user_counts`` on the global CSR exactly.
    """
    import jax

    from predictionio_tpu.ops.cooccurrence import distinct_user_counts

    local = distinct_user_counts(s.local)
    if jax.process_count() > 1:
        from predictionio_tpu.utils.jax_compat import process_allgather

        return np.asarray(
            process_allgather(local)
        ).reshape(jax.process_count(), -1).sum(axis=0).astype(np.float32)
    return local


def array_coo_chunks(
    users: np.ndarray,
    items: np.ndarray,
    values: np.ndarray,
    times: np.ndarray | None = None,
    chunk_rows: int = 262_144,
) -> ChunkSource:
    """ChunkSource over in-memory COO arrays (tests / already-loaded data)."""

    def source() -> Iterator[Chunk]:
        for lo in range(0, len(users), chunk_rows):
            hi = lo + chunk_rows
            yield (
                np.asarray(users[lo:hi], np.int64),
                np.asarray(items[lo:hi], np.int64),
                np.asarray(values[lo:hi], np.float32),
                None if times is None else np.asarray(times[lo:hi], np.float64),
            )

    return source
