"""Ring attention: sequence-parallel attention over the device mesh.

Long-context support the reference never had (SURVEY.md section 5.7: the
nearest analogue is ``PEvents`` streaming arbitrarily long per-entity event
histories). Sequence models over those histories (the ``models/sequence``
template) need attention over sequences longer than one chip's memory, so
the sequence dimension shards over a mesh axis and key/value blocks rotate
around the ring via ``jax.lax.ppermute`` -- one hop per step, riding ICI,
never materializing the full [T, T] score matrix on any chip.

Numerics are the flash-attention online softmax, carried ACROSS ring steps:
each rank keeps running (max, sum, out) statistics for its local queries and
folds in one remote K/V block per step. ``lax.scan`` keeps the loop static
for XLA and reverse-mode differentiable (training path).

``plain_attention`` is the single-device reference implementation; the test
suite checks ring == plain on a virtual 8-device CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from predictionio_tpu.parallel.mesh import seq_parallel_shard_map
from predictionio_tpu.utils.jax_compat import pcast_varying

_NEG = -1e30  # finite "masked" score: keeps exp() NaN-free on all-masked rows


def plain_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    mask: jnp.ndarray | None = None,
    sm_scale: float | None = None,
) -> jnp.ndarray:
    """Reference attention. Shapes: q,k,v [B, T, H, D] -> [B, T, H, D].

    ``mask``: optional [B, Tk] key validity (padding) mask.
    """
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        cm = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(cm[None, None], s, _NEG)
    if mask is not None:
        s = jnp.where(mask[:, None, None, :], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _ring_attention_local(
    q, k, v, kv_mask, *, axis_name: str, axis_size: int, causal: bool, sm_scale,
    mesh_axes: tuple[str, ...] = (),
):
    """Per-shard body: local queries stay put, K/V blocks rotate the ring.

    Shapes (per shard): q,k,v [B, Tl, H, D]; kv_mask [B, Tl].
    """
    b, t_local, h, d = q.shape
    scale = sm_scale if sm_scale is not None else d**-0.5
    my_rank = jax.lax.axis_index(axis_name)
    q_pos = my_rank * t_local + jnp.arange(t_local)  # global query positions

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def accumulate(acc, blocks, i):
        """Fold one K/V block (originally from rank ``my_rank - i``) into the
        running flash-attention statistics."""
        o, m, l = acc
        k_blk, v_blk, msk_blk = blocks
        src = (my_rank - i) % axis_size
        k_pos = src * t_local + jnp.arange(t_local)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
        valid = msk_blk[:, None, None, :]  # [B,1,1,Tk]
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])[None, None]
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None]) * valid  # zero fully-masked entries
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
        return o, m_new, l

    # fresh constants are "unvarying" under shard_map's vma tracking; the
    # scan carry must match the varying outputs, so cast them explicitly
    pvary = lambda x: pcast_varying(x, mesh_axes) if mesh_axes else x
    o0 = pvary(jnp.zeros((b, h, t_local, d), q.dtype))
    m0 = pvary(jnp.full((b, h, t_local), _NEG, q.dtype))
    l0 = pvary(jnp.zeros((b, h, t_local), q.dtype))

    # step 0 folds the resident block; steps 1..S-1 rotate FIRST, then fold --
    # no ring hop is spent producing a block nobody reads
    acc = accumulate((o0, m0, l0), (k, v, kv_mask), 0)

    def step(carry, i):
        acc, blocks = carry
        blocks = tuple(jax.lax.ppermute(x, axis_name, perm) for x in blocks)
        return (accumulate(acc, blocks, i), blocks), None

    if axis_size > 1:
        (acc, _), _ = jax.lax.scan(
            step, (acc, (k, v, kv_mask)), jnp.arange(1, axis_size)
        )
    o, _, l = acc
    o = o / jnp.maximum(l, 1e-20)[..., None]
    return o.transpose(0, 2, 1, 3)  # [B, Tl, H, D]


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh,
    axis_name: str = "seq",
    causal: bool = True,
    mask: jnp.ndarray | None = None,
    sm_scale: float | None = None,
) -> jnp.ndarray:
    """Attention with the sequence dim sharded over ``mesh[axis_name]``.

    Global shapes: q,k,v [B, T, H, D] with T divisible by the axis size;
    ``mask`` [B, T] marks valid (non-padding) key positions. Batch shards
    over the mesh's ``data`` axis when present (dp x sp composes).
    """
    if mask is None:
        mask = jnp.ones(q.shape[:2], bool)
    axis_size = mesh.shape[axis_name]
    fn = seq_parallel_shard_map(
        functools.partial(
            _ring_attention_local,
            axis_name=axis_name,
            axis_size=axis_size,
            causal=causal,
            sm_scale=sm_scale,
            mesh_axes=tuple(mesh.axis_names),
        ),
        mesh,
        axis_name,
    )
    return fn(q, k, v, mask)
