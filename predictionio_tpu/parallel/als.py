"""Alternating Least Squares on the device mesh.

The TPU-native replacement for MLlib ALS (reference call site: the
recommendation template's ``ALSAlgorithm.train`` -> ``org.apache.spark.mllib
.recommendation.ALS``, SURVEY.md section 2.6/3.1 -- Spark dep, not repo
code). Design anchor: ALX (arxiv 2112.02194, PAPERS.md), "ALS on TPUs":

- interactions live as padded CSR blocks (``ops.ragged``): static shapes,
  gathers instead of ragged loops;
- rows are LENGTH-BUCKETED: each side's entities are relabeled into
  length-sorted slots and split into a few buckets, each bucket its own
  padded block with its own (much tighter) padded length. At ML-20M's
  history distribution one global pad length wastes ~25-35% of gather
  slots on padding; bucketing recovers most of that as iteration time.
  The opposite side's column ids are slot-mapped at pack time, so the
  device math never sees the permutation -- ``slot_of`` maps factors
  back to original entity order at the host boundary only;
- each half-step solves all rows' K x K normal equations as one batched
  Cholesky per bucket on the MXU: Gram via ``einsum`` over the padded
  gather, masked;
- sharding: every bucket's rows shard over the ``data`` mesh axis; the
  opposite-side factor matrix is replicated (XLA all-gathers it once per
  half-step -- the collective that replaces MLlib's factor-block shuffle);
- implicit-feedback mode (MLlib ``trainImplicit`` parity) uses the YtY trick:
  the global Gram is one replicated K x K matmul + per-row corrections over
  observed entries only.

Explicit objective:  sum_obs (r - u.v)^2 + lam * (|U|^2 + |V|^2)
Implicit objective (Hu-Koren-Volinsky): confidence c = 1 + alpha*r on
observed pairs, preference p = 1; unobserved pairs have c = 1, p = 0.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from predictionio_tpu.ops.als_gram import gram_rhs
from predictionio_tpu.ops.linalg import batched_spd_solve
from predictionio_tpu.ops.ragged import PaddedCSR, pack_padded_csr, round_up
from predictionio_tpu.parallel.mesh import cached_by_mesh
from predictionio_tpu.utils.jax_compat import axis_size, shard_map


@dataclass
class ALSConfig:
    rank: int = 16
    iterations: int = 10
    reg: float = 0.1           # lambda (MLlib: lambda_)
    alpha: float = 40.0        # implicit confidence scale
    implicit: bool = False
    seed: int = 0
    max_len: int | None = None  # per-row history cap (SURVEY 5.7)
    dtype: str = "float32"     # factor dtype; Grams always accumulate f32
    buckets: int = 1           # length buckets per side (1 = single block)
    #: "replicated": the opposite-side factor matrix is all-gathered whole
    #: per half-step (fine while a catalog fits one device's HBM).
    #: "model": ALX block model-parallelism -- factors shard over the
    #: ``model`` mesh axis, each device gathers only its local hits, and a
    #: psum_scatter over ``model`` completes the sum; per-device factor
    #: memory drops to total_slots/model_axis rows (see docs/parallelism.md
    #: for the max-catalog math). Requires build_als_data(model_shards=m).
    factor_sharding: str = "replicated"
    #: half-step tail implementation, chosen per TARGET platform like the
    #: unrolled-vs-LAPACK ``batched_spd_solve`` split: "pallas" runs the
    #: fused gather->Gram kernel (``ops.als_gram``) that never writes the
    #: [rows, L, K] gathered intermediate to HBM; "xla" is the einsum path.
    #: "auto" = pallas on accelerators, xla on CPU meshes (where the fused
    #: kernel runs in interpret mode -- a correctness vehicle, not a fast
    #: path). Tiny ranks on CPU stay fastest on "xla".
    solver: str = "auto"


@dataclass
class BucketedCSR:
    """One side's interactions as length-bucketed padded CSR blocks.

    Block ``b`` covers factor-matrix slots ``[offset_b, offset_b +
    padded_rows_b)``; real rows are deterministically SCATTERED across the
    block's padded range (multi-host load balance -- see _plan_buckets),
    padding rows carry zero mask wherever they fall. ``slot_of[original_
    id]`` is the factor row the entity occupies; built with ``buckets=1``
    the slot map is the identity and the single block equals the
    pre-bucketing layout.
    ``indices`` entries are the OPPOSITE side's slots; padding slots carry
    the sentinel ``opposite.total_slots`` (callers append one zero row to
    the gathered factor matrix so padding gathers stay in-bounds).
    """

    blocks: tuple[PaddedCSR, ...]
    slot_of: np.ndarray  # int64 [num_rows]: original row id -> factor slot
    num_rows: int        # real (original) row count
    total_slots: int     # sum of the blocks' padded row counts
    #: set by the SHARDED reader (parallel.reader): blocks then hold only
    #: this process's data-axis rows and these are the GLOBAL per-bucket
    #: padded row counts used to assemble the device arrays via
    #: make_array_from_process_local_data. None = blocks are global.
    global_rows: tuple[int, ...] | None = None
    #: edges this process retained after the partitioned scan (the
    #: memory-scaling evidence the sharded-reader tests assert on)
    retained_edges: int = 0

    @property
    def truncated(self) -> int:
        return sum(b.truncated for b in self.blocks)

    @property
    def padded_slots(self) -> int:
        """Total gather slots (the quantity bucketing minimizes)."""
        return sum(int(np.prod(b.indices.shape)) for b in self.blocks)

    def _single(self) -> PaddedCSR:
        if len(self.blocks) != 1:
            raise ValueError(
                "flat accessors are only defined for single-bucket data; "
                f"this side has {len(self.blocks)} buckets"
            )
        return self.blocks[0]

    # single-bucket compatibility accessors (tests / direct kernel drivers)
    @property
    def indices(self) -> np.ndarray:
        return self._single().indices

    @property
    def values(self) -> np.ndarray:
        return self._single().values

    @property
    def mask(self) -> np.ndarray:
        return self._single().mask


@dataclass
class ALSData:
    """Both orientations of the interaction matrix, padded for the mesh."""

    by_row: BucketedCSR  # users x items
    by_col: BucketedCSR  # items x users


@dataclass
class _BucketPlan:
    order: np.ndarray      # original ids in slot order (real rows only)
    sizes: list[int]       # real rows per bucket
    offsets: list[int]     # first slot of each bucket
    slot_of: np.ndarray    # [num_rows]
    total_slots: int
    lengths: list[int]     # padded L per bucket (every process must agree)

    @property
    def padded_rows(self) -> list[int]:
        ends = self.offsets[1:] + [self.total_slots]
        return [e - o for o, e in zip(self.offsets, ends)]


def _plan_buckets(
    counts: np.ndarray,
    cap: int | None,
    n_buckets: int,
    row_multiple: int,
    len_multiple: int = 8,
) -> _BucketPlan:
    """Partition rows into <=``n_buckets`` length buckets minimizing the
    total padded slot count sum_b padded_rows_b * padded_len_b.

    Rows are sorted by (capped) length descending; candidate cut points
    are the positions where the 8-rounded length drops (<= cap/8 + 1 of
    them, so the exact DP over candidates is tiny). Using FEWER buckets
    than allowed is considered too: each bucket pays a row-roundup tax.
    """
    n = counts.size

    def padded_len(raw: int) -> int:
        capped_max = min(raw, cap) if cap else raw
        return max(round_up(capped_max, len_multiple), len_multiple)

    if n_buckets <= 1 or n <= 1:
        total = max(round_up(max(n, 1), row_multiple), row_multiple)
        return _BucketPlan(
            order=np.arange(n, dtype=np.int64),
            sizes=[n],
            offsets=[0],
            slot_of=np.arange(n, dtype=np.int64),
            total_slots=total,
            lengths=[padded_len(int(counts.max()) if n else 0)],
        )

    capped = np.minimum(counts, cap) if cap else counts
    order = np.argsort(-capped, kind="stable").astype(np.int64)
    rounded = np.maximum(
        ((capped[order] + len_multiple - 1) // len_multiple) * len_multiple,
        len_multiple,
    )
    cuts = list(np.nonzero(np.diff(rounded) != 0)[0] + 1)
    cand = [0] + cuts + [n]
    if len(cand) > 66:  # cap DP size for absurd max_len; keep ends exact
        step = (len(cand) - 2) // 64 + 1
        cand = [0] + cand[1:-1][::step] + [n]

    def seg_cost(i: int, j: int) -> int:
        rows = cand[j] - cand[i]
        return round_up(rows, row_multiple) * int(rounded[cand[i]])

    m = len(cand) - 1
    inf = float("inf")
    dp = [[inf] * (m + 1) for _ in range(n_buckets + 1)]
    back: list[list[int]] = [[0] * (m + 1) for _ in range(n_buckets + 1)]
    dp[0][0] = 0.0
    for b in range(1, n_buckets + 1):
        for j in range(1, m + 1):
            for i in range(j):
                if dp[b - 1][i] == inf:
                    continue
                cost = dp[b - 1][i] + seg_cost(i, j)
                if cost < dp[b][j]:
                    dp[b][j] = cost
                    back[b][j] = i
    b_best = min(range(1, n_buckets + 1), key=lambda b: dp[b][m])
    bounds = [m]
    b, j = b_best, m
    while b > 0:
        j = back[b][j]
        bounds.append(j)
        b -= 1
    bounds.reverse()  # candidate indices 0 = start .. m = end

    sizes, offsets, lengths = [], [], []
    slot_of = np.empty(n, dtype=np.int64)
    off = 0
    for b, (lo, hi) in enumerate(zip(bounds[:-1], bounds[1:])):
        size = cand[hi] - cand[lo]
        sizes.append(size)
        offsets.append(off)
        lengths.append(int(rounded[cand[lo]]))
        # deterministic scatter over the bucket's WHOLE padded range: in
        # length-sorted front-packed order, every bucket's heaviest rows
        # (and all its real rows, when padding is substantial) would land
        # in the FIRST contiguous data shards -- process 0 of a multi-host
        # mesh would retain most of the edge set. Scattering costs nothing
        # (the padded length is the bucket's, order-independent), keeps
        # the slot map a plan-level fact every process derives identically
        # from the same counts, and balances both edge retention and
        # per-shard solve work.
        padded_b = max(round_up(size, row_multiple), row_multiple)
        perm = np.random.default_rng(0x5EED + b).permutation(padded_b)[:size]
        slot_of[order[cand[lo] : cand[hi]]] = off + perm
        off += padded_b
    return _BucketPlan(
        order=order, sizes=sizes, offsets=offsets, slot_of=slot_of,
        total_slots=off, lengths=lengths,
    )


def _pack_side(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    times: np.ndarray | None,
    plan: _BucketPlan,
    opp_total_slots: int,
    opp_slot_of: np.ndarray,
    cap: int | None,
    row_multiple: int,
) -> BucketedCSR:
    """Pack one orientation into its bucket blocks (slot-mapped columns)."""
    row_slots = plan.slot_of[rows]
    cols_slotted = opp_slot_of[cols]
    blocks = []
    for off, padded, length in zip(
        plan.offsets, plan.padded_rows, plan.lengths
    ):
        sel = (row_slots >= off) & (row_slots < off + padded)
        blocks.append(
            pack_padded_csr(
                row_slots[sel] - off,
                cols_slotted[sel],
                vals[sel],
                num_rows=padded,
                num_cols=opp_total_slots,
                max_len=cap,
                times=None if times is None else times[sel],
                row_multiple=row_multiple,
                pad_len=length,
            )
        )
    return BucketedCSR(
        blocks=tuple(blocks),
        slot_of=plan.slot_of,
        num_rows=int(plan.slot_of.shape[0]),
        total_slots=plan.total_slots,
    )


def build_als_data(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    num_users: int,
    num_items: int,
    config: ALSConfig,
    times: np.ndarray | None = None,
    num_shards: int = 1,
    model_shards: int = 1,
) -> ALSData:
    """Pack COO interactions into both (bucketed) CSR orientations.

    Every bucket's row count is padded to a multiple of
    8 * num_shards * model_shards so each data shard is equal AND
    lane-aligned, and (``factor_sharding="model"``) each data shard splits
    evenly again over the model axis; with ``config.buckets == 1`` and the
    default shard counts the layout (and therefore the math and the
    seed-for-seed results) is exactly the historical single-block one.
    """
    users = np.asarray(users, dtype=np.int64)
    items = np.asarray(items, dtype=np.int64)
    ratings = np.asarray(ratings, dtype=np.float32)
    # ids beyond the declared catalog are an encoder/count mismatch; fail
    # HERE (np.bincount would silently grow the entity universe and hand
    # back a wrong-shaped factor model far from the cause)
    for ids, declared, what in ((users, num_users, "user"),
                                (items, num_items, "item")):
        if ids.size and int(ids.max()) >= declared:
            raise ValueError(
                f"{what} id {int(ids.max())} out of range for "
                f"num_{what}s={declared}"
            )
    rm = 8 * max(num_shards, 1) * max(model_shards, 1)
    nb = max(int(config.buckets), 1)
    plan_u = _plan_buckets(
        np.bincount(users, minlength=num_users), config.max_len, nb, rm
    )
    plan_i = _plan_buckets(
        np.bincount(items, minlength=num_items), config.max_len, nb, rm
    )
    by_row = _pack_side(
        users, items, ratings, times, plan_u,
        plan_i.total_slots, plan_i.slot_of, config.max_len, rm,
    )
    by_col = _pack_side(
        items, users, ratings, times, plan_i,
        plan_u.total_slots, plan_u.slot_of, config.max_len, rm,
    )
    return ALSData(by_row=by_row, by_col=by_col)


def _factor_precision(dtype):
    """Matmul precision for einsums whose operands are both factor-typed.

    f32 operands need "highest" (stops XLA lowering them to bf16 passes on
    TPU); bf16 operands are already exact in a single MXU pass with f32
    accumulation, and "highest" would force 3-pass emulation for nothing.
    """
    return "highest" if dtype == jnp.float32 else None


def _finish_explicit(gram, rhs, n_obs, reg, rank, unroll, out_dtype):
    """ALS-WR ridge + batched solve over precomputed Gram/rhs -- the tail
    both the XLA einsum path and the fused Pallas kernel share bit-for-bit
    (so solver parity reduces to Gram/rhs parity)."""
    # MLlib-style weighted regularization: lambda * n_obs (ALS-WR); constant
    # lambda would also be defensible -- n_obs matches the reference template
    ridge = reg * jnp.maximum(n_obs, 1.0)
    gram = gram + ridge[:, None, None] * jnp.eye(rank, dtype=gram.dtype)
    return batched_spd_solve(gram, rhs, unroll=unroll).astype(out_dtype)


def _finish_implicit(gram_fix, rhs, yty, reg, rank, unroll, out_dtype):
    """YtY + correction + constant ridge + solve (shared tail, see above).

    ``gram_fix`` holds only the per-row observed-entry corrections
    sum_obs (c-1) y y^T; the replicated global Gram lands here."""
    gram = yty[None] + gram_fix + reg * jnp.eye(rank, dtype=yty.dtype)
    return batched_spd_solve(gram, rhs, unroll=unroll).astype(out_dtype)


def _gram_solve_explicit(gathered, values, n_obs, reg, rank, unroll, out_dtype):
    """Gram + ALS-WR ridge + rhs + batched solve over pre-gathered factors.

    PADDING INVARIANT (what lets the mask array stay on the host): padding
    slots' ``gathered`` rows are zero (their ``indices`` point at a zero
    factor row -- the appended trailing row in replicated mode, any
    out-of-shard index in model-sharded mode) and pack_padded_csr writes
    zero ``values`` into padding slots. Every padding contribution to the
    Gram and rhs therefore dies through the gathered zeros -- no ``[R, L]``
    mask stream over HBM, no ``[R, L, K]`` mask multiply over the largest
    intermediate. Only the per-row observation count ``n_obs`` (for ALS-WR
    regularization) survives to the device, as an ``[R]`` vector.

    Mixed precision, ALX-style: ``gathered`` may be bf16 (half the HBM
    traffic for the gather and half the ICI traffic for the collective;
    bf16 inputs are the MXU's native mode), while the Gram/rhs accumulate
    in f32 and the normal-equation solve runs in f32; the solution is cast
    back to ``out_dtype`` on return. ``reg`` may be a traced scalar (the
    iteration program is shared across regularization values).
    """
    gram = jnp.einsum(
        "rlk,rlj->rkj", gathered, gathered,
        precision=_factor_precision(gathered.dtype),
        preferred_element_type=jnp.float32,
    )
    rhs = jnp.einsum(
        "rlk,rl->rk", gathered, values,
        precision="highest", preferred_element_type=jnp.float32,
    )
    return _finish_explicit(gram, rhs, n_obs, reg, rank, unroll, out_dtype)


def _gram_solve_implicit(gathered, values, yty, reg, alpha, rank, unroll, out_dtype):
    """Hu-Koren-Volinsky implicit tail with the YtY trick.

    G = YtY + sum_obs (c-1) y y^T + lam*I ; rhs = sum_obs c * y
    Same mixed-precision contract and padding invariant as the explicit
    tail: padding slots carry zero gathered rows and zero values, so every
    padding term dies without a mask (``(1 + c-1) * y`` at a padding slot
    multiplies the gathered zero row). Implicit mode uses constant lambda
    (MLlib trainImplicit parity), so no n_obs.
    """
    conf_minus_1 = alpha * values
    gram_fix = jnp.einsum(
        "rlk,rl,rlj->rkj", gathered, conf_minus_1, gathered,
        precision="highest", preferred_element_type=jnp.float32,
    )
    rhs = jnp.einsum(
        "rlk,rl->rk", gathered, (1.0 + conf_minus_1),
        precision="highest", preferred_element_type=jnp.float32,
    )
    return _finish_implicit(gram_fix, rhs, yty, reg, rank, unroll, out_dtype)


def _factors_yty(factors):
    """f32 K x K Gram of a factor matrix (implicit mode's global term)."""
    return jnp.einsum(
        "nk,nj->kj", factors, factors,
        precision=_factor_precision(factors.dtype),
        preferred_element_type=jnp.float32,
    )


def _half_step_explicit(indices, values, n_obs, factors, reg, rank, unroll):
    """Replicated-factor explicit half-step (gather + shared tail)."""
    gathered = factors[indices]                       # [R, L, K]
    return _gram_solve_explicit(
        gathered, values, n_obs, reg, rank, unroll, factors.dtype
    )


def _half_step_implicit(indices, values, n_obs, factors, yty, reg, alpha,
                        rank, unroll):
    """Replicated-factor implicit half-step.

    ``n_obs`` is unused (constant lambda) but kept so both modes share one
    block layout. ``yty`` is the side's global factor Gram, computed ONCE
    per half-step by the caller (it is bucket-invariant; computing it here
    would redo the [S, K] reduction for every bucket).
    """
    del n_obs
    gathered = factors[indices]
    return _gram_solve_implicit(
        gathered, values, yty, reg, alpha, rank, unroll, factors.dtype
    )


def _half_step_pallas(idx, values, n_obs, factors, yty, reg, alpha,
                      implicit, rank, unroll, interpret):
    """Replicated-factor half-step through the fused gather->Gram kernel.

    Runs inside shard_map over the mesh (a pallas_call is opaque to GSPMD,
    so the data-axis row split is explicit here): each device streams its
    CSR row shard through ``ops.als_gram.gram_rhs`` against the replicated
    factor table and solves its rows locally -- no collectives; the
    [rows, L, K] gathered intermediate never exists in HBM.
    """
    gram, rhs = gram_rhs(
        idx, values, factors, alpha, implicit=implicit, interpret=interpret
    )
    if implicit:
        return _finish_implicit(
            gram, rhs, yty, reg, rank, unroll, factors.dtype
        )
    return _finish_explicit(gram, rhs, n_obs, reg, rank, unroll, factors.dtype)


def _sharded_block_body(idx, values, n_obs, opp_local, yty, reg, alpha,
                        implicit, rank, unroll, solver="xla",
                        interpret=False):
    """Per-device half-step for one bucket with MODEL-SHARDED factors.

    Runs inside shard_map over the full ("data", "model") mesh. Each
    device holds opp_local = its model-axis shard of the opposite factor
    matrix ([S/m, K], replicated across the data axis) and the full local
    data-shard of the bucket's CSR rows. ``yty`` (implicit mode) arrives
    replicated from the caller -- it is bucket-invariant and was formerly
    re-psum'd here per bucket. The ALX block exchange:

    1. gather local hits only (out-of-shard indices -- including the
       padding sentinel, which is out of EVERY shard -- contribute zeros);
    2. psum_scatter over "model" completes the sum while handing each
       device only its 1/m slice of the rows (half the traffic of a psum,
       and the [rows, L, K] gathered intermediate shrinks by m);
    3. each device solves its rows' normal equations -- compute scales
       with the full d*m device count, not just d.

    solver="pallas" replaces steps 1-2's [rows, L, K] exchange with the
    fused kernel: out-of-shard indices remap to a LOCAL trailing zero row
    (the same padding invariant, applied to the shard), each device
    accumulates its partial [rows, K, K]/[rows, K] Gram/rhs on-chip, and
    the psum_scatter runs over those -- (K^2 + K)/(L * K) of the XLA
    path's ICI traffic (~15x less at L=256, K=16) and no HBM gathered
    intermediate.

    Output rows per device: the model-axis slice of the local data shard,
    i.e. global layout P(("data", "model")).
    """
    m = axis_size("model")
    mi = jax.lax.axis_index("model")
    s_m = opp_local.shape[0]
    loc = idx - mi * s_m
    rows = idx.shape[0] // m
    if solver == "pallas":
        hit = (loc >= 0) & (loc < s_m)
        safe = jnp.where(hit, loc, s_m).astype(jnp.int32)
        gram, rhs = gram_rhs(
            safe, values, _append_zero_row(opp_local), alpha,
            implicit=implicit, interpret=interpret,
        )
        gram = jax.lax.psum_scatter(
            gram, "model", scatter_dimension=0, tiled=True
        )
        rhs = jax.lax.psum_scatter(
            rhs, "model", scatter_dimension=0, tiled=True
        )
        if implicit:
            return _finish_implicit(
                gram, rhs, yty, reg, rank, unroll, opp_local.dtype
            )
        n_s = jax.lax.dynamic_slice_in_dim(n_obs, mi * rows, rows, 0)
        return _finish_explicit(
            gram, rhs, n_s, reg, rank, unroll, opp_local.dtype
        )
    hit = (loc >= 0) & (loc < s_m)
    g = opp_local[jnp.clip(loc, 0, s_m - 1)]
    g = g * hit[..., None].astype(g.dtype)
    g = jax.lax.psum_scatter(g, "model", scatter_dimension=0, tiled=True)
    val_s = jax.lax.dynamic_slice_in_dim(values, mi * rows, rows, 0)
    if implicit:
        return _gram_solve_implicit(
            g, val_s, yty, reg, alpha, rank, unroll, opp_local.dtype
        )
    n_s = jax.lax.dynamic_slice_in_dim(n_obs, mi * rows, rows, 0)
    return _gram_solve_explicit(
        g, val_s, n_s, reg, rank, unroll, opp_local.dtype
    )


def _append_zero_row(factors: jnp.ndarray) -> jnp.ndarray:
    return jnp.concatenate(
        [factors, jnp.zeros((1, factors.shape[1]), factors.dtype)], axis=0
    )


def resolve_solver(solver: str, platform: str) -> str:
    """Resolve ``ALSConfig.solver`` against a target platform -- ONE
    definition of the "auto" rule (make_iteration and bench.py must agree
    on which path a run measured): pallas on accelerators, xla on CPU
    meshes, where the fused kernel would only run interpreted."""
    if solver not in ("auto", "xla", "pallas"):
        raise ValueError(
            "ALSConfig.solver must be 'auto', 'xla' or 'pallas', "
            f"got {solver!r}"
        )
    if solver == "auto":
        return "xla" if platform == "cpu" else "pallas"
    return solver


def make_iteration(mesh, config: ALSConfig):
    """The jitted full ALS iteration for (mesh, config) -- see _build_iteration.

    The returned callable takes the per-bucket CSR triples for both sides,
    the factor buffers, then the ``reg`` and ``alpha`` scalars (runtime
    values; the compiled program is shared across them). Bucket structure
    is part of jit's input signature, not the cache key: the same callable
    serves any bucket count (each distinct structure traces once).
    """
    if config.factor_sharding not in ("replicated", "model"):
        raise ValueError(
            "ALSConfig.factor_sharding must be 'replicated' or 'model', "
            f"got {config.factor_sharding!r}"
        )
    # per TARGET platform, like the unrolled-vs-LAPACK solve split: the
    # fused kernel is built for the MXU+DMA engines; on CPU it would run
    # interpreted (a correctness vehicle), so auto keeps the CPU default
    # on the einsum path
    solver = resolve_solver(config.solver, mesh.devices.flat[0].platform)
    return _build_iteration(
        mesh, config.rank, config.implicit, config.factor_sharding, solver
    )


@cached_by_mesh(maxsize=32)
def _build_iteration(mesh, rank: int, implicit: bool,
                     factor_axis: str = "replicated", solver: str = "xla"):
    """Build the jitted full ALS iteration (both half-steps fused).

    CSR rows (every bucket) shard over the 'data' mesh axis. Factor
    placement follows ``factor_axis``:

    - "replicated": factors live row-sharded over 'data' and are
      re-materialized replicated (+ zero pad row) INSIDE the jit, so the
      all-gather that replaces MLlib's factor-block shuffle is an
      on-device XLA collective, not a host round-trip.
    - "model": ALX block model-parallelism. Factors live row-sharded over
      the 'model' axis; each half-step runs as a shard_map over the full
      mesh doing local-hit gathers + a psum_scatter over 'model' (see
      _sharded_block_body). No device ever materializes a whole factor
      matrix: per-device factor memory is total_slots/m rows, which is
      what lifts the catalog-size ceiling from one device's HBM to the
      model axis's aggregate (docs/parallelism.md has the sizing math).

    ``solver`` (already resolved, "xla" or "pallas") picks the half-step
    tail: the einsum path GSPMD partitions on its own; the fused Pallas
    kernel (``ops.als_gram``) is opaque to GSPMD, so both factor layouts
    route it through an explicit shard_map (interpret mode on CPU meshes,
    the ``ops/flash_attention`` precedent -- tier-1 CPU tests run the same
    kernel code). Implicit mode's ``yty`` is computed ONCE per half-step
    here (bucket-invariant) and fed to every bucket's solve.

    Factor buffers are donated: each iteration updates in place instead
    of reallocating.

    ``reg``/``alpha`` are RUNTIME scalars, not baked constants: a
    ``pio eval`` grid over lambda/alpha reuses one compiled program per
    (mesh, rank, mode) instead of paying a full XLA compile per candidate
    (minutes each on a remote-compile TPU backend). The remaining cache key
    covers repeated ``als_fit`` calls in one process (serving retrains,
    benchmarks).
    """
    P = PartitionSpec
    row = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())

    # solve-path choice is per TARGET platform, not default backend: the
    # benchmark compiles a CPU mesh while a TPU backend is live (and vice
    # versa), and the unrolled solver is ~5x faster on TPU / ~8x slower on
    # CPU than LAPACK's batched Cholesky (ops.linalg.batched_spd_solve).
    # Any non-cpu platform counts as TPU-like: the axon tunnel backend
    # reports platform "axon" for real TPU chips.
    unroll = mesh.devices.flat[0].platform != "cpu"
    interpret = mesh.devices.flat[0].platform == "cpu"

    def side_yty(opp_real):
        """Global factor Gram of one side (implicit mode), hoisted out of
        the per-bucket loop; explicit mode feeds a dummy the steps drop."""
        if implicit:
            return _factors_yty(opp_real)
        return jnp.zeros((rank, rank), jnp.float32)

    if factor_axis == "model":
        fsh = NamedSharding(mesh, P("model"))
        body = functools.partial(
            _sharded_block_body, implicit=implicit, rank=rank,
            unroll=unroll, solver=solver, interpret=interpret,
        )
        smapped = shard_map(
            body,
            mesh=mesh,
            in_specs=(P("data", None), P("data", None), P("data"),
                      P("model", None), P(), P(), P()),
            out_specs=P(("data", "model"), None),
            # the pallas body has no replication/vma rule; the xla body
            # keeps the checker on
            check_vma=solver != "pallas",
        )

        def iteration(u_blocks, i_blocks, users, items, reg, alpha):
            def solve_side(blocks, opp):
                # inter-bucket padding rows are zero and the sentinel is
                # out of every shard, so the full sharded [S, K] Gram is
                # the implicit global term (GSPMD psums it once per side)
                yty = side_yty(opp)
                outs = [
                    smapped(idx, val, n_obs, opp, yty, reg, alpha)
                    for idx, val, n_obs in blocks
                ]
                if len(outs) == 1:
                    # reshard P(("data","model")) -> P("model"): the
                    # all-gather over 'data' that readies this side for
                    # the next gather
                    return jax.lax.with_sharding_constraint(outs[0], fsh)
                # multi-bucket assembly resharded PIECEWISE via
                # dynamic_update_slice: jnp.concatenate of differently
                # tuple-sharded bucket outputs followed by a reshard
                # miscompiles under the legacy (0.4.x) GSPMD partitioner
                # (values land in the wrong rows); updating each bucket's
                # rows into a P("model") buffer keeps every reshard a
                # single-array one, which partitions correctly on both
                # APIs and lowers to the same all-gather traffic
                total = sum(o.shape[0] for o in outs)
                buf = jax.lax.with_sharding_constraint(
                    jnp.zeros((total, outs[0].shape[1]), outs[0].dtype),
                    fsh,
                )
                off = 0
                for o in outs:
                    piece = jax.lax.with_sharding_constraint(o, fsh)
                    buf = jax.lax.dynamic_update_slice(buf, piece, (off, 0))
                    off += o.shape[0]
                return jax.lax.with_sharding_constraint(buf, fsh)

            users = solve_side(u_blocks, items)
            items = solve_side(i_blocks, users)
            return users, items

        return jax.jit(
            iteration,
            in_shardings=(row, row, fsh, fsh, rep, rep),
            out_shardings=(fsh, fsh),
            donate_argnums=(2, 3),
        )

    if solver == "pallas":
        pallas_step = functools.partial(
            _half_step_pallas, implicit=implicit, rank=rank, unroll=unroll,
            interpret=interpret,
        )
        smapped = shard_map(
            pallas_step,
            mesh=mesh,
            in_specs=(P("data", None), P("data", None), P("data"),
                      P(), P(), P(), P()),
            out_specs=P("data", None),
            check_vma=False,
        )

    def iteration(u_blocks, i_blocks, users, items, reg, alpha):
        if solver == "pallas":
            step = smapped
        elif implicit:
            step = functools.partial(
                _half_step_implicit, reg=reg, alpha=alpha, rank=rank,
                unroll=unroll,
            )
        else:
            step = functools.partial(
                _half_step_explicit, reg=reg, rank=rank, unroll=unroll
            )

        def solve_side(blocks, opp_full):
            if solver == "pallas":
                yty = side_yty(opp_full[:-1])
                outs = [
                    step(idx, val, n_obs, opp_full, yty, reg, alpha)
                    for idx, val, n_obs in blocks
                ]
            elif implicit:
                yty = side_yty(opp_full[:-1])
                outs = [
                    step(idx, val, n_obs, opp_full, yty)
                    for idx, val, n_obs in blocks
                ]
            else:
                outs = [
                    step(idx, val, n_obs, opp_full)
                    for idx, val, n_obs in blocks
                ]
            out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
            return jax.lax.with_sharding_constraint(out, row)

        items_full = jax.lax.with_sharding_constraint(_append_zero_row(items), rep)
        users = solve_side(u_blocks, items_full)
        users_full = jax.lax.with_sharding_constraint(_append_zero_row(users), rep)
        items = solve_side(i_blocks, users_full)
        return users, items

    return jax.jit(
        iteration,
        in_shardings=(row, row, row, row, rep, rep),
        out_shardings=(row, row),
        donate_argnums=(2, 3),
    )


@dataclass
class ALSModel:
    user_factors: np.ndarray  # [num_users, K]
    item_factors: np.ndarray  # [num_items, K]
    #: lazily-built catalog norm cache -- similar_items is called once per
    #: anchor at serving time and must not rescan item_factors every call
    _item_norms: np.ndarray | None = field(
        default=None, init=False, repr=False, compare=False
    )
    #: lazily-built device retrieval indexes (``ops/mips.RetrievalIndex``),
    #: keyed by (kind, RetrievalConfig) -- see
    #: ``models/_als_common.retrieval_index``. Old pickled blobs predate
    #: this field; readers go through getattr with a default.
    _retrieval_cache: dict | None = field(
        default=None, init=False, repr=False, compare=False
    )

    def __getstate__(self):
        # device arrays + jitted programs must never enter a model blob:
        # the registry is the durability path, indexes rebuild at deploy
        state = self.__dict__.copy()
        state["_retrieval_cache"] = None
        return state

    def score_items_for_user(self, user_index: int) -> np.ndarray:
        # einsum, not @: BLAS sgemv picks its kernel by matrix height, so a
        # gathered-row product is a ULP off the full one -- einsum's per-row
        # reduction is height-independent, which lets the mips shortlist
        # re-rank (_als_common._host_rerank) reproduce these scores bitwise
        return np.einsum("ik,k->i", self.item_factors, self.user_factors[user_index])

    def score_users_for_item(self, item_index: int) -> np.ndarray:
        return self.user_factors @ self.item_factors[item_index]

    @property
    def item_norms(self) -> np.ndarray:
        if self._item_norms is None:
            self._item_norms = np.linalg.norm(self.item_factors, axis=1)
        return self._item_norms

    def similar_items(self, item_index: int) -> np.ndarray:
        """Cosine scores of all items against one (ALS-space similarity).

        einsum for the same reason as ``score_items_for_user``: the mips
        shortlist replays this row arithmetic and must land bitwise."""
        v = self.item_factors[item_index]
        norms = self.item_norms * (self.item_norms[item_index] + 1e-12)
        return np.einsum("ik,k->i", self.item_factors, v) / np.maximum(norms, 1e-12)


def device_put_blocks(side: BucketedCSR, put) -> tuple:
    """``put`` each bucket block as its device triple (indices, values,
    n_obs). The ``[R, L]`` mask never crosses the host link: the padding
    invariant (see _half_step_explicit) reduces it to the per-row
    observation count."""
    return tuple(
        (put(b.indices), put(b.values), put(b.mask.sum(axis=1)))
        for b in side.blocks
    )


def modeled_bytes_per_iteration(
    data: ALSData, rank: int, itemsize: int, fused: bool
) -> float:
    """HBM bytes one full ALS iteration moves through its half-step tails
    (``ops.als_gram.half_step_bytes`` summed over both sides' buckets).
    The half-step is bandwidth-bound, so achieved GB/s against this model
    is the training-efficiency axis -- the number the ``--profile``
    telemetry journal and the bench secondary both report."""
    from predictionio_tpu.ops.als_gram import half_step_bytes

    return sum(
        half_step_bytes(*block.indices.shape, rank, itemsize, fused)
        for side in (data.by_row, data.by_col)
        for block in side.blocks
    )


def real_edges(data: ALSData) -> int:
    """Real (unpadded) observations -- the edges/sec denominator. Sides
    built by the sharded reader hold only this process's rows; the count
    is then per-process, which is the per-chip rate ALX reports."""
    return int(sum(b.mask.sum() for b in data.by_row.blocks))


def _initial_side_factors(side, rank: int, seed: int) -> np.ndarray:
    """Seeded N(0, 1/sqrt(K)) init for one side, drawn in ORIGINAL entity
    order and scattered into factor slots: invariant to the bucket plan,
    to shard-count padding, AND to the resident-vs-streamed layout (both
    duck-type ``num_rows``/``total_slots``/``slot_of``); phantom rows stay
    zero (invisible to the implicit-mode global Gram)."""
    rng = np.random.default_rng(seed)
    scale = 1.0 / np.sqrt(rank)
    real = rng.normal(size=(side.num_rows, rank)) * scale
    out = np.zeros((side.total_slots, rank))
    out[side.slot_of] = real
    return out


def _scatter_side_init(side, host: np.ndarray) -> np.ndarray:
    """Checkpointed factors (original entity order) -> slot order."""
    out = np.zeros((side.total_slots, host.shape[1]), dtype=np.float64)
    out[side.slot_of] = np.asarray(host)[: side.num_rows]
    return out


def als_fit(
    data: ALSData,
    config: ALSConfig,
    mesh=None,
    callback=None,
    callback_interval: int = 1,
    init: tuple[np.ndarray, np.ndarray] | None = None,
    start_iteration: int = 0,
    telemetry=None,
) -> ALSModel:
    """Run ALS to convergence budget; returns host-side factor matrices.

    ``callback(iteration, user_factors, item_factors)`` runs every
    ``callback_interval`` iterations (skipping the final one, whose result
    als_fit returns anyway) with HOST numpy copies in ORIGINAL entity
    order (safe to retain -- the checkpointing hook; the on-device buffers
    are donated between iterations and must not escape). The interval
    lives HERE so non-callback iterations never pay the device sync + host
    copy that materializing the factors costs. ``init``/``start_iteration``
    resume from checkpointed factors (original order): the remaining
    iterations run, which is exact for ALS (each iteration depends only on
    the previous factors). ``mesh`` defaults to a 1-device local mesh.

    ``telemetry`` (``obs.telemetry.TrainTelemetry``) records one journal
    line per iteration: wall time, edges/sec, achieved GB/s vs the
    bytes-moved model, recompile count. Per-step timing needs a device
    sync after EVERY iteration (a one-scalar fetch), which serializes the
    dispatch pipeline -- that cost is only paid when profiling is on;
    the un-profiled loop keeps its async chain.
    """
    from predictionio_tpu.obs.trace import global_tracer
    from predictionio_tpu.parallel.mesh import local_mesh

    tracer = global_tracer()

    mesh = mesh or local_mesh(1, 1)
    if config.dtype not in ("float32", "bfloat16"):
        # e.g. an integer dtype would truncate the N(0, 1/sqrt(K)) init to
        # all zeros -- a fixed point of the update -- and train a silently
        # degenerate model
        raise ValueError(
            f"ALSConfig.dtype must be 'float32' or 'bfloat16', got"
            f" {config.dtype!r}"
        )
    dtype = jnp.dtype(config.dtype)

    if init is not None:
        users0 = _scatter_side_init(data.by_row, init[0])
        items0 = _scatter_side_init(data.by_col, init[1])
    else:
        users0 = _initial_side_factors(data.by_row, config.rank, config.seed)
        items0 = _initial_side_factors(data.by_col, config.rank, config.seed + 1)

    from predictionio_tpu.parallel.mesh import fetch_global as fetch
    from predictionio_tpu.parallel.mesh import put_global

    row = NamedSharding(mesh, PartitionSpec("data"))
    # default path: every process loads the same event store; put_global
    # feeds each exactly its addressable row shards. Sides built by the
    # SHARDED reader (global_rows set) carry only this process's rows and
    # assemble via make_array_from_process_local_data -- no host ever held
    # the global edge set (SURVEY 2.6 DP row: host-side sharded reader).
    put_row = lambda a: put_global(a, row)

    def put_side(side: BucketedCSR):
        if side.global_rows is None:
            return device_put_blocks(side, put_row)
        return tuple(
            (
                jax.make_array_from_process_local_data(
                    row, b.indices, (rows, b.indices.shape[1])
                ),
                jax.make_array_from_process_local_data(
                    row, b.values, (rows, b.values.shape[1])
                ),
                jax.make_array_from_process_local_data(
                    row, b.mask.sum(axis=1), (rows,)
                ),
            )
            for b, rows in zip(side.blocks, side.global_rows)
        )

    with tracer.span(
        "als.transfer",
        attrs={"edges": data.by_row.retained_edges or None},
    ):
        # host->device CSR transfer: the step the device-resident-epochs
        # ROADMAP item wants to overlap; its span makes the cost visible
        u_blocks = put_side(data.by_row)
        i_blocks = put_side(data.by_col)

    if config.factor_sharding == "model":
        m = mesh.shape["model"]
        d = mesh.shape["data"]
        for side, name in ((data.by_row, "user"), (data.by_col, "item")):
            # sides built by the sharded reader hold only this process's
            # rows in their blocks; the divisibility guarantee (and the
            # device array shape) is on the GLOBAL per-bucket row counts
            rows_per_bucket = (
                side.global_rows
                if side.global_rows is not None
                else tuple(b.indices.shape[0] for b in side.blocks)
            )
            if side.total_slots % m or any(
                rows % (d * m) for rows in rows_per_bucket
            ):
                raise ValueError(
                    f"factor_sharding='model' needs every {name} bucket's "
                    f"padded rows divisible by data*model = {d}*{m}; build "
                    f"the data with build_als_data(..., num_shards={d}, "
                    f"model_shards={m})"
                )
        fsh = NamedSharding(mesh, PartitionSpec("model"))
    else:
        fsh = row
    user_factors = put_global(users0.astype(dtype), fsh)
    item_factors = put_global(items0.astype(dtype), fsh)

    iteration = make_iteration(mesh, config)
    # globally-replicated scalars: a process-local jnp scalar cannot feed a
    # jit whose sharding spans other processes' devices (multi-host train)
    from predictionio_tpu.parallel.mesh import replicated

    rep = replicated(mesh)
    reg = put_global(np.float32(config.reg), rep)
    alpha = put_global(np.float32(config.alpha), rep)

    def to_host(factors, side: BucketedCSR) -> np.ndarray:
        # f32 on the host regardless of the on-device factor dtype:
        # checkpoints and serving stay dtype-stable across bf16 runs
        return fetch(factors)[side.slot_of].astype(np.float32)

    if telemetry is not None:
        from predictionio_tpu.obs.telemetry import jit_cache_size

        def step_sync(x) -> None:
            # one-scalar fetch: a hard device sync even on remote-tunnel
            # backends where block_until_ready returns early (bench.py
            # precedent); the donated-buffer chain keeps it honest
            np.asarray(jax.device_get(x[:1, :1]))

    for it in range(start_iteration, config.iterations):
        if telemetry is not None:
            # per-half-step resolution lives inside one jitted program;
            # the per-iteration span + journal line (wall, edges/sec,
            # achieved GB/s) is the honest host-visible boundary
            with tracer.span("als.iteration", attrs={"step": it}):
                step_t0 = time.perf_counter()
                user_factors, item_factors = iteration(
                    u_blocks, i_blocks, user_factors, item_factors, reg, alpha
                )
                step_sync(user_factors)
                telemetry.record_step(
                    it,
                    time.perf_counter() - step_t0,
                    recompile_count=jit_cache_size(iteration),
                )
        else:
            user_factors, item_factors = iteration(
                u_blocks, i_blocks, user_factors, item_factors, reg, alpha
            )
        if (
            callback is not None
            and (it + 1) % callback_interval == 0
            and it + 1 < config.iterations
        ):
            # host copies: the device buffers are donated into the next
            # iteration; handing them out would raise 'Array has been
            # deleted' one iteration later, far from the cause
            callback(
                it,
                to_host(user_factors, data.by_row),
                to_host(item_factors, data.by_col),
            )

    # serving model is always f32 host-side (numpy top-k math on bf16 via
    # ml_dtypes is slow and lossy; the dtype knob is a TRAINING layout)
    return ALSModel(
        user_factors=to_host(user_factors, data.by_row),
        item_factors=to_host(item_factors, data.by_col),
    )


# --------------------------------------------------------------------------
# device-resident epochs over streamed blocks (ALX, arxiv 2112.02194)
# --------------------------------------------------------------------------


class _StreamPrograms:
    """Jitted programs of one streamed-epoch configuration.

    ``prep`` runs ONCE per half-step (the loop-invariant hoist the J006
    lint encodes): it materializes the opposite side's replicated
    ``[S+1, K]`` gather table and the implicit-mode YtY Gram, so the
    per-block python loop re-ships NOTHING invariant -- each block step
    moves only that block's streams plus two 4-byte scalars (offset,
    uniform value). ``step(has_values)`` solves one block's rows and
    dynamic_update_slice's them into the DONATED side buffer: the factor
    table is updated in place and never leaves the device during the
    epoch. A half-step's solve never reads its own side, so in-place
    block updates are exact, not approximate.
    """

    def __init__(self, mesh, rank: int, implicit: bool, factor_axis: str,
                 solver: str):
        self.implicit = implicit
        self.factor_axis = factor_axis
        P = PartitionSpec
        row = NamedSharding(mesh, P("data"))
        rep = NamedSharding(mesh, P())
        unroll = mesh.devices.flat[0].platform != "cpu"
        interpret = mesh.devices.flat[0].platform == "cpu"

        def side_yty(opp):
            if implicit:
                return _factors_yty(opp)
            return jnp.zeros((rank, rank), jnp.float32)

        if factor_axis == "model":
            fsh = NamedSharding(mesh, P("model"))
            body = functools.partial(
                _sharded_block_body, implicit=implicit, rank=rank,
                unroll=unroll, solver=solver, interpret=interpret,
            )
            smapped = shard_map(
                body,
                mesh=mesh,
                in_specs=(P("data", None), P("data", None), P("data"),
                          P("model", None), P(), P(), P()),
                out_specs=P(("data", "model"), None),
                check_vma=solver != "pallas",
            )
            self.prep = jax.jit(
                lambda opp: (opp, side_yty(opp)),
                in_shardings=(fsh,), out_shardings=(fsh, rep),
            )

            def solve_rows(idx, val, n_obs, opp, yty, reg, alpha):
                piece = smapped(idx, val, n_obs, opp, yty, reg, alpha)
                # single-array reshard P(("data","model")) -> P("model"):
                # the J005-safe assembly (no concat ever feeds a reshard)
                return jax.lax.with_sharding_constraint(piece, fsh)

            buf_sh = fsh
        else:
            fsh = row
            if solver == "pallas":
                pallas_step = functools.partial(
                    _half_step_pallas, implicit=implicit, rank=rank,
                    unroll=unroll, interpret=interpret,
                )
                smapped = shard_map(
                    pallas_step,
                    mesh=mesh,
                    in_specs=(P("data", None), P("data", None), P("data"),
                              P(), P(), P(), P()),
                    out_specs=P("data", None),
                    check_vma=False,
                )

            def solve_rows(idx, val, n_obs, opp_full, yty, reg, alpha):
                if solver == "pallas":
                    return smapped(idx, val, n_obs, opp_full, yty, reg, alpha)
                if implicit:
                    return _half_step_implicit(
                        idx, val, n_obs, opp_full, yty, reg, alpha, rank,
                        unroll,
                    )
                return _half_step_explicit(
                    idx, val, n_obs, opp_full, reg, rank, unroll
                )

            self.prep = jax.jit(
                lambda f: (_append_zero_row(f), side_yty(f)),
                in_shardings=(row,), out_shardings=(rep, rep),
            )
            buf_sh = row

        self.factor_sharding = buf_sh

        def make_step(has_values: bool):
            def block_update(buf, idx, val_in, n_obs, opp, yty, reg, alpha, off):
                if has_values:
                    val = val_in
                else:
                    # uniform-value block: the value stream never crossed
                    # the host link. Exact, not lossy -- padding slots
                    # gather the appended zero factor row, so their value
                    # is don't-care (the module's padding invariant).
                    val = jnp.full(idx.shape, val_in, jnp.float32)
                if n_obs.ndim == 0:
                    # implicit mode never reads per-row counts (constant
                    # ridge): the driver ships a scalar placeholder and the
                    # [rows] vector materializes on device
                    n_obs = jnp.zeros((idx.shape[0],), jnp.float32)
                rows = solve_rows(idx, val, n_obs, opp, yty, reg, alpha)
                return jax.lax.dynamic_update_slice(buf, rows, (off, 0))

            val_sh = row if has_values else rep
            nob_sh = rep if implicit else row
            opp_sh = fsh if factor_axis == "model" else rep
            return jax.jit(
                block_update,
                in_shardings=(buf_sh, row, val_sh, nob_sh, opp_sh, rep,
                              rep, rep, rep),
                out_shardings=buf_sh,
                donate_argnums=(0,),
            )

        self._steps = {True: make_step(True), False: make_step(False)}

    def step(self, has_values: bool):
        return self._steps[has_values]


@cached_by_mesh(maxsize=32)
def _build_stream_programs(mesh, rank: int, implicit: bool,
                           factor_axis: str, solver: str) -> _StreamPrograms:
    return _StreamPrograms(mesh, rank, implicit, factor_axis, solver)


def als_fit_streamed(
    data,
    config: ALSConfig,
    mesh=None,
    callback=None,
    callback_interval: int = 1,
    init: tuple[np.ndarray, np.ndarray] | None = None,
    start_iteration: int = 0,
    telemetry=None,
    device_budget_bytes: int = 0,
    stats=None,
) -> ALSModel:
    """``als_fit`` restructured as ALX device-resident epochs.

    Both factor tables are placed on device ONCE (sharded per
    ``config.factor_sharding``) and stay resident across every half-step;
    the padded-CSR row blocks of ``data`` (a ``parallel.stream.
    StreamedALSData`` block store) stream host->device through a
    prefetch-1 feeder -- block N+1's ``device_put`` is in flight while the
    half-step kernel consumes block N -- and are dropped the moment their
    rows are solved. The ``[rows, L]`` host intermediate for a whole side
    never exists: peak host memory is O(block), which is what lifts the
    edge ceiling from "fits in RAM twice" to "fits on disk".

    Bit-identical to ``als_fit`` over ``build_als_data`` at equal shapes
    (same plans, same per-row packing, same kernels, same update order);
    the parity tests in ``tests/test_als_stream.py`` pin all solver x
    mode x dtype x sharding combinations.

    ``device_budget_bytes`` > 0 pins streamed blocks device-resident (in
    first-seen order) until the budget is exhausted: later iterations
    re-ship only the overflow. At ``0`` every iteration re-streams --
    predictable O(block) memory on hosts where "device" memory IS host
    RAM (the CPU box). ``stats`` (``parallel.stream.StreamStats``)
    receives the measured host->device traffic -- the evidence the bench's
    achieved-vs-modeled transfer metric reports.
    """
    import time as _time

    from predictionio_tpu.obs.trace import global_tracer
    from predictionio_tpu.parallel.mesh import (
        fetch_global,
        local_mesh,
        put_global,
        replicated,
    )
    from predictionio_tpu.parallel.stream import (
        FeedAccounting,
        StreamStats,
        prefetch_blocks,
    )

    tracer = global_tracer()
    mesh = mesh or local_mesh(1, 1)
    if config.dtype not in ("float32", "bfloat16"):
        raise ValueError(
            f"ALSConfig.dtype must be 'float32' or 'bfloat16', got"
            f" {config.dtype!r}"
        )
    if config.factor_sharding not in ("replicated", "model"):
        raise ValueError(
            "ALSConfig.factor_sharding must be 'replicated' or 'model', "
            f"got {config.factor_sharding!r}"
        )
    if jax.process_count() > 1:
        raise ValueError(
            "als_fit_streamed is single-process (the block store feeds "
            "local devices); multi-host training uses the sharded-reader "
            "resident path"
        )
    solver = resolve_solver(config.solver, mesh.devices.flat[0].platform)
    dtype = jnp.dtype(config.dtype)
    implicit = bool(config.implicit)
    stats = stats if stats is not None else StreamStats()

    d = mesh.shape["data"]
    m = mesh.shape.get("model", 1)
    if config.factor_sharding == "model":
        for side, name in ((data.by_row, "user"), (data.by_col, "item")):
            if side.total_slots % m or any(
                s.rows % (d * m) for s in side.specs
            ):
                raise ValueError(
                    f"factor_sharding='model' needs every {name} block's "
                    f"rows divisible by data*model = {d}*{m}; build the "
                    f"block store with num_shards={d}, model_shards={m}"
                )
        fsh = NamedSharding(mesh, PartitionSpec("model"))
    else:
        if any(
            s.rows % d for side in (data.by_row, data.by_col)
            for s in side.specs
        ):
            raise ValueError(
                f"streamed blocks must shard evenly over the {d}-way data "
                f"axis; build the block store with num_shards={d}"
            )
        fsh = NamedSharding(mesh, PartitionSpec("data"))
    row = NamedSharding(mesh, PartitionSpec("data"))
    rep = replicated(mesh)

    if init is not None:
        users0 = _scatter_side_init(data.by_row, init[0])
        items0 = _scatter_side_init(data.by_col, init[1])
    else:
        users0 = _initial_side_factors(data.by_row, config.rank, config.seed)
        items0 = _initial_side_factors(
            data.by_col, config.rank, config.seed + 1
        )
    with tracer.span(
        "als.transfer", attrs={"edges": data.real_edges or None}
    ):
        # ONE factor placement per epoch sequence -- the device-resident
        # contract; everything else streams through the feeder below
        user_factors = put_global(users0.astype(dtype), fsh)
        item_factors = put_global(items0.astype(dtype), fsh)
    # loop-invariant scalars cross the host link exactly once per fit
    # (the hoisted shape the J006 lint pins)
    reg = put_global(np.float32(config.reg), rep)
    alpha = put_global(np.float32(config.alpha), rep)

    programs = _build_stream_programs(
        mesh, config.rank, implicit, config.factor_sharding, solver
    )
    accounting = FeedAccounting()
    pinned: dict = {}
    budget_left = [int(device_budget_bytes)]

    def put_block(spec, host):
        idx, val, nobs = host
        idx_d = put_global(idx, row)
        moved = idx.nbytes
        if val is not None:
            val_d = put_global(val, row)
            moved += val.nbytes
        else:
            val_d = np.float32(spec.const)  # 4-byte scalar rides the call
            stats.h2d_scalar_bytes += 4
        if implicit:
            nobs_d = np.float32(0.0)  # scalar placeholder; see block_update
        else:
            nobs_d = put_global(nobs, row)
            moved += nobs.nbytes
        stats.h2d_block_bytes += moved
        return (idx_d, val_d, nobs_d), moved

    def feed(side, side_name):
        acquired: dict[int, bool] = {}

        def produce(spec):
            hit = pinned.get((side_name, spec.index))
            if hit is not None:
                stats.blocks_pinned += 1
                return hit
            accounting.acquire()
            acquired[spec.index] = True
            host = side.load_block(spec)
            dev, moved = put_block(spec, host)
            del host  # the feeder's two-block residency bound
            stats.blocks_streamed += 1
            if budget_left[0] >= moved:
                pinned[(side_name, spec.index)] = dev
                budget_left[0] -= moved
                stats.pinned_bytes += moved
            return dev

        def consumed(spec) -> None:
            if acquired.pop(spec.index, False):
                accounting.release()

        return prefetch_blocks(side.specs, produce, consumed)

    def solve_side(side, side_name, opp, buf):
        opp_arg, yty = programs.prep(opp)
        for spec, (idx_d, val_d, nobs_d) in feed(side, side_name):
            step = programs.step(spec.const is None)
            buf = step(
                buf, idx_d, val_d, nobs_d, opp_arg, yty, reg, alpha,
                np.int32(spec.offset),
            )
            stats.h2d_scalar_bytes += 4  # the block offset scalar
        stats.half_steps += 1
        return buf

    def to_host(factors, side) -> np.ndarray:
        return fetch_global(factors)[side.slot_of].astype(np.float32)

    if telemetry is not None:
        from predictionio_tpu.obs.telemetry import jit_cache_size

        def step_sync(x) -> None:
            np.asarray(jax.device_get(x[:1, :1]))

        def recompiles() -> int:
            return sum(
                jit_cache_size(programs.step(flag)) for flag in (True, False)
            )

    for it in range(start_iteration, config.iterations):
        if telemetry is not None:
            with tracer.span("als.iteration", attrs={"step": it}):
                step_t0 = _time.perf_counter()
                user_factors = solve_side(
                    data.by_row, "u", item_factors, user_factors
                )
                item_factors = solve_side(
                    data.by_col, "i", user_factors, item_factors
                )
                step_sync(user_factors)
                telemetry.record_step(
                    it,
                    _time.perf_counter() - step_t0,
                    recompile_count=recompiles(),
                )
        else:
            user_factors = solve_side(
                data.by_row, "u", item_factors, user_factors
            )
            item_factors = solve_side(
                data.by_col, "i", user_factors, item_factors
            )
        if (
            callback is not None
            and (it + 1) % callback_interval == 0
            and it + 1 < config.iterations
        ):
            callback(
                it,
                to_host(user_factors, data.by_row),
                to_host(item_factors, data.by_col),
            )

    stats.max_inflight_blocks = accounting.max_live
    return ALSModel(
        user_factors=to_host(user_factors, data.by_row),
        item_factors=to_host(item_factors, data.by_col),
    )
