"""Alternating Least Squares on the device mesh.

The TPU-native replacement for MLlib ALS (reference call site: the
recommendation template's ``ALSAlgorithm.train`` -> ``org.apache.spark.mllib
.recommendation.ALS``, SURVEY.md section 2.6/3.1 -- Spark dep, not repo
code). Design anchor: ALX (arxiv 2112.02194, PAPERS.md), "ALS on TPUs":

- interactions live as padded CSR blocks (``ops.ragged``): static shapes,
  gathers instead of ragged loops;
- each half-step solves all rows' K x K normal equations as one batched
  Cholesky on the MXU: Gram via ``einsum`` over the padded gather, masked;
- sharding: rows of the padded CSR shard over the ``data`` mesh axis; the
  opposite-side factor matrix is replicated (XLA all-gathers it once per
  half-step -- the collective that replaces MLlib's factor-block shuffle);
- implicit-feedback mode (MLlib ``trainImplicit`` parity) uses the YtY trick:
  the global Gram is one replicated K x K matmul + per-row corrections over
  observed entries only.

Explicit objective:  sum_obs (r - u.v)^2 + lam * (|U|^2 + |V|^2)
Implicit objective (Hu-Koren-Volinsky): confidence c = 1 + alpha*r on
observed pairs, preference p = 1; unobserved pairs have c = 1, p = 0.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from predictionio_tpu.ops.linalg import batched_spd_solve
from predictionio_tpu.ops.ragged import PaddedCSR, pack_padded_csr
from predictionio_tpu.parallel.mesh import cached_by_mesh


@dataclass
class ALSConfig:
    rank: int = 16
    iterations: int = 10
    reg: float = 0.1           # lambda (MLlib: lambda_)
    alpha: float = 40.0        # implicit confidence scale
    implicit: bool = False
    seed: int = 0
    max_len: int | None = None  # per-row history cap (SURVEY 5.7)
    dtype: str = "float32"     # factor dtype; Grams always accumulate f32


@dataclass
class ALSData:
    """Both orientations of the interaction matrix, padded for the mesh."""

    by_row: PaddedCSR  # users x items
    by_col: PaddedCSR  # items x users


def build_als_data(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    num_users: int,
    num_items: int,
    config: ALSConfig,
    times: np.ndarray | None = None,
    num_shards: int = 1,
) -> ALSData:
    """Pack COO interactions into both CSR orientations, row counts padded
    to multiples of 8 * num_shards so every shard is equal AND lane-aligned
    (max(8, n) breaks for shard counts like 6 that don't divide 8)."""
    common = dict(max_len=config.max_len, row_multiple=8 * max(num_shards, 1))
    by_row = pack_padded_csr(
        users, items, ratings, num_users, num_items, times=times, **common
    )
    by_col = pack_padded_csr(
        items, users, ratings, num_items, num_users, times=times, **common
    )
    return ALSData(by_row=by_row, by_col=by_col)


def _factor_precision(dtype):
    """Matmul precision for einsums whose operands are both factor-typed.

    f32 operands need "highest" (stops XLA lowering them to bf16 passes on
    TPU); bf16 operands are already exact in a single MXU pass with f32
    accumulation, and "highest" would force 3-pass emulation for nothing.
    """
    return "highest" if dtype == jnp.float32 else None


def _half_step_explicit(indices, values, mask, factors, reg, rank, unroll):
    """Solve one side's factors given the other side's (replicated) factors.

    factors carries a trailing zero row so padding gathers are in-bounds.
    Mixed precision, ALX-style: factors may be bf16 (half the HBM traffic
    for the gather and half the ICI traffic for the all-gather; bf16 inputs
    are the MXU's native mode), while the Gram/rhs accumulate in f32 and
    the normal-equation solve runs in f32; the solution is cast back to the
    factor dtype on return. ``reg`` may be a traced scalar (the iteration
    program is shared across regularization values -- see _build_iteration).
    """
    gathered = factors[indices]                       # [R, L, K]
    gathered = gathered * mask[..., None].astype(factors.dtype)
    gram = jnp.einsum(
        "rlk,rlj->rkj", gathered, gathered,
        precision=_factor_precision(factors.dtype),
        preferred_element_type=jnp.float32,
    )
    # MLlib-style weighted regularization: lambda * n_obs (ALS-WR); constant
    # lambda would also be defensible -- n_obs matches the reference template
    n_obs = mask.sum(axis=1)
    ridge = reg * jnp.maximum(n_obs, 1.0)
    gram = gram + ridge[:, None, None] * jnp.eye(rank, dtype=gram.dtype)
    rhs = jnp.einsum(
        "rlk,rl->rk", gathered, values * mask,
        precision="highest", preferred_element_type=jnp.float32,
    )
    return batched_spd_solve(gram, rhs, unroll=unroll).astype(factors.dtype)


def _half_step_implicit(indices, values, mask, factors, reg, alpha, rank, unroll):
    """Hu-Koren-Volinsky implicit step with the YtY trick.

    G = YtY + sum_obs (c-1) y y^T + lam*I ; rhs = sum_obs c * y
    Same mixed-precision contract as the explicit step: bf16-capable factor
    storage, f32 Gram accumulation and solve.
    """
    active = factors[:-1]  # drop the padding row from the global Gram
    yty = jnp.einsum(
        "nk,nj->kj", active, active,
        precision=_factor_precision(factors.dtype),
        preferred_element_type=jnp.float32,
    )
    gathered = factors[indices] * mask[..., None].astype(factors.dtype)
    conf_minus_1 = alpha * values * mask
    gram_fix = jnp.einsum(
        "rlk,rl,rlj->rkj", gathered, conf_minus_1, gathered,
        precision="highest", preferred_element_type=jnp.float32,
    )
    gram = yty[None] + gram_fix + reg * jnp.eye(rank, dtype=yty.dtype)
    rhs = jnp.einsum(
        "rlk,rl->rk", gathered, (1.0 + conf_minus_1) * mask,
        precision="highest", preferred_element_type=jnp.float32,
    )
    return batched_spd_solve(gram, rhs, unroll=unroll).astype(factors.dtype)


def _append_zero_row(factors: jnp.ndarray) -> jnp.ndarray:
    return jnp.concatenate(
        [factors, jnp.zeros((1, factors.shape[1]), factors.dtype)], axis=0
    )


def make_iteration(mesh, config: ALSConfig):
    """The jitted full ALS iteration for (mesh, config) -- see _build_iteration.

    The returned callable takes the CSR args + factor buffers followed by
    the ``reg`` and ``alpha`` scalars (runtime values; the compiled program
    is shared across them).
    """
    return _build_iteration(mesh, config.rank, config.implicit)


@cached_by_mesh(maxsize=32)
def _build_iteration(mesh, rank: int, implicit: bool):
    """Build the jitted full ALS iteration (both half-steps fused).

    CSR rows shard over the 'data' mesh axis; factor matrices live row-
    sharded and are re-materialized replicated (+ zero pad row) INSIDE the
    jit, so the all-gather that replaces MLlib's factor-block shuffle is an
    on-device XLA collective, not a host round-trip. Factor buffers are
    donated: each iteration updates in place instead of reallocating.

    ``reg``/``alpha`` are RUNTIME scalars, not baked constants: a
    ``pio eval`` grid over lambda/alpha reuses one compiled program per
    (mesh, rank, mode) instead of paying a full XLA compile per candidate
    (minutes each on a remote-compile TPU backend). The remaining cache key
    covers repeated ``als_fit`` calls in one process (serving retrains,
    benchmarks).
    """
    row = NamedSharding(mesh, PartitionSpec("data"))
    rep = NamedSharding(mesh, PartitionSpec())

    # solve-path choice is per TARGET platform, not default backend: the
    # benchmark compiles a CPU mesh while a TPU backend is live (and vice
    # versa), and the unrolled solver is ~5x faster on TPU / ~8x slower on
    # CPU than LAPACK's batched Cholesky (ops.linalg.batched_spd_solve).
    # Any non-cpu platform counts as TPU-like: the axon tunnel backend
    # reports platform "axon" for real TPU chips.
    unroll = mesh.devices.flat[0].platform != "cpu"

    def iteration(u_idx, u_val, u_msk, i_idx, i_val, i_msk, users, items, reg, alpha):
        if implicit:
            step = functools.partial(
                _half_step_implicit, reg=reg, alpha=alpha, rank=rank, unroll=unroll
            )
        else:
            step = functools.partial(
                _half_step_explicit, reg=reg, rank=rank, unroll=unroll
            )
        items_full = jax.lax.with_sharding_constraint(_append_zero_row(items), rep)
        users = step(u_idx, u_val, u_msk, items_full)
        users_full = jax.lax.with_sharding_constraint(_append_zero_row(users), rep)
        items = step(i_idx, i_val, i_msk, users_full)
        return users, items

    return jax.jit(
        iteration,
        in_shardings=(row,) * 8 + (rep, rep),
        out_shardings=(row, row),
        donate_argnums=(6, 7),
    )


@dataclass
class ALSModel:
    user_factors: np.ndarray  # [num_users, K]
    item_factors: np.ndarray  # [num_items, K]
    #: lazily-built catalog norm cache -- similar_items is called once per
    #: anchor at serving time and must not rescan item_factors every call
    _item_norms: np.ndarray | None = field(
        default=None, init=False, repr=False, compare=False
    )

    def score_items_for_user(self, user_index: int) -> np.ndarray:
        return self.item_factors @ self.user_factors[user_index]

    def score_users_for_item(self, item_index: int) -> np.ndarray:
        return self.user_factors @ self.item_factors[item_index]

    @property
    def item_norms(self) -> np.ndarray:
        if self._item_norms is None:
            self._item_norms = np.linalg.norm(self.item_factors, axis=1)
        return self._item_norms

    def similar_items(self, item_index: int) -> np.ndarray:
        """Cosine scores of all items against one (ALS-space similarity)."""
        v = self.item_factors[item_index]
        norms = self.item_norms * (self.item_norms[item_index] + 1e-12)
        return (self.item_factors @ v) / np.maximum(norms, 1e-12)


def als_fit(
    data: ALSData,
    config: ALSConfig,
    mesh=None,
    callback=None,
    callback_interval: int = 1,
    init: tuple[np.ndarray, np.ndarray] | None = None,
    start_iteration: int = 0,
) -> ALSModel:
    """Run ALS to convergence budget; returns host-side factor matrices.

    ``callback(iteration, user_factors, item_factors)`` runs every
    ``callback_interval`` iterations (skipping the final one, whose result
    als_fit returns anyway) with HOST numpy copies (safe to retain -- the
    checkpointing hook; the on-device buffers are donated between
    iterations and must not escape). The interval lives HERE so
    non-callback iterations never pay the device sync + host copy that
    materializing the factors costs. ``init``/``start_iteration`` resume
    from checkpointed factors: the remaining iterations run, which is exact
    for ALS (each iteration depends only on the previous factors).
    ``mesh`` defaults to a 1-device local mesh.
    """
    from predictionio_tpu.parallel.mesh import local_mesh

    mesh = mesh or local_mesh(1, 1)
    if config.dtype not in ("float32", "bfloat16"):
        # e.g. an integer dtype would truncate the N(0, 1/sqrt(K)) init to
        # all zeros -- a fixed point of the update -- and train a silently
        # degenerate model
        raise ValueError(
            f"ALSConfig.dtype must be 'float32' or 'bfloat16', got"
            f" {config.dtype!r}"
        )
    dtype = jnp.dtype(config.dtype)
    scale = 1.0 / np.sqrt(config.rank)

    def init_factors(num_real: int, num_padded: int, seed: int) -> np.ndarray:
        # draw exactly the real rows from a dedicated stream, then zero-pad:
        # init is invariant to shard-count-dependent padding, and phantom
        # rows stay invisible to the implicit-mode global Gram
        rng = np.random.default_rng(seed)
        real = rng.normal(size=(num_real, config.rank)) * scale
        return np.pad(real, ((0, num_padded - num_real), (0, 0)))

    if init is not None:
        users0 = np.pad(
            np.asarray(init[0]),
            ((0, data.by_row.indices.shape[0] - init[0].shape[0]), (0, 0)),
        )
        items0 = np.pad(
            np.asarray(init[1]),
            ((0, data.by_col.indices.shape[0] - init[1].shape[0]), (0, 0)),
        )
    else:
        users0 = init_factors(
            data.by_row.num_rows, data.by_row.indices.shape[0], config.seed
        )
        items0 = init_factors(
            data.by_col.num_rows, data.by_col.indices.shape[0], config.seed + 1
        )

    from predictionio_tpu.parallel.mesh import fetch_global as fetch
    from predictionio_tpu.parallel.mesh import put_global

    row = NamedSharding(mesh, PartitionSpec("data"))
    # multi-host: every process loads the same event store; put_global
    # feeds each exactly its addressable row shards
    put_row = lambda a: put_global(a, row)

    u_idx = put_row(data.by_row.indices)
    u_val = put_row(data.by_row.values)
    u_msk = put_row(data.by_row.mask)
    i_idx = put_row(data.by_col.indices)
    i_val = put_row(data.by_col.values)
    i_msk = put_row(data.by_col.mask)

    user_factors = put_row(users0.astype(dtype))
    item_factors = put_row(items0.astype(dtype))

    iteration = make_iteration(mesh, config)
    # globally-replicated scalars: a process-local jnp scalar cannot feed a
    # jit whose sharding spans other processes' devices (multi-host train)
    from predictionio_tpu.parallel.mesh import replicated

    rep = replicated(mesh)
    reg = put_global(np.float32(config.reg), rep)
    alpha = put_global(np.float32(config.alpha), rep)

    for it in range(start_iteration, config.iterations):
        user_factors, item_factors = iteration(
            u_idx, u_val, u_msk, i_idx, i_val, i_msk, user_factors, item_factors,
            reg, alpha,
        )
        if (
            callback is not None
            and (it + 1) % callback_interval == 0
            and it + 1 < config.iterations
        ):
            # host copies: the device buffers are donated into the next
            # iteration; handing them out would raise 'Array has been
            # deleted' one iteration later, far from the cause. f32 on the
            # host regardless of the on-device factor dtype: checkpoints
            # and serving stay dtype-stable across bf16 runs
            callback(
                it,
                fetch(user_factors)[: data.by_row.num_rows].astype(np.float32),
                fetch(item_factors)[: data.by_col.num_rows].astype(np.float32),
            )

    # serving model is always f32 host-side (numpy top-k math on bf16 via
    # ml_dtypes is slow and lossy; the dtype knob is a TRAINING layout)
    user_np = fetch(user_factors)[: data.by_row.num_rows].astype(np.float32)
    item_np = fetch(item_factors)[: data.by_col.num_rows].astype(np.float32)
    return ALSModel(user_factors=user_np, item_factors=item_np)
