"""Streamed padded-CSR block store: the host side of device-resident epochs.

ALX (arxiv 2112.02194) structures a TPU matrix-factorization epoch as
"factor tables resident in HBM, row blocks streamed in asynchronously".
The resident half lives in ``parallel.als.als_fit_streamed``; this module
is the streaming half -- it turns an unordered COO chunk stream (the PR-3
snapshot memmap replay, a SQL chunk scan, or a synthetic generator) into
an on-disk cache of **packed padded-CSR row blocks** that an epoch can
replay with O(block) host memory:

1. **plan** -- one counting pass derives both sides' bucket plans exactly
   like ``build_als_data`` (same ``_plan_buckets``, same slot maps), then
   each bucket's padded row range is cut into fixed-height blocks;
2. **spill** -- one partitioning pass appends every edge to its (side,
   block) spill file in stream order. Disk holds O(edges); the host holds
   one chunk;
3. **pack** -- each block's spill packs through ``pack_padded_csr``
   (identical per-row layout to the resident build: same stream order,
   same truncation, same padded length) and lands as raw ``int32`` index /
   ``float32`` value / ``float32`` n_obs files. The ``[rows, L]`` host
   intermediate for a whole side never exists -- only one block's worth.

**Uniform-value elision**: most implicit-feedback streams carry one
constant value (views = 1.0). A block whose real entries are all equal
stores no value file at all; the epoch driver re-materializes
``full(cval)`` on device. That is exact, not approximate: padding slots'
indices point at the appended zero factor row, so every padding term
multiplies a zero vector and the value there is don't-care (the
``parallel.als`` padding invariant). At ML-scale this halves the
host->device stream (indices only).

The feeder (:func:`prefetch_blocks`, driven by ``als_fit_streamed``'s
``feed``) is a prefetch-1 generator: block N+1 is read from disk and
``device_put`` while the device still computes block N (JAX's async
dispatch keeps the transfer in flight under the compute), and at most two
host blocks are ever alive -- the peak-RSS bound the regression tests pin
via :class:`FeedAccounting`.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from dataclasses import dataclass, field

import numpy as np

from predictionio_tpu.ops.ragged import pack_padded_csr
from predictionio_tpu.parallel.als import _plan_buckets

#: bump on any incompatible change to block files or the manifest
STREAM_FORMAT_VERSION = 1

#: default packed-block height target in bytes (idx + val streams); the
#: actual height is per bucket: ``block_bytes // (L * 8)`` rounded down to
#: the row multiple. 32 MB keeps a 2-core box's resident set small while
#: amortizing per-block dispatch overhead.
DEFAULT_BLOCK_BYTES = 32 * 1024 * 1024

_SPILL_TIMES = np.dtype([("r", "<i4"), ("c", "<i4"), ("v", "<f4"), ("t", "<f8")])
_SPILL_PLAIN = np.dtype([("r", "<i4"), ("c", "<i4"), ("v", "<f4")])


@dataclass(frozen=True)
class BlockSpec:
    """One packed block: rows ``[offset, offset + rows)`` of a side's
    factor table, padded length ``pad_len`` (its bucket's L)."""

    index: int          # block number within the side
    bucket: int
    offset: int         # first factor slot (global within the side)
    rows: int           # padded rows (multiple of the layout row multiple)
    pad_len: int
    #: every real entry carries this value (value stream elided); None =
    #: mixed values, a value file exists
    const: float | None = None
    edges: int = 0      # real (mask=1) entries in the block
    truncated: int = 0

    def idx_bytes(self) -> int:
        return self.rows * self.pad_len * 4

    def val_bytes(self) -> int:
        return 0 if self.const is not None else self.rows * self.pad_len * 4

    def nobs_bytes(self) -> int:
        return self.rows * 4


@dataclass
class StreamedSide:
    """One orientation's block store. Duck-types the ``BucketedCSR``
    surface ``als_fit``'s init/readback needs (``slot_of``, ``num_rows``,
    ``total_slots``) without ever materializing the side."""

    name: str                 # "u" | "i"
    directory: str
    specs: list[BlockSpec]
    slot_of: np.ndarray       # original entity id -> factor slot
    num_rows: int             # real entities
    total_slots: int
    global_rows: None = None  # streamed sides are always process-global

    @property
    def real_edges(self) -> int:
        return sum(s.edges for s in self.specs)

    @property
    def truncated(self) -> int:
        return sum(s.truncated for s in self.specs)

    @property
    def padded_slots(self) -> int:
        return sum(s.rows * s.pad_len for s in self.specs)

    def _path(self, spec: BlockSpec, kind: str) -> str:
        return os.path.join(
            self.directory, f"{self.name}-{spec.index:05d}.{kind}.bin"
        )

    def load_block(
        self, spec: BlockSpec
    ) -> tuple[np.ndarray, np.ndarray | None, np.ndarray]:
        """Read one packed block: ``(indices i32 [rows, L], values f32
        [rows, L] or None when const, n_obs f32 [rows])``. ``np.fromfile``
        (not memmap): the copy is freed when the caller drops it, so the
        feeder's two-block residency bound is a real RSS bound."""
        idx = np.fromfile(self._path(spec, "idx"), dtype=np.int32)
        idx = idx.reshape(spec.rows, spec.pad_len)
        if spec.const is None:
            val = np.fromfile(self._path(spec, "val"), dtype=np.float32)
            val = val.reshape(spec.rows, spec.pad_len)
        else:
            val = None
        nobs = np.fromfile(self._path(spec, "nob"), dtype=np.float32)
        return idx, val, nobs


@dataclass
class StreamedALSData:
    """Both orientations as block stores + the layout facts a fit needs."""

    by_row: StreamedSide      # users x items
    by_col: StreamedSide      # items x users
    directory: str
    row_multiple: int
    manifest: dict = field(default_factory=dict)

    @property
    def real_edges(self) -> int:
        return self.by_row.real_edges


@dataclass
class StreamStats:
    """Measured host->device traffic of one streamed fit -- the evidence
    behind the bench's achieved-vs-modeled transfer metric."""

    h2d_block_bytes: int = 0   # actually device_put block payloads
    h2d_scalar_bytes: int = 0  # per-block offsets/consts (noise, reported)
    half_steps: int = 0
    blocks_streamed: int = 0
    blocks_pinned: int = 0
    pinned_bytes: int = 0
    max_inflight_blocks: int = 0

    @property
    def bytes_per_half_step(self) -> float:
        return self.h2d_block_bytes / max(self.half_steps, 1)


# --------------------------------------------------------------------------
# build
# --------------------------------------------------------------------------


def _side_specs(plan, row_multiple: int, block_rows: int | None,
                block_bytes: int) -> list[BlockSpec]:
    """Cut each bucket's padded row range into fixed-height blocks (the
    last block of a bucket may be shorter; heights stay multiples of the
    row multiple so every block shards evenly over data*model)."""
    specs: list[BlockSpec] = []
    index = 0
    for bucket, (off, padded, length) in enumerate(
        zip(plan.offsets, plan.padded_rows, plan.lengths)
    ):
        if block_rows is not None:
            height = max(row_multiple, (block_rows // row_multiple) * row_multiple)
        else:
            height = max(
                row_multiple,
                (block_bytes // (length * 8)) // row_multiple * row_multiple,
            )
        start = 0
        while start < padded:
            rows = min(height, padded - start)
            specs.append(BlockSpec(
                index=index, bucket=bucket, offset=off + start, rows=rows,
                pad_len=length,
            ))
            index += 1
            start += rows
    return specs


def _counts_digest(counts: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(counts).tobytes()).hexdigest()[:16]


def layout_key(
    config,
    row_multiple: int,
    block_rows: int | None,
    block_bytes: int,
    cnt_u: np.ndarray,
    cnt_i: np.ndarray,
    edges: int,
    with_times: bool,
    content_crc: int = 0,
) -> str:
    """Identity of one streamed layout: the bucket plans are a pure
    function of the counts + packing knobs, and ``content_crc`` (a
    running checksum of the stream's value AND time bytes) covers what
    the counts cannot -- the same (user, item) structure packed with
    different values (an ``event_values`` weight edit, a rating change)
    or reordered timestamps must never reuse a cached store."""
    material = json.dumps({
        "version": STREAM_FORMAT_VERSION,
        "buckets": max(int(config.buckets), 1),
        "max_len": config.max_len,
        "row_multiple": row_multiple,
        "block_rows": block_rows,
        "block_bytes": block_bytes,
        "edges": edges,
        "users": _counts_digest(cnt_u),
        "items": _counts_digest(cnt_i),
        "n_users": int(cnt_u.size),
        "n_items": int(cnt_i.size),
        "with_times": with_times,
        "content_crc": int(content_crc),
    }, sort_keys=True)
    return hashlib.sha256(material.encode()).hexdigest()[:16]


class _SideSpill:
    """Partition pass state for one orientation: an append handle per
    block plus the searchsorted row->block map."""

    def __init__(self, directory: str, name: str, specs: list[BlockSpec],
                 with_times: bool):
        self.dir = directory
        self.name = name
        self.specs = specs
        self.starts = np.array([s.offset for s in specs], dtype=np.int64)
        self.dtype = _SPILL_TIMES if with_times else _SPILL_PLAIN
        self._files: dict[int, object] = {}

    def _file(self, block: int):
        f = self._files.get(block)
        if f is None:
            f = open(self._spill_path(block), "ab")
            self._files[block] = f
        return f

    def _spill_path(self, block: int) -> str:
        return os.path.join(self.dir, f"{self.name}-{block:05d}.spill")

    def take(self, row_slots, col_slots, vals, times) -> None:
        block_of = np.searchsorted(self.starts, row_slots, side="right") - 1
        order = np.argsort(block_of, kind="stable")
        rec = np.empty(row_slots.size, dtype=self.dtype)
        rec["r"] = (row_slots - self.starts[block_of]).astype(np.int32)
        rec["c"] = col_slots.astype(np.int32)
        rec["v"] = vals
        if "t" in self.dtype.names:
            # a timeless chunk in a timed stream must still be
            # deterministic (pack sorts on this field)
            rec["t"] = 0.0 if times is None else times
        rec = rec[order]
        blocks = block_of[order]
        bounds = np.nonzero(np.diff(blocks))[0] + 1
        for lo, hi in zip(
            np.r_[0, bounds], np.r_[bounds, blocks.size]
        ):
            if lo == hi:
                continue
            self._file(int(blocks[lo])).write(rec[lo:hi].tobytes())

    def read_and_unlink(self, block: int) -> np.ndarray:
        f = self._files.pop(block, None)
        if f is not None:
            f.close()
        path = self._spill_path(block)
        try:
            rec = np.fromfile(path, dtype=self.dtype)
        except (OSError, FileNotFoundError):
            rec = np.empty(0, dtype=self.dtype)
        try:
            os.unlink(path)
        except OSError:
            pass
        return rec

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()


def _pack_side(
    spill: _SideSpill,
    specs: list[BlockSpec],
    directory: str,
    name: str,
    opp_total_slots: int,
    max_len: int | None,
    row_multiple: int,
) -> list[BlockSpec]:
    """Pack every spill file into its block triple; returns specs with
    const/edge metadata filled. Host memory: one block at a time."""
    import dataclasses

    out: list[BlockSpec] = []
    for spec in specs:
        rec = spill.read_and_unlink(spec.index)
        times = rec["t"] if "t" in rec.dtype.names and rec.size else None
        csr = pack_padded_csr(
            rec["r"].astype(np.int64),
            rec["c"].astype(np.int64),
            rec["v"],
            num_rows=spec.rows,
            num_cols=opp_total_slots,
            max_len=max_len,
            times=times,
            row_multiple=row_multiple,
            pad_len=spec.pad_len,
        )
        if csr.indices.shape != (spec.rows, spec.pad_len):
            raise AssertionError(
                f"packed block shape {csr.indices.shape} != spec "
                f"({spec.rows}, {spec.pad_len})"
            )
        vals = rec["v"]
        if vals.size == 0:
            const: float | None = 0.0  # all padding: value is don't-care
        elif np.all(vals == vals[0]):
            const = float(vals[0])
        else:
            const = None
        spec = dataclasses.replace(
            spec,
            const=const,
            edges=int(csr.mask.sum()),
            truncated=int(csr.truncated),
        )
        csr.indices.tofile(os.path.join(
            directory, f"{name}-{spec.index:05d}.idx.bin"))
        if const is None:
            csr.values.tofile(os.path.join(
                directory, f"{name}-{spec.index:05d}.val.bin"))
        csr.mask.sum(axis=1, dtype=np.float32).tofile(os.path.join(
            directory, f"{name}-{spec.index:05d}.nob.bin"))
        out.append(spec)
    return out


def _spec_json(s: BlockSpec) -> dict:
    return {
        "index": int(s.index), "bucket": int(s.bucket),
        "offset": int(s.offset), "rows": int(s.rows),
        "pad_len": int(s.pad_len),
        "const": None if s.const is None else float(s.const),
        "edges": int(s.edges), "truncated": int(s.truncated),
    }


def _side_from_manifest(directory: str, name: str, side: dict) -> StreamedSide:
    specs = [BlockSpec(**spec) for spec in side["specs"]]
    slot_of = np.fromfile(
        os.path.join(directory, f"{name}-slot_of.bin"), dtype=np.int64
    )
    return StreamedSide(
        name=name,
        directory=directory,
        specs=specs,
        slot_of=slot_of,
        num_rows=int(side["num_rows"]),
        total_slots=int(side["total_slots"]),
    )


def load_streamed_als_data(directory: str) -> StreamedALSData | None:
    """Open a committed block store; None when absent/invalid (size-checked
    per block so a torn build never feeds a fit)."""
    try:
        with open(os.path.join(directory, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if manifest.get("format_version") != STREAM_FORMAT_VERSION:
        return None
    try:
        by_row = _side_from_manifest(directory, "u", manifest["u"])
        by_col = _side_from_manifest(directory, "i", manifest["i"])
        for side in (by_row, by_col):
            for spec in side.specs:
                if os.path.getsize(side._path(spec, "idx")) != spec.idx_bytes():
                    return None
                if spec.const is None and os.path.getsize(
                    side._path(spec, "val")
                ) != spec.val_bytes():
                    return None
                if os.path.getsize(side._path(spec, "nob")) != spec.nobs_bytes():
                    return None
    except (OSError, KeyError, TypeError, ValueError):
        return None
    return StreamedALSData(
        by_row=by_row,
        by_col=by_col,
        directory=directory,
        row_multiple=int(manifest["row_multiple"]),
        manifest=manifest,
    )


def build_streamed_als_data(
    chunks,
    num_users: int | None,
    num_items: int | None,
    config,
    cache_dir: str,
    num_shards: int = 1,
    model_shards: int = 1,
    block_rows: int | None = None,
    block_bytes: int = DEFAULT_BLOCK_BYTES,
    reuse: bool = True,
) -> StreamedALSData:
    """Plan + spill + pack a COO chunk stream into a block store.

    Layout-equivalent to ``build_als_data(..., num_shards, model_shards)``
    -- same bucket plans, slot maps, padded lengths and per-row packing --
    so ``als_fit_streamed`` over the result is bit-identical to ``als_fit``
    over the resident build. Peak host memory is O(chunk + one block),
    never O(edges); the edge set lives on disk under ``cache_dir``.

    With ``reuse`` (default) a committed store whose layout key matches is
    loaded instead of rebuilt -- repeat epochs/trains pay zero passes.
    ``chunks`` is a ``parallel.reader.ChunkSource``: a zero-arg callable
    yielding ``(users, items, values, times|None)`` arrays; it is iterated
    twice (counts, spill).
    """
    from predictionio_tpu.parallel.reader import _grow_bincount

    rm = 8 * max(num_shards, 1) * max(model_shards, 1)
    nb = max(int(config.buckets), 1)
    import zlib

    cnt_u = np.zeros(num_users or 0, dtype=np.int64)
    cnt_i = np.zeros(num_items or 0, dtype=np.int64)
    edges = 0
    with_times = True
    content_crc = 0
    for uu, ii, vv, tt in chunks():
        cnt_u = _grow_bincount(cnt_u, uu)
        cnt_i = _grow_bincount(cnt_i, ii)
        edges += int(uu.size)
        # the endpoint streams must be in the key too: two edge sets with
        # IDENTICAL degree histograms (e.g. swapped endpoints) but
        # different pairings pack different matrices
        content_crc = zlib.crc32(
            np.ascontiguousarray(uu, np.int64).tobytes(), content_crc
        )
        content_crc = zlib.crc32(
            np.ascontiguousarray(ii, np.int64).tobytes(), content_crc
        )
        content_crc = zlib.crc32(
            np.ascontiguousarray(vv, np.float32).tobytes(), content_crc
        )
        if tt is None:
            with_times = False
        else:
            content_crc = zlib.crc32(
                np.ascontiguousarray(tt, np.float64).tobytes(), content_crc
            )
    for side_name, total in (("user", cnt_u.size), ("item", cnt_i.size)):
        if total >= 2 ** 31:
            raise ValueError(
                f"{side_name} universe {total} exceeds the int32 block "
                "index space"
            )

    key = layout_key(
        config, rm, block_rows, block_bytes, cnt_u, cnt_i, edges, with_times,
        content_crc,
    )
    target = os.path.join(cache_dir, f"blocks-{key}")
    if reuse:
        cached = load_streamed_als_data(target)
        if cached is not None:
            return cached

    plan_u = _plan_buckets(cnt_u, config.max_len, nb, rm)
    plan_i = _plan_buckets(cnt_i, config.max_len, nb, rm)
    specs_u = _side_specs(plan_u, rm, block_rows, block_bytes)
    specs_i = _side_specs(plan_i, rm, block_rows, block_bytes)

    os.makedirs(cache_dir, exist_ok=True)
    tmp = os.path.join(cache_dir, f".tmp-{os.getpid()}-{time.monotonic_ns()}")
    os.makedirs(tmp)
    try:
        spill_u = _SideSpill(tmp, "u", specs_u, with_times)
        spill_i = _SideSpill(tmp, "i", specs_i, with_times)
        t0 = time.perf_counter()
        for uu, ii, vv, tt in chunks():
            u_slots = plan_u.slot_of[uu]
            i_slots = plan_i.slot_of[ii]
            tt = tt if with_times else None
            spill_u.take(u_slots, i_slots, vv, tt)
            spill_i.take(i_slots, u_slots, vv, tt)
        spill_u.close()
        spill_i.close()
        spill_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        specs_u = _pack_side(
            spill_u, specs_u, tmp, "u", plan_i.total_slots, config.max_len, rm
        )
        specs_i = _pack_side(
            spill_i, specs_i, tmp, "i", plan_u.total_slots, config.max_len, rm
        )
        plan_u.slot_of.tofile(os.path.join(tmp, "u-slot_of.bin"))
        plan_i.slot_of.tofile(os.path.join(tmp, "i-slot_of.bin"))
        manifest = {
            "format_version": STREAM_FORMAT_VERSION,
            "layout_key": key,
            "row_multiple": rm,
            "edges": edges,
            "with_times": with_times,
            "spill_seconds": round(spill_s, 3),
            "pack_seconds": round(time.perf_counter() - t0, 3),
            "u": {
                "specs": [_spec_json(s) for s in specs_u],
                "num_rows": int(plan_u.slot_of.shape[0]),
                "total_slots": int(plan_u.total_slots),
            },
            "i": {
                "specs": [_spec_json(s) for s in specs_i],
                "num_rows": int(plan_i.slot_of.shape[0]),
                "total_slots": int(plan_i.total_slots),
            },
        }
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        # atomic publish; a racing builder of the same key built the
        # identical layout, so either copy serves. A torn carcass at the
        # target (crashed earlier build) is replaced.
        try:
            os.rename(tmp, target)
        except OSError:
            existing = load_streamed_als_data(target)
            if existing is not None:
                shutil.rmtree(tmp, ignore_errors=True)
                return existing
            shutil.rmtree(target, ignore_errors=True)
            os.rename(tmp, target)
        loaded = load_streamed_als_data(target)
        if loaded is None:
            raise OSError(f"block store at {target} failed validation")
        return loaded
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


# --------------------------------------------------------------------------
# the feeder
# --------------------------------------------------------------------------


class FeedAccounting:
    """Counts simultaneously-alive host blocks; the regression test pins
    the two-block bound (prefetch depth 1 + the block being consumed)."""

    def __init__(self) -> None:
        self.live = 0
        self.max_live = 0

    def acquire(self) -> None:
        self.live += 1
        self.max_live = max(self.max_live, self.live)

    def release(self) -> None:
        self.live -= 1


def prefetch_blocks(specs, produce, on_consumed=None):
    """Drive ``produce(spec)`` with prefetch depth 1 and yield ``(spec,
    produced)`` pairs: block N+1's ``produce`` (disk read + async
    ``device_put``) runs before block N is yielded for compute, so the
    transfer is in flight under the consumer's kernel. ``on_consumed``
    fires once the consumer has moved past a block (the release edge of
    the two-in-flight accounting)."""
    if not specs:
        return
    prev_spec = specs[0]
    ahead = produce(prev_spec)
    for nxt in specs[1:]:
        cur_spec, cur = prev_spec, ahead
        ahead = produce(nxt)  # N+1's transfer flies while N computes
        yield cur_spec, cur
        if on_consumed is not None:
            on_consumed(cur_spec)  # consumer asked for N+1: N is done
        prev_spec = nxt
    yield prev_spec, ahead
    if on_consumed is not None:
        on_consumed(prev_spec)


# --------------------------------------------------------------------------
# transfer models (the bench's modeled-vs-measured axis)
# --------------------------------------------------------------------------


def stream_bytes_per_half_step(data: StreamedALSData, implicit: bool) -> float:
    """Modeled host->device bytes one half-step streams with no pinning:
    the solved side's index stream + non-uniform value streams (+ n_obs in
    explicit mode, which needs per-row counts for ALS-WR ridge). Averaged
    over the two half-steps of an iteration."""
    total = 0
    for side in (data.by_row, data.by_col):
        for s in side.specs:
            total += s.idx_bytes() + s.val_bytes()
            if not implicit:
                total += s.nobs_bytes()
    return total / 2.0


def reship_bytes_per_half_step(
    data, rank: int, itemsize: int, implicit: bool = False
) -> float:
    """The re-ship baseline: what a NON-resident epoch moves host->device
    per half-step -- both orientations' CSR blocks (index + value + n_obs
    streams; no elision, values always ship) plus both factor tables
    re-materialized on device. This is the per-step transfer structure the
    pre-streaming loop amortized only by holding the whole edge set in
    device memory -- exactly what stops scaling past HBM/host RAM.

    Accepts ``StreamedALSData`` or the resident ``parallel.als.ALSData``.
    """
    del implicit  # the baseline ships n_obs/vals regardless; keep the knob
    total = 0.0
    sides = (data.by_row, data.by_col)
    for side in sides:
        specs = getattr(side, "specs", None)
        if specs is not None:
            shapes = [(s.rows, s.pad_len) for s in specs]
        else:
            shapes = [b.indices.shape for b in side.blocks]
        for rows, length in shapes:
            total += rows * length * 8 + rows * 4  # idx i32 + val f32 + n_obs
        total += (side.total_slots + 1) * rank * itemsize  # factor table
    return total
