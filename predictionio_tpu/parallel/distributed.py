"""Multi-host runtime: the spark-submit/cluster-manager replacement.

The reference scales out through Spark's control plane -- spark-submit to a
cluster manager, driver-to-executor RPC, Netty block shuffle (SURVEY.md
section 2.7). The TPU-native control plane is ``jax.distributed``: one
Python process per host, a coordinator address, and after initialization a
single global device list over which GSPMD lays collectives -- all_gather /
psum / ppermute ride ICI inside a slice and DCN across slices. Nothing else
to build: there is no NCCL/MPI analogue to port, the XLA runtime IS the
communication backend.

What this module adds on top of the raw primitives:

- :func:`init_distributed`: idempotent `jax.distributed.initialize` from
  explicit args or ``PIO_COORDINATOR`` / ``PIO_NUM_PROCESSES`` /
  ``PIO_PROCESS_ID`` env (the launcher contract: set three env vars per
  host, run the same ``pio train`` command everywhere).
- :func:`build_mesh`: one entry point for both single-slice meshes and
  hybrid DCN x ICI meshes (``dcn_mesh_shape``), so engine.json's runtime
  section scales from one chip to a multi-slice pod without code changes.
  Per-axis sizes multiply: global axis = ici * dcn; ICI-contiguous devices
  stay adjacent so collectives on the fast axes never cross DCN.
- :func:`host_local_batch`: per-process data feeding -- each host loads its
  own shard of the batch and the pieces assemble into one global sharded
  array (`jax.make_array_from_process_local_data`), replacing the
  driver-scatters-partitions model of Spark with host-parallel reads.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("pio.distributed")

_INITIALIZED = False

#: runtime-conf keys that describe THIS launch, not the engine: they must
#: not be replayed from a persisted EngineInstance (a serving process would
#: try to join the long-dead training coordinator as the wrong rank)
LAUNCH_SCOPED_KEYS = ("pio.coordinator", "pio.num_processes", "pio.process_id")
LAUNCH_SCOPED_ENV = ("PIO_COORDINATOR", "PIO_NUM_PROCESSES", "PIO_PROCESS_ID")


def launch_process_id(runtime_conf=None) -> int:
    """This process's rank under the launcher contract, 0 when standalone.

    Usable BEFORE jax.distributed initializes (which happens lazily inside
    mesh construction): run_train needs the rank up front to decide which
    process owns the persistence side effects (lock, instance row, model
    blob, step checkpoints).
    """
    if runtime_conf and runtime_conf.get("pio.process_id") is not None:
        return int(runtime_conf["pio.process_id"])
    return int(os.environ.get("PIO_PROCESS_ID", "0") or 0)


def strip_launch_conf(runtime_conf: dict | None) -> dict:
    """Drop launch-scoped keys before persisting runtime conf."""
    return {
        k: v for k, v in (runtime_conf or {}).items()
        if k not in LAUNCH_SCOPED_KEYS
    }


def init_distributed(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize the multi-host runtime (idempotent).

    Args fall back to ``PIO_COORDINATOR`` / ``PIO_NUM_PROCESSES`` /
    ``PIO_PROCESS_ID``. Returns True when running multi-process after the
    call, False for the single-process (no coordinator) case.
    """
    global _INITIALIZED
    coordinator = coordinator or os.environ.get("PIO_COORDINATOR")
    if not coordinator and not _INITIALIZED:
        return False
    import jax

    if _INITIALIZED:
        if coordinator:
            logger.warning(
                "distributed runtime already initialized; ignoring "
                "coordinator=%s", coordinator,
            )
        return jax.process_count() > 1
    num_processes = int(
        num_processes
        if num_processes is not None
        else os.environ.get("PIO_NUM_PROCESSES", "1")
    )
    process_id = int(
        process_id if process_id is not None else os.environ.get("PIO_PROCESS_ID", "0")
    )
    try:
        # cross-process collectives on the CPU backend need an explicit
        # transport on legacy (0.4.x) jax ("Multiprocess computations
        # aren't implemented on the CPU backend" otherwise); newer jax
        # selects gloo on its own. Must be set before backend init, which
        # initialize() below triggers.
        if jax.config.jax_platforms and "cpu" in str(jax.config.jax_platforms):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # unknown option on some versions: defaults are fine
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _INITIALIZED = True
    logger.info(
        "distributed runtime up: process %d/%d via %s",
        process_id, num_processes, coordinator,
    )
    return jax.process_count() > 1


def build_mesh(
    mesh_shape: list[int],
    axes: tuple[str, ...],
    dcn_mesh_shape: list[int] | None = None,
):
    """Build a Mesh over the global device list.

    ``mesh_shape`` is the per-slice (ICI) shape; a ``-1`` entry absorbs the
    remaining devices. ``dcn_mesh_shape``, when given, is the per-axis
    DCN replication factor (same rank; typically ``[num_slices, 1, ...]``):
    the global mesh axis sizes are the elementwise product and device order
    comes from ``mesh_utils.create_hybrid_device_mesh`` so ICI neighbors
    stay adjacent on the fast axes.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(mesh_shape) != len(axes):
        raise ValueError(
            f"mesh_shape {mesh_shape} and mesh_axes {axes} have different ranks"
        )
    if dcn_mesh_shape is not None:
        if len(dcn_mesh_shape) != len(axes):
            raise ValueError(
                f"dcn_mesh_shape {dcn_mesh_shape} and mesh_axes {axes} have "
                "different ranks"
            )
        from predictionio_tpu.utils.jax_compat import create_hybrid_device_mesh

        dcn_total = _prod(dcn_mesh_shape)
        if len(devices) % dcn_total:
            raise ValueError(
                f"dcn_mesh_shape {dcn_mesh_shape} (product {dcn_total}) does "
                f"not divide the {len(devices)}-device fleet"
            )
        resolved = _resolve_wildcard(mesh_shape, len(devices) // dcn_total)
        total = _prod(resolved) * dcn_total
        if total != len(devices):
            # create_hybrid_device_mesh requires the exact fleet; an under-
            # subscribed shape would die deep inside jax with no context
            raise ValueError(
                f"mesh shape {resolved} x dcn {dcn_mesh_shape} covers {total} "
                f"device(s) but the fleet has {len(devices)}; a hybrid mesh "
                "must use every device (use -1 wildcards to auto-fill)"
            )
        # TPU slices carry slice_index; CPU/virtual devices don't, so the
        # DCN granule degrades to the process there (the CI/test path)
        grid = create_hybrid_device_mesh(
            resolved,
            dcn_mesh_shape,
            devices=devices,
            process_is_granule=not hasattr(devices[0], "slice_index"),
        )
        mesh = Mesh(grid, axes)
        logger.info(
            "hybrid mesh: ici=%s x dcn=%s over %d %s device(s)",
            dict(zip(axes, resolved)), dcn_mesh_shape, grid.size,
            devices[0].platform,
        )
        return mesh

    resolved = _resolve_wildcard(mesh_shape, len(devices))
    total = _prod(resolved)
    if total > len(devices):
        raise ValueError(
            f"mesh shape {resolved} needs {total} devices, have {len(devices)}"
        )
    mesh = Mesh(np.array(devices[:total]).reshape(resolved), axes)
    logger.info(
        "mesh: %s over %d %s device(s)",
        dict(zip(axes, resolved)), total, devices[0].platform,
    )
    return mesh


def host_local_batch(mesh, spec, local_arrays):
    """Assemble per-process local batch shards into global sharded arrays.

    Each host passes the rows IT loaded (a pytree of numpy arrays); the
    result is a pytree of global jax.Arrays laid out per ``spec`` on
    ``mesh`` without any host ever holding the global batch. Single-process
    meshes degrade to a plain sharded device_put.
    """
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    put = lambda x: jax.make_array_from_process_local_data(sharding, x)
    return jax.tree_util.tree_map(put, local_arrays)


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _resolve_wildcard(shape: list[int], n_devices: int) -> list[int]:
    resolved = [int(s) for s in shape]
    if resolved.count(-1) > 1:
        raise ValueError(f"mesh shape {shape} has more than one -1")
    if -1 in resolved:
        known = _prod(s for s in resolved if s != -1)
        resolved[resolved.index(-1)] = max(n_devices // known, 1)
    return resolved
