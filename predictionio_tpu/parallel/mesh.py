"""Mesh + sharding helpers shared by algorithms.

Conventions: axes ``("data", "model")``. Batch-parallel arrays shard their
leading dim over ``data``; model-parallel factor blocks shard over ``model``;
replicated arrays use an empty PartitionSpec.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from predictionio_tpu.utils.jax_compat import shard_map


def cached_by_mesh(maxsize: int = 32):
    """LRU cache for ``build(mesh, *static_args)`` program builders.

    ``jax.sharding.Mesh`` hashes BY VALUE (axis names + devices + shape +
    axis types), so an lru_cache keyed on the mesh deduplicates the fresh-
    but-equivalent meshes that long-lived serving/eval processes construct
    per retrain: one compiled program per distinct topology. The retention
    this implies is deliberate and bounded -- at most ``maxsize`` compiled
    programs (plus the tiny Mesh keys; devices are process-lifetime
    singletons anyway), evicted LRU. Thread-safe (lru_cache's internal
    lock; serving is a threaded HTTP server)."""
    return functools.lru_cache(maxsize=maxsize)


def local_mesh(data: int | None = None, model: int = 1) -> Mesh:
    """Mesh over the local devices; ``data=None`` takes all remaining."""
    devices = jax.devices()
    if data is None:
        data = len(devices) // model
    grid = np.array(devices[: data * model]).reshape(data, model)
    return Mesh(grid, ("data", "model"))


def require_axes(mesh: Mesh, axes, what: str) -> None:
    """Fail fast when a spec/collective axis name is not bound by this
    mesh. The runtime twin of ``pio check``'s S001/S002: today every
    mesh is ``local_mesh()``'s ``("data", "model")`` singleton, but the
    MPMD slice directions mint per-engine meshes with their own axis
    sets -- an eager ValueError naming both sides beats jax's late
    unbound-axis-name error deep inside a trace."""
    missing = [a for a in axes if a is not None and a not in mesh.axis_names]
    if missing:
        raise ValueError(
            f"{what}: axis name(s) {missing} not bound by this mesh "
            f"(axes={list(mesh.axis_names)}) -- build the spec from the "
            f"mesh's own axis names or thread the intended mesh here"
        )


def row_sharded(mesh: Mesh, axis: str = "data") -> NamedSharding:
    require_axes(mesh, (axis,), "row_sharded")
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def fetch_global(arr) -> np.ndarray:
    """Host copy of a (possibly multi-process) sharded array: allgathers
    across processes when local devices cannot address every shard."""
    if jax.process_count() > 1 and not arr.is_fully_replicated:
        from predictionio_tpu.utils.jax_compat import process_allgather

        return np.asarray(process_allgather(arr, tiled=True))
    return np.asarray(arr)


def put_global(a, sharding: NamedSharding):
    """Place a host array every process holds IN FULL (each read the same
    event store / initialized from the same seed) onto a possibly
    multi-process sharding: each process contributes exactly its
    addressable shards. The callback form handles ANY spec -- row shards,
    model-axis parameter shards, replicated, and meshes where a sharded
    axis does not span processes (per-process slicing by rank would feed
    those wrong-sized shards)."""
    if jax.process_count() == 1:
        return jax.device_put(a, sharding)
    host = np.asarray(a)
    return jax.make_array_from_callback(host.shape, sharding, lambda idx: host[idx])


def shard_examples(mesh: Mesh | None, x, y):
    """Shared dp entry for the full-batch trainers (NB, LogReg).

    Returns ``(x_j, y_j, w_j, mesh)``: examples row-sharded over ``data``
    with zero-weight padding rows (so weighted means and masked counts stay
    exact when n does not divide the axis), or plain host arrays --
    ``mesh`` comes back None -- when no mesh was given or it has no
    ``data`` axis (custom-axis configs train unsharded rather than crash).
    """
    import jax.numpy as jnp

    weights = np.ones(np.asarray(x).shape[0], dtype=np.float32)
    if mesh is not None and "data" not in mesh.axis_names:
        mesh = None
    if mesh is None:
        return jnp.asarray(x), jnp.asarray(y), jnp.asarray(weights), None
    x_j, y_j, w_j = shard_rows(
        mesh, np.asarray(x, np.float32), np.asarray(y), weights
    )
    return x_j, y_j, w_j, mesh


def check_steps_ran(steps: int, n_examples: int, data_axis_size: int, what: str):
    """Raise when a training loop completed without a single step: the data
    can't fill even one batch across the data axis (shared guard for the
    sharded model templates)."""
    if steps == 0:
        raise ValueError(
            f"no training steps ran: {n_examples} {what}(s) cannot fill even "
            f"one batch across the {data_axis_size}-way data axis -- use "
            "fewer devices or more data"
        )


def seq_parallel_shard_map(body, mesh: Mesh, axis_name: str, check_vma: bool = True):
    """shard_map wrapper shared by the sequence-parallel attention
    strategies: q,k,v [B, T, H, D] shard as (data?, axis_name, None, None),
    the [B, T] key mask as (data?, axis_name). Keeps ring and Ulysses on one
    contract (mask defaulting and batch-axis resolution live in the callers'
    shared entry, this is the spec plumbing).

    ``check_vma=False`` is needed when the body runs a pallas kernel in
    interpret mode (the interpreter's internal index constants trip the
    varying-mesh-axes checker); bodies relying on ``pcast`` must keep it on.
    """
    from jax.sharding import PartitionSpec as P

    require_axes(mesh, (axis_name,), "seq_parallel_shard_map")
    batch_axis = "data" if "data" in mesh.axis_names else None
    spec = P(batch_axis, axis_name, None, None)
    mspec = P(batch_axis, axis_name)
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec, mspec), out_specs=spec,
        check_vma=check_vma,
    )


def shard_rows(mesh: Mesh, *arrays, axis: str = "data"):
    """Pad rows to the axis size and device_put sharded on the leading dim."""
    require_axes(mesh, (axis,), "shard_rows")
    n_shards = mesh.shape[axis]
    out = []
    for arr in arrays:
        rows = arr.shape[0]
        padded = -(-rows // n_shards) * n_shards
        if padded != rows:
            pad_width = [(0, padded - rows)] + [(0, 0)] * (arr.ndim - 1)
            arr = np.pad(arr, pad_width)
        out.append(jax.device_put(arr, row_sharded(mesh, axis)))
    return out[0] if len(out) == 1 else tuple(out)
