"""Mesh + sharding helpers shared by algorithms.

Conventions: axes ``("data", "model")``. Batch-parallel arrays shard their
leading dim over ``data``; model-parallel factor blocks shard over ``model``;
replicated arrays use an empty PartitionSpec.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def local_mesh(data: int | None = None, model: int = 1) -> Mesh:
    """Mesh over the local devices; ``data=None`` takes all remaining."""
    devices = jax.devices()
    if data is None:
        data = len(devices) // model
    grid = np.array(devices[: data * model]).reshape(data, model)
    return Mesh(grid, ("data", "model"))


def row_sharded(mesh: Mesh, axis: str = "data") -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_rows(mesh: Mesh, *arrays, axis: str = "data"):
    """Pad rows to the axis size and device_put sharded on the leading dim."""
    n_shards = mesh.shape[axis]
    out = []
    for arr in arrays:
        rows = arr.shape[0]
        padded = -(-rows // n_shards) * n_shards
        if padded != rows:
            pad_width = [(0, padded - rows)] + [(0, 0)] * (arr.ndim - 1)
            arr = np.pad(arr, pad_width)
        out.append(jax.device_put(arr, row_sharded(mesh, axis)))
    return out[0] if len(out) == 1 else tuple(out)
