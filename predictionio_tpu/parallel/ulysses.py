"""Ulysses-style sequence parallelism: all-to-all head-scatter attention.

The second long-context strategy next to ``parallel.ring_attention`` (the
reference has no sequence models at all -- SURVEY.md section 5.7 -- so both
are new TPU-native capability). Where ring attention keeps queries resident
and rotates K/V blocks around the ICI ring (sp hops of [B, T/sp] blocks),
Ulysses re-shards ONCE per attention call: an all-to-all swaps the sharded
dimension from sequence to heads, every chip computes exact full-sequence
attention for its head group, and a second all-to-all swaps back.

Trade-off (why both exist): Ulysses moves 3 x [B, T, H/sp, D] per chip in
two fused all-to-alls -- cheaper than the ring's sp ppermute hops when the
head count divides nicely over the axis -- but caps the sequence axis at the
number of heads and materializes full-[T] K/V per chip. Ring has no head
constraint and never holds more than one remote block. Templates pick via
``seqParallel: "ring" | "ulysses"``.

All-to-alls ride ICI inside ``shard_map``; attention math reuses
``plain_attention`` so both strategies share one reference numerics path.
"""

from __future__ import annotations

import functools

import jax

from predictionio_tpu.parallel.mesh import seq_parallel_shard_map
from predictionio_tpu.parallel.ring_attention import plain_attention


def _ulysses_local(
    q, k, v, kv_mask, *, axis_name: str, causal: bool, sm_scale,
    use_flash: bool = False,
):
    """Per-shard body. Shapes: q,k,v [B, Tl, H, D]; kv_mask [B, Tl].

    all_to_all #1: shard heads, gather sequence  -> [B, T, H/sp, D]
    local attention over the full sequence for H/sp heads (flash kernel
    when requested: the full-[T] score matrix is exactly what Ulysses
    would otherwise materialize per chip)
    all_to_all #2: shard sequence, gather heads  -> [B, Tl, H, D]
    """
    scatter = lambda x: jax.lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    q_h, k_h, v_h = scatter(q), scatter(k), scatter(v)
    mask_full = jax.lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
    if use_flash:
        from predictionio_tpu.ops.flash_attention import flash_attention

        out = flash_attention(
            q_h, k_h, v_h, mask_full, causal=causal, sm_scale=sm_scale,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        out = plain_attention(
            q_h, k_h, v_h, causal=causal, mask=mask_full, sm_scale=sm_scale
        )
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q,
    k,
    v,
    mesh,
    axis_name: str = "seq",
    causal: bool = True,
    mask=None,
    sm_scale: float | None = None,
    use_flash: bool = False,
):
    """Attention with the sequence dim sharded over ``mesh[axis_name]``.

    Same contract as ``ring_attention``: global shapes q,k,v [B, T, H, D]
    with T divisible by the axis size, optional [B, T] key validity mask,
    batch sharding over a ``data`` axis when the mesh has one. Additional
    constraint: H must be divisible by the axis size (heads are the
    scattered dim).
    """
    import jax.numpy as jnp

    if mask is None:
        mask = jnp.ones(q.shape[:2], bool)
    axis_size = mesh.shape[axis_name]
    h = q.shape[2]
    if h % axis_size:
        raise ValueError(
            f"ulysses needs num_heads ({h}) divisible by the '{axis_name}' "
            f"axis size ({axis_size}); use ring attention otherwise"
        )
    # flash-in-interpret (CPU tests) trips shard_map's vma checker on the
    # interpreter's internal index constants; this body never uses pcast,
    # so the check can be dropped exactly when that combination is active
    interpret_flash = use_flash and jax.default_backend() != "tpu"
    fn = seq_parallel_shard_map(
        functools.partial(
            _ulysses_local, axis_name=axis_name, causal=causal,
            sm_scale=sm_scale, use_flash=use_flash,
        ),
        mesh,
        axis_name,
        check_vma=not interpret_flash,
    )
    return fn(q, k, v, mask)
