"""predictionio_tpu: a TPU-native machine learning server.

A brand-new framework with the capabilities of Apache PredictionIO
(reference: remington-wpt/incubator-predictionio): an event-ingestion REST
server with ``$set/$unset/$delete`` entity-property semantics, a DASE engine
lifecycle (DataSource -> Preparator -> Algorithm(s) -> Serving, plus
Evaluation), a ``pio``-style CLI, pluggable metadata/event/model storage, and
a low-latency query server -- with the Spark/MLlib execution layer replaced
by JAX/XLA on a TPU device mesh (pjit/shard_map + Pallas kernels).

Layer map (mirrors SURVEY.md section 1; reference paths cited per-module):

- ``predictionio_tpu.data``        -- L2 event model + L1 storage backends
- ``predictionio_tpu.data.api``    -- L5 Event Server (REST ingestion)
- ``predictionio_tpu.controller``  -- L3 DASE controller API
- ``predictionio_tpu.workflow``    -- L4 train/eval/deploy lifecycle
- ``predictionio_tpu.tools``       -- L6 CLI + ops tooling
- ``predictionio_tpu.ops``         -- TPU compute kernels (segment/ragged/pallas)
- ``predictionio_tpu.parallel``    -- mesh/sharding/collectives (replaces Spark L0)
- ``predictionio_tpu.models``      -- engine templates (ALS, classification,
                                      similar-product, universal recommender, NCF)
"""

from predictionio_tpu.version import __version__

__all__ = ["__version__"]
