"""Native host-side kernels (C++, ctypes-loaded, numpy fallback).

The compute path is JAX/XLA on device; this package is the native runtime
around it -- host-side data packing that sits between the event store and
``jax.device_put``. The library is compiled from the in-tree C++ source with
g++ on first use and cached; every caller must handle ``load() -> None`` and
fall back to the numpy implementation (no hard dependency on a toolchain).

Env knobs:
- ``PIO_NATIVE=0`` disables native kernels entirely (forces numpy paths);
- ``PIO_NATIVE_CACHE`` overrides the build cache dir (default: a ``_build``
  dir next to this file).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["csr_pack.cpp"]

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _cache_dir() -> str:
    return os.environ.get("PIO_NATIVE_CACHE", os.path.join(_HERE, "_build"))


def _source_digest() -> str:
    h = hashlib.sha256()
    for name in _SOURCES:
        with open(os.path.join(_HERE, name), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _build() -> str | None:
    """Compile the shared library if its cached copy is stale; returns path."""
    cache = _cache_dir()
    lib_path = os.path.join(cache, f"libpio_native_{_source_digest()}.so")
    if os.path.exists(lib_path):
        return lib_path
    sources = [os.path.join(_HERE, s) for s in _SOURCES]
    tmp_path = None
    try:
        # unwritable cache dir (read-only install) must mean numpy fallback,
        # not a crash, so dir/tempfile setup sits inside the try too
        os.makedirs(cache, exist_ok=True)
        # build to a temp name, then atomic-rename: concurrent builders race
        # benignly instead of loading a half-written .so
        fd, tmp_path = tempfile.mkstemp(suffix=".so", dir=cache)
        os.close(fd)
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp_path, *sources]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.rename(tmp_path, lib_path)
        return lib_path
    except (subprocess.SubprocessError, OSError):
        if tmp_path is not None:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
        return None


def load() -> ctypes.CDLL | None:
    """The native library, building it on first call; None when unavailable."""
    global _lib, _load_failed
    if os.environ.get("PIO_NATIVE", "1") == "0":
        return None
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        lib_path = _build()
        if lib_path is None:
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            _load_failed = True
            return None
        import numpy as np
        from numpy.ctypeslib import ndpointer

        lib.pack_padded_csr.restype = ctypes.c_int64
        lib.pack_padded_csr.argtypes = [
            ndpointer(np.int64, flags="C_CONTIGUOUS"),   # rows
            ndpointer(np.int64, flags="C_CONTIGUOUS"),   # cols
            ndpointer(np.float32, flags="C_CONTIGUOUS"), # vals
            ctypes.c_void_p,                             # times (nullable)
            ctypes.c_int64,                              # n
            ctypes.c_int64,                              # num_rows
            ctypes.c_int64,                              # length
            ctypes.c_int64,                              # padded_rows
            ctypes.c_int64,                              # num_cols
            ndpointer(np.int32, flags="C_CONTIGUOUS"),   # out_indices
            ndpointer(np.float32, flags="C_CONTIGUOUS"), # out_values
            ndpointer(np.float32, flags="C_CONTIGUOUS"), # out_mask
        ]
        _lib = lib
        return _lib


def pack_padded_csr_native(
    rows, cols, vals, times, num_rows, length, padded_rows, num_cols,
    indices, values, mask,
) -> int | None:
    """Run the native pack; returns truncated count, or None if unavailable
    or the kernel rejected the input (caller falls back to numpy)."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    if cols.size != rows.size or vals.size != rows.size:
        return None  # numpy fallback raises the proper shape error
    times_arg = None
    if times is not None:
        times = np.asarray(times)
        if times.size != rows.size:
            return None
        # float64 preserves float-timestamp ordering exactly as the numpy
        # lexsort path sees it; integer epochs beyond 2^53 would collapse
        # adjacent values, so those fall back to the exact int64 lexsort
        if np.issubdtype(times.dtype, np.integer) and times.size:
            if np.abs(times.astype(np.float64)).max() >= 2.0**53:
                return None
        times = np.ascontiguousarray(times, dtype=np.float64)
        times_arg = times.ctypes.data_as(ctypes.c_void_p)
    truncated = lib.pack_padded_csr(
        np.ascontiguousarray(rows, dtype=np.int64),
        np.ascontiguousarray(cols, dtype=np.int64),
        np.ascontiguousarray(vals, dtype=np.float32),
        times_arg,
        rows.size,
        num_rows,
        length,
        padded_rows,
        num_cols,
        indices,
        values,
        mask,
    )
    return None if truncated < 0 else int(truncated)
