// Host-side COO -> padded-CSR packing kernel.
//
// The TPU-native framework's "native layer" is the host<->device input
// pipeline (SURVEY.md section 2.9: the reference has no C++ of its own; its
// native substrate is the JVM/Spark stack this framework replaces). This
// kernel feeds the ALS/serving paths: 20M+ interaction triples must become
// static-shape padded blocks before every training run, and the numpy path
// pays an O(n log n) lexsort where a row-bucket counting sort is O(n).
//
// Semantics mirror ops/ragged.pack_padded_csr exactly:
//  - entries are grouped by row, ordered by (time asc, input order) when
//    times are given, else by input order (stable);
//  - rows longer than L keep their LAST L entries (most recent);
//  - padding slots keep indices == num_cols, values/mask == 0 (the caller
//    pre-fills the output arrays).
//
// Build: g++ -O3 -shared -fPIC -o libpio_native.so csr_pack.cpp

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Returns the number of truncated interactions, or -1 on invalid input.
// out_indices must be pre-filled with num_cols, out_values/out_mask with 0.
int64_t pack_padded_csr(
    const int64_t* rows,
    const int64_t* cols,
    const float* vals,
    const double* times,  // nullable; double so float timestamps order
                          // identically to the numpy lexsort path
    int64_t n,
    int64_t num_rows,
    int64_t length,        // padded row capacity L
    int64_t padded_rows,
    int64_t num_cols,
    int32_t* out_indices,  // [padded_rows, length]
    float* out_values,     // [padded_rows, length]
    float* out_mask        // [padded_rows, length]
) {
    if (n < 0 || num_rows <= 0 || length <= 0 || padded_rows < num_rows) {
        return -1;
    }
    // 1) per-row counts
    std::vector<int64_t> counts(static_cast<size_t>(num_rows) + 1, 0);
    for (int64_t i = 0; i < n; ++i) {
        int64_t r = rows[i];
        // reject out-of-range ids (cols too: silently remapping them would
        // diverge from the numpy path) -- caller falls back
        if (r < 0 || r >= num_rows) return -1;
        if (cols[i] < 0 || cols[i] >= num_cols) return -1;
        ++counts[static_cast<size_t>(r)];
    }
    // 2) exclusive prefix sum -> bucket offsets
    std::vector<int64_t> offsets(static_cast<size_t>(num_rows) + 1, 0);
    for (int64_t r = 0; r < num_rows; ++r) {
        offsets[static_cast<size_t>(r) + 1] =
            offsets[static_cast<size_t>(r)] + counts[static_cast<size_t>(r)];
    }
    // 3) stable scatter of entry ids into row buckets (counting sort pass)
    std::vector<int64_t> order(static_cast<size_t>(n));
    {
        std::vector<int64_t> cursor(offsets.begin(), offsets.end() - 1);
        for (int64_t i = 0; i < n; ++i) {
            order[static_cast<size_t>(cursor[static_cast<size_t>(rows[i])]++)] = i;
        }
    }
    // 4) within-row time order (stable: ties keep input order); skipped when
    //    no timestamps were provided, matching the numpy lexsort semantics
    if (times != nullptr) {
        for (int64_t r = 0; r < num_rows; ++r) {
            int64_t lo = offsets[static_cast<size_t>(r)];
            int64_t hi = offsets[static_cast<size_t>(r) + 1];
            if (hi - lo > 1) {
                std::stable_sort(
                    order.begin() + lo, order.begin() + hi,
                    [times](int64_t a, int64_t b) { return times[a] < times[b]; });
            }
        }
    }
    // 5) fill the padded blocks, keeping each row's last `length` entries
    int64_t truncated = 0;
    for (int64_t r = 0; r < num_rows; ++r) {
        int64_t lo = offsets[static_cast<size_t>(r)];
        int64_t hi = offsets[static_cast<size_t>(r) + 1];
        int64_t count = hi - lo;
        int64_t drop = count > length ? count - length : 0;
        truncated += drop;
        int64_t base = r * length;
        for (int64_t k = drop; k < count; ++k) {
            int64_t src = order[static_cast<size_t>(lo + k)];
            int64_t dst = base + (k - drop);
            out_indices[dst] = static_cast<int32_t>(cols[src]);
            out_values[dst] = vals[src];
            out_mask[dst] = 1.0f;
        }
    }
    return truncated;
}

}  // extern "C"
