"""Batch predict: offline bulk scoring from a query file.

Behavioral model: reference ``core/.../workflow/BatchPredict.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.3 #26,
v0.13+): JSON-lines queries in, JSON-lines predictions out, through the
deployed-equivalent model chain.
"""

from __future__ import annotations

import json

from predictionio_tpu.data import storage
from predictionio_tpu.workflow.context import RuntimeContext
from predictionio_tpu.workflow.core_workflow import (
    engine_params_from_instance,
    resolve_engine_instance,
)
from predictionio_tpu.workflow.json_extractor import EngineVariant, build_engine


def run_batch_predict(
    variant: EngineVariant,
    input_path: str,
    output_path: str,
    instance_id: str | None = None,
) -> int:
    """Score every JSON-lines query in ``input_path``; returns count."""
    engine = build_engine(variant)
    instance = resolve_engine_instance(variant, instance_id)
    engine_params = engine_params_from_instance(instance)
    blob = storage.get_model_data_models().get(instance.id)
    ctx = RuntimeContext(instance.runtime_conf)
    models = engine.prepare_deploy(
        ctx, engine_params, instance.id, blob.models if blob else None
    )
    algorithms = engine._algorithms(engine_params)
    serving = engine.serving(engine_params)

    count = 0
    with open(input_path) as fin, open(output_path, "w") as fout:
        for line in fin:
            line = line.strip()
            if not line:
                continue
            query_obj = json.loads(line)
            predictions = [
                a.predict(m, a.query_from_json(query_obj))
                for a, m in zip(algorithms, models)
            ]
            result = serving.serve(algorithms[0].query_from_json(query_obj), predictions)
            result_json = algorithms[0].result_to_json(result)
            fout.write(json.dumps({"query": query_obj, "prediction": result_json}) + "\n")
            count += 1
    return count
