"""Batch predict: offline bulk scoring from a query file.

Behavioral model: reference ``core/.../workflow/BatchPredict.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.3 #26,
v0.13+): JSON-lines queries in, JSON-lines predictions out, through the
deployed-equivalent model chain.
"""

from __future__ import annotations

import json
import logging

from predictionio_tpu.data import storage
from predictionio_tpu.workflow.context import RuntimeContext
from predictionio_tpu.workflow.core_workflow import (
    engine_params_from_instance,
    resolve_engine_instance,
)
from predictionio_tpu.workflow.json_extractor import EngineVariant, build_engine

logger = logging.getLogger("pio.batchpredict")

#: queries scored per batch_predict call (bounds the [chunk, items] score
#: matrix a vectorized algorithm materializes)
_CHUNK = 4096


def run_batch_predict(
    variant: EngineVariant,
    input_path: str,
    output_path: str,
    instance_id: str | None = None,
) -> int:
    """Score every JSON-lines query in ``input_path``; returns count."""
    engine = build_engine(variant)
    instance = resolve_engine_instance(variant, instance_id)
    engine_params = engine_params_from_instance(instance)
    blob = storage.get_model_data_models().get(instance.id)
    ctx = RuntimeContext(instance.runtime_conf)
    models = engine.prepare_deploy(
        ctx, engine_params, instance.id, blob.models if blob else None
    )
    algorithms = engine._algorithms(engine_params)
    serving = engine.serving(engine_params)

    count = 0
    with open(input_path) as fin, open(output_path, "w") as fout:

        def score_one(obj) -> dict:
            predictions = [
                a.predict(m, a.query_from_json(obj))
                for a, m in zip(algorithms, models)
            ]
            result = serving.serve(algorithms[0].query_from_json(obj), predictions)
            return {"query": obj, "prediction": algorithms[0].result_to_json(result)}

        def flush(chunk_objs: list) -> None:
            nonlocal count
            if not chunk_objs:
                return
            # route through the batch_predict hook (reference
            # batchPredictBase): algorithms with a vectorized override (ALS
            # scores a chunk as ONE matmul) get their batch shape; the
            # default falls back to looped predict
            try:
                per_algo = []
                for a, m in zip(algorithms, models):
                    queries = [
                        (i, a.query_from_json(obj))
                        for i, obj in enumerate(chunk_objs)
                    ]
                    per_algo.append(dict(a.batch_predict(m, queries)))
                rows = []
                for i, obj in enumerate(chunk_objs):
                    predictions = [results[i] for results in per_algo]
                    result = serving.serve(
                        algorithms[0].query_from_json(obj), predictions
                    )
                    rows.append(
                        {"query": obj, "prediction": algorithms[0].result_to_json(result)}
                    )
            except Exception:
                # one malformed query must not discard the chunk's other
                # results: degrade to per-query scoring (slow, but only
                # chunks containing a failing query pay), recording an
                # error row for each query that fails. Log the trigger --
                # a SYSTEMIC failure (model regression, corrupt blob) would
                # otherwise masquerade as per-row input errors
                logger.warning(
                    "batch scoring failed for a %d-query chunk; rescoring"
                    " per query",
                    len(chunk_objs),
                    exc_info=True,
                )
                rows = []
                for obj in chunk_objs:
                    try:
                        rows.append(score_one(obj))
                    except Exception as exc:
                        rows.append({"query": obj, "error": str(exc)})
            for row in rows:
                fout.write(json.dumps(row) + "\n")
                count += 1
            chunk_objs.clear()

        chunk: list = []
        for line in fin:
            line = line.strip()
            if not line:
                continue
            chunk.append(json.loads(line))
            if len(chunk) >= _CHUNK:
                flush(chunk)
        flush(chunk)
    return count
