"""Step checkpointing: preemption safety for long TPU training runs.

The reference has NO mid-training checkpoints -- Spark lineage was its
failure story and models persist only on completion (SURVEY.md section 5.3/
5.4). On TPU, preemption safety must come from explicit step checkpoints:
orbax writes ``{step, params, opt_state}``; ``latest_step`` lets a re-run
``pio train`` resume instead of restarting.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any

logger = logging.getLogger("pio.checkpoint")


def _checkpoint_base(base_dir: str | None = None) -> str:
    return base_dir or os.path.join(
        os.environ.get("PIO_FS_BASEDIR", os.path.expanduser("~/.pio_store")),
        "checkpoints",
    )


class RunLockHeld(RuntimeError):
    """Another live process owns this run's checkpoint namespace."""

    def __init__(self, run_key: str, pid: int):
        super().__init__(
            f"run {run_key!r} is locked by live pid {pid}: another train with"
            " the same variant+params is running. Refusing to start (a fresh"
            " train would delete its live checkpoints; --resume would adopt a"
            " RUNNING instance). Wait for it or kill it first."
        )
        self.pid = pid


class RunLock:
    """``flock``-based lockfile serializing trains that share one run_key.

    ``run_key`` is a pure function of variant+params (core_workflow), so two
    concurrent identical trains would share a checkpoint dir: the second's
    ``fresh`` wipe deletes the first's live checkpoints, and ``--resume``
    would adopt a still-RUNNING instance.

    Why flock and not a pid file: the kernel drops the lock the instant the
    holder dies (no stale-pid liveness polling, which is both racy --
    two waiters can each judge the lock stale and both 'take over' -- and
    wrong across users, where ``kill(pid, 0)`` raises EPERM for a live
    process). The pid written into the file is diagnostic only. Single-host
    by design; multi-host pods isolate via per-host PIO_FS_BASEDIR or run
    one train per coordinator.
    """

    def __init__(self, run_key: str, base_dir: str | None = None):
        base = _checkpoint_base(base_dir)
        os.makedirs(base, exist_ok=True)
        self.run_key = run_key
        self.path = os.path.join(base, f"{run_key}.lock")
        self._fd: int | None = None

    def acquire(self) -> "RunLock":
        import fcntl

        while True:
            fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except BlockingIOError:
                try:
                    pid = int(os.read(fd, 32).decode().strip() or -1)
                except (OSError, ValueError):
                    pid = -1
                os.close(fd)
                raise RunLockHeld(self.run_key, pid) from None
            except BaseException:
                os.close(fd)
                raise
            # release() unlinks the path, so the inode we just locked may
            # already be orphaned (opened before a concurrent release):
            # verify fd and path still agree, else retry on the fresh file
            try:
                if os.fstat(fd).st_ino == os.stat(self.path).st_ino:
                    break
            except FileNotFoundError:
                pass
            os.close(fd)
        os.ftruncate(fd, 0)
        os.write(fd, str(os.getpid()).encode())
        self._fd = fd
        return self

    def release(self) -> None:
        if self._fd is not None:
            try:
                os.unlink(self.path)
            except FileNotFoundError:
                pass
            os.close(self._fd)  # closing the fd drops the flock
            self._fd = None

    def __enter__(self) -> "RunLock":
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class CheckpointManager:
    """Thin orbax wrapper keyed by a stable run key.

    ``fresh=True`` (a non-resume train) deletes any existing checkpoints
    under the key first, so stale checkpoints from an earlier run with the
    same params never short-circuit a from-scratch retrain.
    """

    def __init__(
        self,
        run_id: str,
        base_dir: str | None = None,
        max_to_keep: int = 3,
        fresh: bool = False,
    ):
        self._max_to_keep = max_to_keep
        self.path = os.path.abspath(
            os.path.join(_checkpoint_base(base_dir), run_id)
        )
        if fresh and os.path.isdir(self.path):
            import shutil

            shutil.rmtree(self.path)
        self._open_manager()

    def _open_manager(self) -> None:
        import orbax.checkpoint as ocp

        os.makedirs(self.path, exist_ok=True)
        option_kwargs: dict = {}
        import jax

        if jax.process_count() > 1:
            # only rank 0 holds a manager (context.checkpoint_manager);
            # without this, orbax's construction/save/close barriers wait
            # on ALL jax processes and rank 0 deadlocks. orbax refuses
            # create=True with active_processes -- the makedirs above
            # already created the root
            try:
                option_kwargs["multiprocessing_options"] = (
                    ocp.options.MultiprocessingOptions(
                        active_processes={0}, primary_host=0
                    )
                )
                option_kwargs["create"] = False
            except (AttributeError, TypeError):
                # older/newer orbax API shape: falling through here builds
                # the ALL-process manager, which deadlocks rank 0 in a
                # multi-process train -- make the cause visible first
                logger.warning(
                    "this orbax version does not support rank-0-only"
                    " checkpointing options; multi-process checkpointing"
                    " may hang",
                    exc_info=True,
                )
        self._manager = ocp.CheckpointManager(
            self.path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._max_to_keep, **option_kwargs
            ),
        )

    def reset(self) -> None:
        """Discard every step + the meta sidecar (e.g. on a dataset-
        fingerprint mismatch: stale factors must not pad/misalign into a
        changed dataset)."""
        import shutil

        self._manager.close()
        shutil.rmtree(self.path, ignore_errors=True)
        self._open_manager()

    # -- meta sidecar: small JSON facts checked BEFORE array restore --------
    # (orbax restore needs a shape-matching template, so shape-invalidating
    # facts like the dataset fingerprint cannot live inside the step state)
    @property
    def _meta_path(self) -> str:
        return os.path.join(self.path, "pio_meta.json")

    def write_meta(self, meta: dict) -> None:
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
            f.flush()
            # the sidecar gates whether a multi-hour train resumes or
            # restarts; rename gives atomicity, only fsync gives the
            # bytes durability (tmp+fsync+rename, pio check R003)
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path)

    def read_meta(self) -> dict | None:
        try:
            with open(self._meta_path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def save(self, step: int, state: Any) -> None:
        import orbax.checkpoint as ocp

        self._manager.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> int | None:
        return self._manager.latest_step()

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        import orbax.checkpoint as ocp

        step = step if step is not None else self._manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.path}")
        return self._manager.restore(
            step, args=ocp.args.StandardRestore(state_template)
        )

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()


def clear_run_checkpoints(run_key: str, base_dir: str | None = None) -> None:
    """Delete every algorithm's checkpoints for a run key (called after a
    COMPLETED train: the model blob is persisted, step checkpoints are dead
    weight -- and must not be resumable into a later retrain)."""
    import glob
    import shutil

    base = _checkpoint_base(base_dir)
    for path in glob.glob(os.path.join(base, f"*-{run_key}")):
        shutil.rmtree(path, ignore_errors=True)
