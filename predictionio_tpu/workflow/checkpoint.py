"""Step checkpointing: preemption safety for long TPU training runs.

The reference has NO mid-training checkpoints -- Spark lineage was its
failure story and models persist only on completion (SURVEY.md section 5.3/
5.4). On TPU, preemption safety must come from explicit step checkpoints:
orbax writes ``{step, params, opt_state}``; ``latest_step`` lets a re-run
``pio train`` resume instead of restarting.
"""

from __future__ import annotations

import logging
import os
from typing import Any

logger = logging.getLogger("pio.checkpoint")


class CheckpointManager:
    """Thin orbax wrapper keyed by engine-instance/run id."""

    def __init__(self, run_id: str, base_dir: str | None = None, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        base = base_dir or os.path.join(
            os.environ.get("PIO_FS_BASEDIR", os.path.expanduser("~/.pio_store")),
            "checkpoints",
        )
        self.path = os.path.abspath(os.path.join(base, run_id))
        os.makedirs(self.path, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.path,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any) -> None:
        import orbax.checkpoint as ocp

        self._manager.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> int | None:
        return self._manager.latest_step()

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        import orbax.checkpoint as ocp

        step = step if step is not None else self._manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.path}")
        return self._manager.restore(
            step, args=ocp.args.StandardRestore(state_template)
        )

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()
