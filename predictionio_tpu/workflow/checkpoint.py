"""Step checkpointing: preemption safety for long TPU training runs.

The reference has NO mid-training checkpoints -- Spark lineage was its
failure story and models persist only on completion (SURVEY.md section 5.3/
5.4). On TPU, preemption safety must come from explicit step checkpoints:
orbax writes ``{step, params, opt_state}``; ``latest_step`` lets a re-run
``pio train`` resume instead of restarting.
"""

from __future__ import annotations

import logging
import os
from typing import Any

logger = logging.getLogger("pio.checkpoint")


class CheckpointManager:
    """Thin orbax wrapper keyed by a stable run key.

    ``fresh=True`` (a non-resume train) deletes any existing checkpoints
    under the key first, so stale checkpoints from an earlier run with the
    same params never short-circuit a from-scratch retrain.
    """

    def __init__(
        self,
        run_id: str,
        base_dir: str | None = None,
        max_to_keep: int = 3,
        fresh: bool = False,
    ):
        import orbax.checkpoint as ocp

        base = base_dir or os.path.join(
            os.environ.get("PIO_FS_BASEDIR", os.path.expanduser("~/.pio_store")),
            "checkpoints",
        )
        self.path = os.path.abspath(os.path.join(base, run_id))
        if fresh and os.path.isdir(self.path):
            import shutil

            shutil.rmtree(self.path)
        os.makedirs(self.path, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.path,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any) -> None:
        import orbax.checkpoint as ocp

        self._manager.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> int | None:
        return self._manager.latest_step()

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        import orbax.checkpoint as ocp

        step = step if step is not None else self._manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.path}")
        return self._manager.restore(
            step, args=ocp.args.StandardRestore(state_template)
        )

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()


def clear_run_checkpoints(run_key: str, base_dir: str | None = None) -> None:
    """Delete every algorithm's checkpoints for a run key (called after a
    COMPLETED train: the model blob is persisted, step checkpoints are dead
    weight -- and must not be resumable into a later retrain)."""
    import glob
    import shutil

    base = base_dir or os.path.join(
        os.environ.get("PIO_FS_BASEDIR", os.path.expanduser("~/.pio_store")),
        "checkpoints",
    )
    for path in glob.glob(os.path.join(base, f"*-{run_key}")):
        shutil.rmtree(path, ignore_errors=True)
