"""Query Server: low-latency REST serving of a deployed engine.

Behavioral model: reference ``core/.../workflow/CreateServer.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.3 #25, section
3.2 call stack). Contract kept:

- ``POST /queries.json``: free-form JSON query -> per-algorithm
  ``predict`` -> ``serving.serve`` -> JSON PredictedResult (+ ``prId`` echo
  when the feedback loop is on)
- ``GET /``: info/status page (JSON here rather than HTML)
- ``GET /reload``: re-resolve the latest COMPLETED instance and hot-swap
  models
- ``POST /stop``: shut the server down (how ``pio undeploy`` works)
- plugin hook points: output blockers / output sniffers
  (``EngineServerPlugin`` parity)
- optional feedback loop: writes query/prediction events back to the Event
  Server (``--feedback --event-server-ip/port --accesskey``)

Default port 8000. Serving stays off the training mesh: predict calls are
host-side (factor caches) or single-chip jitted functions prepared at load
time -- the <5 ms p50 path (SURVEY.md section 7.3).

Concurrent requests are coalesced into padded micro-batches
(``workflow/microbatch``): request threads park on futures while one
flusher drives the engines' vectorized ``batch_predict`` paths, so the
scorer sees batch sizes that grow with load instead of always 1. The
single-request response surface is preserved byte-for-byte; disable with
``--batch-window-ms 0``.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import threading
import time as _time
import uuid
from concurrent.futures import TimeoutError as _FutureTimeout
from dataclasses import dataclass
from typing import Any

from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.obs.trace import (
    NULL_SPAN,
    SAMPLED_OUT_ROOT,
    format_traceparent,
)
from predictionio_tpu.utils.http import (
    Request,
    Response,
    ServiceThread,
    instrumented_router,
    make_server,
)
from predictionio_tpu.workflow.context import RuntimeContext
from predictionio_tpu.workflow.microbatch import (
    BatchConfig,
    BatcherStopped,
    MicroBatcher,
)
from predictionio_tpu.workflow.core_workflow import (
    engine_params_from_instance,
    resolve_engine_instance,
)
from predictionio_tpu.workflow.json_extractor import EngineVariant, build_engine

logger = logging.getLogger("pio.server")

DEFAULT_PORT = 8000


class EngineServerPlugin:
    """Output blocker/sniffer hook points (reference EngineServerPlugin)."""

    def output_blocker(self, query: Any, prediction: Any) -> None:
        pass

    def output_sniffer(self, query: Any, prediction: Any) -> None:
        pass


class ServerRejection(Exception):
    def __init__(self, message: str, status: int = 403):
        super().__init__(message)
        self.status = status


@dataclass
class FeedbackConfig:
    event_server_url: str
    access_key: str


class QueryService:
    """Holds the deployed engine state; hot-swappable via /reload."""

    def __init__(
        self,
        variant: EngineVariant,
        engine: Engine | None = None,
        instance_id: str | None = None,
        feedback: FeedbackConfig | None = None,
        plugins: list[EngineServerPlugin] | None = None,
        batching: BatchConfig | None = None,
        tracing: bool | None = None,
        trace_sample: float | None = None,
        slow_query_ms: float | None = None,
        extra_metrics_snapshots=None,
        model_version: int | None = None,
        registry=None,
        shard: int | None = None,
        num_shards: int = 1,
    ):
        self.variant = variant
        self.engine = engine or build_engine(variant)
        self.requested_instance_id = instance_id
        self.requested_model_version = model_version
        self._registry = registry  # lazily resolved from the variant
        #: sharded serving fabric identity: this scorer owns the user rows
        #: whose ``shardmap.shard_of(user) == shard`` out of ``num_shards``
        #: partitions (item-side and replicated state stay whole). A plain
        #: deploy is shard None / num_shards 1 and loads full models.
        self.shard = shard
        self.num_shards = int(num_shards or 1)
        if self.num_shards > 1 and not (
            isinstance(shard, int) and 0 <= shard < self.num_shards
        ):
            raise ValueError(
                f"shard must be in [0, {self.num_shards}) when"
                f" num_shards={self.num_shards}, got {shard!r}"
            )
        self.feedback = feedback
        self.plugins = list(plugins or [])
        self.batching = BatchConfig() if batching is None else batching
        #: set by the multi-process tier: {"workers": N, ...} for the info
        #: page (``pio top``/operators see the process model at a glance)
        self.frontend_info: dict | None = None
        #: set by the multi-process tier: the scorer bridge's
        #: ``wakeup_stats`` callable; the /metrics mirror turns it into
        #: the wakeup-budget gauges (``pio_scorer_wakeups_per_request``,
        #: ``pio_scorer_dispatch_threads``)
        self.scorer_stats = None
        #: measured future-park wakeups: sync ring dispatches that had to
        #: block a dispatcher thread on the batcher future (the async
        #: fast path never parks). Plain int: += is GIL-atomic enough for
        #: a telemetry counter
        self._future_parks = 0
        #: async fast-path timeout backstop: same budget as the sync
        #: path's bounded future wait (window + execution allowance); a
        #: wedged batch answers 503 instead of holding admission permits
        #: forever. Enforced by a lazy 1 Hz watchdog thread.
        self._async_timeout_s = (
            self.batching.window_ms / 1000.0 + 30.0
            if self.batching.enabled else 30.0
        )
        self._async_lock = threading.Lock()
        #: in-flight async queries: dicts with future/request/span/t0/
        #: on_done/deadline/claimed; ``claimed`` is the exactly-once gate
        #: between the future callback and the watchdog's 503. Entries
        #: leave the list at claim time, so it only ever holds truly
        #: in-flight requests (bounded by the bridge's admission limit).
        self._async_pending: list = []
        self._async_watchdog: threading.Thread | None = None
        self._async_stop = False
        self._lock = threading.RLock()
        #: serializes whole swap operations (rehydrate + bind): without it
        #: two concurrent swaps bind in COMPLETION order, so a slow
        #: rollback rehydrate could silently overwrite a newer version
        #: that already reported success. Queries never take this lock.
        self._swap_lock = threading.Lock()
        self._served = 0
        self._started = _dt.datetime.now(_dt.timezone.utc)
        #: swap-epoch state: which registry version is live (None = plain
        #: instance deploy), when it was swapped in, and the last fold-in
        #: lag the retrain loop pushed (``online.loop``)
        self.model_version: int | None = None
        self.last_swap_ts: float | None = None
        self.foldin_lag_s: float | None = None
        self._load_models()

        # _served stays the single source of truth (handle_info reads it);
        # the registry only mirrors it at scrape time
        def mirror(registry):
            with self._lock:
                served = self._served
                version = self.model_version
                swap_ts = self.last_swap_ts
                lag = self.foldin_lag_s
            registry.set_counter(
                "pio_queries_served_total", served,
                help="Queries answered successfully",
            )
            if self._batcher is not None:
                registry.set_gauge(
                    "pio_serving_queue_depth", self._batcher.depth(),
                    help="Queries waiting in the micro-batcher queue",
                )
            if version is not None:
                registry.set_gauge(
                    "pio_model_version", float(version),
                    help="Registry model version currently serving",
                )
            if self.num_shards > 1:
                registry.set_gauge(
                    "pio_scorer_shard_index", float(self.shard),
                    help="This scorer's shard index in the serving fabric",
                )
                registry.set_gauge(
                    "pio_scorer_shard_count", float(self.num_shards),
                    help="Scorer shard count of the serving fabric",
                )
            if swap_ts is not None:
                registry.set_gauge(
                    "pio_model_last_swap_timestamp_seconds", swap_ts,
                    help="Unix time of the last model hot swap",
                )
            if lag is not None:
                registry.set_gauge(
                    "pio_foldin_lag_seconds", lag,
                    help="Seconds of ingested events not yet reflected in"
                    " the serving model (pushed by pio retrain --follow)",
                )
            stats_fn = self.scorer_stats
            if stats_fn is not None:
                try:
                    s = stats_fn()
                except Exception:
                    s = None
                if s:
                    total = (
                        s["wake_events"] + s["handoffs"]
                        + s["completion_signals"] + self._future_parks
                    )
                    n = s["query_requests"]
                    registry.set_counter(
                        "pio_scorer_wakeups_total", float(total),
                        help="Cross-thread wakeups on the scorer's query"
                        " path (consumer eventfd wakes + dispatcher"
                        " handoffs + future parks + completion signals)",
                    )
                    registry.set_counter(
                        "pio_scorer_query_requests_total", float(n),
                        help="Query frames popped from the frontend rings",
                    )
                    registry.set_gauge(
                        "pio_scorer_wakeups_per_request",
                        round(total / n, 3) if n else 0.0,
                        help="Measured query-path wakeups per request"
                        " (sync dispatch ~4, async fast path <= 2)",
                    )
                    registry.set_gauge(
                        "pio_scorer_dispatch_threads",
                        float(s["dispatch_threads"]),
                        help="Dispatcher threads serving the query path"
                        " (0 = async fast path; control routes keep a"
                        " separate small pool)",
                    )
                    registry.set_gauge(
                        "pio_scorer_completion_retry_depth",
                        float(s["retry_depth"]),
                        help="Completions parked on the ring-full timer"
                        " retry queue",
                    )

        self.router, self.metrics = instrumented_router(
            before_scrape=mirror, tracing=tracing,
            trace_sample=trace_sample,
            extra_snapshots=extra_metrics_snapshots,
        )
        if slow_query_ms is not None:
            # one summary log line per query trace over the threshold
            self.router.tracer.set_slow_threshold(
                "POST /queries.json", slow_query_ms / 1000.0
            )
        self.router.add("GET", "/", self.handle_info)
        self.router.add("POST", "/queries.json", self.handle_query)
        self.router.add("GET", "/reload", self.handle_reload)
        self.router.add("POST", "/stop", self.handle_stop)
        self.router.add("POST", "/models/swap", self.handle_model_swap)
        self.router.add("POST", "/models/lag", self.handle_model_lag)
        self.router.add("GET", "/models.json", self.handle_models)
        self._stop_event = threading.Event()
        # the batcher captures engine state per flush (under self._lock),
        # so /reload hot-swaps apply to the very next batch; it fans
        # batch-level spans back out to each coalesced request's trace
        self._batcher = (
            MicroBatcher(
                self._predict_batch, self.batching,
                metrics=self.metrics, tracer=self.router.tracer,
            )
            if self.batching.enabled
            else None
        )

    # -- model lifecycle ----------------------------------------------------
    def registry(self):
        """The variant's model registry (``online.registry``), resolved
        lazily so plain deploys never touch the registry tree."""
        if self._registry is None:
            from predictionio_tpu.online.registry import ModelRegistry

            self._registry = ModelRegistry.for_variant(self.variant)
        return self._registry

    def _enforce_shard_budget(self, nbytes: int, what: str) -> None:
        """``PIO_SHARD_BUDGET_BYTES``: the per-shard memory contract of the
        sharded fabric. A shard REFUSES to materialize any model blob
        larger than its configured budget -- the guarantee that lets
        operators size shards below the full table: a generation with
        per-shard blobs serves a model N times the budget because each
        scorer only ever touches its own slice, while a fallback load of
        the full blob fails loudly instead of silently blowing the shard's
        memory envelope. No-op outside sharded mode or without the env."""
        if self.num_shards <= 1:
            return
        import os

        raw = os.environ.get("PIO_SHARD_BUDGET_BYTES", "").strip()
        if not raw:
            return
        try:
            budget = int(raw)
        except ValueError:
            logger.warning("ignoring non-integer PIO_SHARD_BUDGET_BYTES=%r", raw)
            return
        if budget > 0 and nbytes > budget:
            raise RuntimeError(
                f"shard {self.shard}/{self.num_shards}: {what} is"
                f" {nbytes} bytes, over the shard budget of {budget}"
                " (PIO_SHARD_BUDGET_BYTES); publish per-shard blobs"
                " (scorer_shards on the retrain loop) or raise the budget"
            )

    def _load_models(self) -> None:
        from predictionio_tpu.data import storage
        from predictionio_tpu.utils.platform import ensure_backend

        if self.requested_model_version is not None:
            # pinned registry deploy / rollback: the version's manifest is
            # self-contained (params + blob); a missing or corrupt version
            # raises RegistryError verbatim -- deploy must fail loudly,
            # never silently serve a different model than the one named
            self._swap_to_version(self.requested_model_version)
            return
        instance = resolve_engine_instance(self.variant, self.requested_instance_id)
        engine_params = engine_params_from_instance(instance)
        # resolve the instance FIRST so an explicit pio.platform in its
        # runtime conf wins; serving must come up even with a wedged
        # accelerator plugin, so this call site opts into the degradation
        # ladder (fallback=True) -- availability over pin fidelity here
        ensure_backend(
            (instance.runtime_conf or {}).get("pio.platform"), fallback=True
        )
        blob_record = storage.get_model_data_models().get(instance.id)
        blob = blob_record.models if blob_record else None
        if blob is not None:
            self._enforce_shard_budget(len(blob), f"instance {instance.id} blob")
        ctx = RuntimeContext(instance.runtime_conf)
        models = self.engine.prepare_deploy(
            ctx, engine_params, instance.id, blob,
            shard=self.shard, num_shards=self.num_shards,
        )
        algorithms = self.engine._algorithms(engine_params)
        serving = self.engine.serving(engine_params)
        with self._lock:
            self.instance = instance
            self.engine_params = engine_params
            self.models = models
            self.algorithms = algorithms
            self.serving_instance = serving
            self.model_version = None
        logger.info(
            "deployed engine instance %s (%d algorithm(s))", instance.id, len(models)
        )

    def _swap_to_version(self, version: int | None) -> int:
        """THE hot-swap epoch protocol: rehydrate a registry version
        OUTSIDE the lock (deserialization and warm-up are slow), then bind
        the whole epoch -- instance, params, models, algorithms, serving,
        version -- in ONE locked assignment. Query paths snapshot the
        epoch under the same lock (``_predict_batch``/``_predict_one``),
        so every in-flight batch finishes on the handle it captured, every
        later submission binds the new one, and no response is ever
        computed from a mixed-version epoch. Returns the swapped version;
        raises ``online.registry.RegistryError`` on a missing/corrupt one
        (the old epoch keeps serving untouched). Swaps are serialized
        against each other (``_swap_lock``) so they take effect in
        REQUEST order, not rehydrate-completion order."""
        with self._swap_lock:
            return self._swap_to_version_locked(version)

    def _swap_to_version_locked(self, version: int | None) -> int:
        from predictionio_tpu.controller.engine import EngineParams
        from predictionio_tpu.utils.platform import ensure_backend

        registry = self.registry()
        entry = registry.get(version) if version is not None else registry.latest()
        if entry is None:
            from predictionio_tpu.online.registry import RegistryError

            raise RegistryError(
                f"model registry is empty under {registry.dir}; run"
                " `pio train` or `pio retrain` first"
            )
        shard_filter: int | None = None
        if self.num_shards > 1 and entry.shard_count == self.num_shards:
            # the generation was published with matching per-shard blobs:
            # load ONLY this shard's slice -- the fabric's memory contract
            blob = entry.load_blob(shard=self.shard)  # CRC-verified
        else:
            if self.num_shards > 1:
                logger.info(
                    "version %d has %d shard blob(s) for a %d-shard"
                    " deploy; loading the full blob and partitioning"
                    " in-process", entry.version, entry.shard_count,
                    self.num_shards,
                )
                shard_filter = self.shard
            blob = entry.load_blob()  # CRC-verified
        self._enforce_shard_budget(
            len(blob), f"registry version {entry.version} blob"
        )
        params_obj = entry.engine_params_obj
        engine_params = (
            EngineParams.from_json_obj(params_obj)
            if params_obj
            else engine_params_from_instance(
                resolve_engine_instance(self.variant, entry.instance_id or None)
            )
        )
        ensure_backend(
            (self.variant.runtime_conf or {}).get("pio.platform"), fallback=True
        )
        ctx = RuntimeContext(self.variant.runtime_conf)
        models = self.engine.prepare_deploy(
            ctx, engine_params, entry.instance_id or "", blob,
            shard=shard_filter, num_shards=self.num_shards,
        )
        algorithms = self.engine._algorithms(engine_params)
        serving = self.engine.serving(engine_params)
        instance = None
        if entry.instance_id:
            try:
                instance = resolve_engine_instance(self.variant, entry.instance_id)
            except LookupError:
                instance = None
        if instance is None and getattr(self, "instance", None) is None:
            # registry-only deploy whose meta row is gone: a stub keeps the
            # info page honest instead of crashing it
            from predictionio_tpu.data.storage.base import EngineInstance

            instance = EngineInstance(
                id=entry.instance_id or f"registry-v{entry.version}",
                status="COMPLETED",
                start_time=self._started,
                engine_id=self.variant.variant_id,
                engine_version=self.variant.engine_version,
                engine_variant=self.variant.path,
                engine_factory=self.variant.engine_factory,
            )
        with self._lock:
            if instance is not None:
                self.instance = instance
            self.engine_params = engine_params
            self.models = models
            self.algorithms = algorithms
            self.serving_instance = serving
            self.model_version = entry.version
            self.last_swap_ts = _time.time()
        logger.info(
            "hot-swapped model version %d (%s, instance %s)",
            entry.version, entry.source, entry.instance_id or "?",
        )
        return entry.version

    # -- handlers -----------------------------------------------------------
    def handle_info(self, request: Request) -> Response:
        with self._lock:
            body = {
                "status": "alive",
                "engineInstance": {
                    "id": self.instance.id,
                    "engineVariant": self.variant.variant_id,
                    "startTime": self.instance.start_time.isoformat(),
                },
                "algorithms": [type(a).__name__ for a in self.algorithms],
                "modelVersion": self.model_version,
                "startTime": self._started.isoformat(),
                "serverStats": {"queryCount": self._served},
                "batching": {
                    "enabled": self._batcher is not None,
                    "maxBatchSize": self.batching.max_batch_size,
                    "windowMs": self.batching.window_ms,
                    "buckets": list(self.batching.buckets),
                },
            }
            if self.num_shards > 1:
                body["shard"] = {
                    "shard": self.shard, "numShards": self.num_shards,
                }
            if self.frontend_info is not None:
                body["frontend"] = self.frontend_info
            return Response(200, body)

    def _predict_one(self, query_obj) -> Any:
        """The unbatched predict -> serve chain for one raw query dict;
        returns ``(result, model_version)`` -- the version is the epoch's,
        captured in the SAME lock acquisition as the model handles, so a
        concurrent hot swap can never mislabel a response."""
        with self._lock:
            algorithms = self.algorithms
            models = self.models
            serving = self.serving_instance
            version = self.model_version
        predictions = []
        typed_query = algorithms[0].query_from_json(query_obj)
        for algorithm, model in zip(algorithms, models):
            query = algorithm.query_from_json(query_obj)
            predictions.append(algorithm.predict(model, query))
        # serving receives the typed query, matching Engine.eval's contract
        return serving.serve(typed_query, predictions), version

    def _predict_batch(self, query_objs: list) -> list:
        """MicroBatcher execute callback: raw query dicts in, one
        ``(result, model_version)`` OR ``Exception`` per slot out
        (aligned). Per-request isolation: the batched hooks run
        optimistically for the whole batch; if one raises, the batch
        degrades to per-query scoring so only the failing queries carry
        their error (the ``workflow/batch_predict`` chunk-fallback
        pattern, on the serving path). The whole batch binds ONE epoch --
        the swap protocol's no-mixed-version guarantee."""
        with self._lock:
            algorithms = self.algorithms
            models = self.models
            serving = self.serving_instance
            version = self.model_version
        n = len(query_objs)
        errors: dict[int, Exception] = {}
        typed: dict[int, Any] = {}
        for i, obj in enumerate(query_objs):
            try:
                typed[i] = algorithms[0].query_from_json(obj)
            except Exception as exc:
                errors[i] = exc
        per_algo: list[dict[int, Any]] = []
        for algorithm, model in zip(algorithms, models):
            pairs = []
            for i in range(n):
                if i in errors:
                    continue
                try:
                    pairs.append((i, algorithm.query_from_json(query_objs[i])))
                except Exception as exc:
                    errors[i] = exc
            try:
                preds = dict(algorithm.batch_predict(model, pairs))
            except Exception:
                logger.warning(
                    "batched predict failed for a %d-query batch; "
                    "rescoring per query", len(pairs), exc_info=True,
                )
                preds = {}
                for i, q in pairs:
                    try:
                        preds[i] = algorithm.predict(model, q)
                    except Exception as exc:
                        errors[i] = exc
            for i, _ in pairs:
                if i not in preds and i not in errors:
                    errors[i] = RuntimeError(
                        f"{type(algorithm).__name__}.batch_predict returned "
                        f"no result for query {i}"
                    )
            per_algo.append(preds)
        ok = [i for i in range(n) if i not in errors]
        served: dict[int, Any] = {}
        if ok:
            try:
                out = serving.serve_batch(
                    [typed[i] for i in ok],
                    [[preds[i] for preds in per_algo] for i in ok],
                )
                if len(out) != len(ok):
                    raise RuntimeError(
                        f"serve_batch returned {len(out)} results for "
                        f"{len(ok)} queries"
                    )
                served = dict(zip(ok, out))
            except Exception:
                served = {}
                for i in ok:
                    try:
                        served[i] = serving.serve(
                            typed[i], [preds[i] for preds in per_algo]
                        )
                    except Exception as exc:
                        errors[i] = exc
        return [
            errors[i] if i in errors else (served[i], version)
            for i in range(n)
        ]

    def handle_query(self, request: Request) -> Response:
        tracer = self.router.tracer
        try:
            with tracer.span("query.parse"):
                query_obj = request.json()
        except json.JSONDecodeError:
            return Response(400, {"message": "malformed JSON query"})
        try:
            if self._batcher is not None:
                # the window is how long a query may WAIT; the allowance on
                # top covers execution (first-bucket jit compiles included)
                wait_s = self.batching.window_ms / 1000.0 + 30.0
                try:
                    fut = self._batcher.submit(query_obj)
                    if request.frontend_pc is not None and not fut.done():
                        # a ring-dispatched request about to park a
                        # dispatcher thread on the future: one measured
                        # wakeup the async fast path does not pay
                        self._future_parks += 1
                    result, version = fut.result(wait_s)
                except BatcherStopped:
                    return Response(503, {"message": "server is stopping"})
                except _FutureTimeout:
                    return Response(
                        503, {"message": "batched predict timed out"}
                    )
            else:
                with tracer.span("query.predict"):
                    result, version = self._predict_one(query_obj)
            for plugin in self.plugins:
                plugin.output_blocker(query_obj, result)
        except ServerRejection as exc:
            return Response(exc.status, {"message": str(exc)})
        except (KeyError, TypeError, ValueError) as exc:
            return Response(400, {"message": f"bad query: {exc}"})
        return self._respond(query_obj, result, version)

    def _respond(self, query_obj, result, version) -> Response:
        """The shared post-predict completion tail -- sniffer plugins,
        serialization, feedback, served count, version header -- used by
        BOTH the sync request-thread path (``handle_query``) and the
        async flusher-callback path (``_finish_async_query``), so the
        tier's byte-identity contract cannot drift between them. Callers
        must have the request's trace context active on the calling
        thread (a request-thread dispatch span, or the async path's
        attached handle) so ``query.respond`` lands in the right trace."""
        tracer = self.router.tracer
        for plugin in self.plugins:
            plugin.output_sniffer(query_obj, result)
        with self._lock:
            serializer = self.algorithms[0]
        with tracer.span("query.respond"):
            result_json = serializer.result_to_json(result)
            if not isinstance(result_json, (dict, list)):
                result_json = {"result": result_json}
        if self.feedback:
            pr_id = uuid.uuid4().hex
            if isinstance(result_json, dict):
                result_json = {**result_json, "prId": pr_id}
            # off the request path: feedback latency must not touch query p50
            threading.Thread(
                target=self._send_feedback,
                args=(query_obj, result_json, pr_id),
                daemon=True,
            ).start()
        with self._lock:
            self._served += 1
        response = Response(200, result_json)
        if version is not None:
            # attribution header: which registry version computed THIS
            # response (captured in the predict path's epoch snapshot, so
            # it is exact across concurrent hot swaps). Bodies stay
            # byte-identical to a plain deploy; the header only exists
            # once the registry/swap subsystem is in play.
            response.headers["x-pio-model-version"] = str(version)
        return response

    # -- async fast path (multi-process tier, dispatcherless dispatch) ------
    #: the fast path bypasses Router.dispatch, so it pins the route label
    #: its metrics/spans use to the registered pattern
    _QUERY_ROUTE = "/queries.json"

    def submit_query_async(self, request: Request, on_done) -> None:
        """The dispatcher-less fast path of the multi-process tier: the
        scorer bridge's ring consumer calls this for ``POST
        /queries.json`` frames instead of routing them through the
        dispatcher pool. Parse + micro-batcher submit happen on the
        CALLING (consumer) thread; everything after the model answers --
        plugin hooks, serialization, feedback, route metrics, the trace
        root -- runs in a ``Future.add_done_callback`` on the batcher's
        flusher thread. ``on_done(response)`` is called exactly once
        (synchronously for immediate errors) and must never block: the
        bridge's continuation does one non-blocking ring push and parks
        overflow on a timer-driven retry queue (``pio check`` C005 is
        the static gate for this contract).

        Trace spans are explicit handles here: the root starts on the
        consumer, is attached around ``submit`` so the batcher captures
        the context, and finishes in the callback -- the
        ``frontend.ring_wait``/``query.parse``/shared batch spans land in
        the same trace shape as the sync path. Every response is built by
        the same code as :meth:`handle_query`, so bodies stay
        byte-identical across dispatch modes."""
        t0 = _time.perf_counter()
        tracer = self.router.tracer
        span = None
        guard = NULL_SPAN
        if tracer.enabled:
            traceparent = next(
                (
                    v for k, v in request.headers.items()
                    if k.lower() == "traceparent"
                ),
                None,
            )
            root = tracer.start_remote(
                f"POST {self._QUERY_ROUTE}", traceparent
            )
            if root.trace_id is not None:  # sampled-out roots record nothing
                span = root
                guard = root
            else:
                # suppress nested span() calls exactly as the sync
                # path's sampled-out root does on its dispatch thread
                guard = SAMPLED_OUT_ROOT
        guard.attach()
        try:
            if span is not None and request.frontend_pc is not None:
                recv_pc, dispatch_pc, worker = request.frontend_pc
                tracer.record_span(
                    span.trace_id, "frontend.ring_wait", recv_pc,
                    dispatch_pc, parent_id=span.span_id,
                    attrs={"worker": worker},
                )
            try:
                with tracer.span("query.parse"):
                    query_obj = request.json()
            except json.JSONDecodeError:
                self._finish_async_response(
                    request,
                    Response(400, {"message": "malformed JSON query"}),
                    span, t0, on_done,
                )
                return
            batcher = self._batcher
            if batcher is None:
                # the bridge only wires this path with batching enabled;
                # answered (not raised) so a misconfiguration stays visible
                self._finish_async_response(
                    request,
                    Response(
                        503, {"message": "async dispatch requires batching"}
                    ),
                    span, t0, on_done,
                )
                return
            try:
                # submit captures current_context() from the attached guard
                future = batcher.submit(query_obj)
            except BatcherStopped:
                self._finish_async_response(
                    request, Response(503, {"message": "server is stopping"}),
                    span, t0, on_done,
                )
                return
            entry = {
                "future": future,
                "query_obj": query_obj,
                "request": request,
                "span": span,
                "t0": t0,
                "on_done": on_done,
                "deadline": t0 + self._async_timeout_s,
                "claimed": False,
            }
            with self._async_lock:
                self._async_pending.append(entry)
                if self._async_watchdog is None and not self._async_stop:
                    self._async_watchdog = threading.Thread(
                        target=self._async_watch,
                        name="pio-async-watchdog", daemon=True,
                    )
                    self._async_watchdog.start()
            future.add_done_callback(
                lambda f: self._finish_async_query(entry, f)
            )
        except Exception:
            # the Router._dispatch backstop contract (e.g. a non-UTF-8
            # body raising UnicodeDecodeError in parse): the request
            # still gets its 500, envelope, metrics, and span finish
            logger.exception("async query submission failed")
            self._finish_async_response(
                request, Response(500, {"message": "internal server error"}),
                span, t0, on_done,
            )
        finally:
            guard.detach()

    def _claim_async(self, entry: dict) -> bool:
        """Exactly-once gate between the future callback and the
        watchdog's timeout 503: first claimer finishes the request (and
        removes the entry, so the pending list holds only live ones)."""
        with self._async_lock:
            if entry["claimed"]:
                return False
            entry["claimed"] = True
            try:
                self._async_pending.remove(entry)
            except ValueError:
                pass
            return True

    def _async_watch(self) -> None:
        """1 Hz sweep over in-flight async queries: a future that blew
        the sync path's wait budget answers 503 "batched predict timed
        out" (releasing its admission permit through on_done) instead of
        holding the permit until a wedged batch resolves -- the sync
        dispatcher's ``result(wait_s)`` backstop, off-thread. Exits
        within a tick of ``close()``."""
        while True:
            with self._async_lock:
                if self._async_stop:
                    return
            _time.sleep(1.0)
            now = _time.perf_counter()
            fire = []
            with self._async_lock:
                keep = []
                for entry in self._async_pending:
                    if entry["claimed"]:
                        continue
                    if now >= entry["deadline"] and not entry["future"].done():
                        entry["claimed"] = True
                        fire.append(entry)
                    else:
                        keep.append(entry)
                self._async_pending = keep
            for entry in fire:
                self._finish_async_response(
                    entry["request"],
                    Response(503, {"message": "batched predict timed out"}),
                    entry["span"], entry["t0"], entry["on_done"],
                )

    def _finish_async_query(self, entry: dict, future) -> None:
        """The flusher-thread continuation: exactly ``handle_query``'s
        post-predict semantics (plugin rejection -> status, bad query ->
        400, anything unexpected -> the dispatch backstop's 500) via the
        shared ``_respond`` tail, then the response envelope. ``future``
        is this callback's own argument and is already resolved --
        ``.result()`` here cannot block. No-op if the watchdog already
        answered the request's timeout 503."""
        if not self._claim_async(entry):
            return
        query_obj = entry["query_obj"]
        span = entry["span"]
        tracer = self.router.tracer
        guard = span
        if guard is None:
            guard = SAMPLED_OUT_ROOT if tracer.enabled else NULL_SPAN
        result = None
        version = None
        response = None
        guard.attach()
        try:
            try:
                result, version = future.result()
                for plugin in self.plugins:
                    plugin.output_blocker(query_obj, result)
            except BatcherStopped:
                response = Response(503, {"message": "server is stopping"})
            except ServerRejection as exc:
                response = Response(exc.status, {"message": str(exc)})
            except (KeyError, TypeError, ValueError) as exc:
                response = Response(400, {"message": f"bad query: {exc}"})
            if response is None:
                response = self._respond(query_obj, result, version)
        except Exception:
            # the Router._dispatch backstop contract, off-router
            logger.exception("async query completion failed")
            response = Response(500, {"message": "internal server error"})
        finally:
            guard.detach()
        self._finish_async_response(
            entry["request"], response, span, entry["t0"], entry["on_done"]
        )

    def _finish_async_response(
        self, request: Request, response: Response, span, t0: float, on_done
    ) -> None:
        """Stamp the routing envelope Router.dispatch would have (trace
        attrs, response ``traceparent``, error-body ``traceId``, route
        metrics), finish the root span, hand off. Never raises."""
        if span is not None:
            span.set_attr("status", response.status)
            if response.status >= 500:
                span.set_status("error")
            response.headers.setdefault(
                "traceparent",
                format_traceparent(span.trace_id, span.span_id),
            )
            if response.status >= 400 and isinstance(response.body, dict):
                response.body.setdefault("traceId", span.trace_id)
            span.finish()
        try:
            self.router.record_route(
                request, self._QUERY_ROUTE, response.status, t0
            )
        except Exception:
            logger.warning("route metrics recording failed", exc_info=True)
        try:
            on_done(response)
        except Exception:
            logger.exception("async completion delivery failed")

    def handle_model_swap(self, request: Request) -> Response:
        """``POST /models/swap {"version": N?}``: hot-swap a registry
        version (default: latest) into the live epoch. The retrain loop's
        notify target; also the runtime rollback lever -- POST an older
        retained version to roll back with zero downtime."""
        from predictionio_tpu.online.registry import RegistryError

        try:
            body = request.json() or {}
        except json.JSONDecodeError:
            return Response(400, {"message": "malformed JSON body"})
        version = body.get("version")
        if version is not None:
            try:
                version = int(version)
            except (TypeError, ValueError):
                return Response(400, {"message": f"bad version {version!r}"})
        try:
            swapped = self._swap_to_version(version)
        except RegistryError as exc:
            return Response(404, {"message": str(exc)})
        except Exception as exc:
            logger.exception("model swap failed")
            return Response(500, {"message": f"swap failed: {exc}"})
        lag = body.get("foldinLagSeconds")
        if isinstance(lag, (int, float)):
            with self._lock:
                self.foldin_lag_s = float(lag)
        return Response(200, {"status": "swapped", "modelVersion": swapped})

    def handle_model_lag(self, request: Request) -> Response:
        """Fold-in lag heartbeat from the retrain loop (keeps `pio top`'s
        LAG column live between swaps)."""
        try:
            body = request.json() or {}
        except json.JSONDecodeError:
            return Response(400, {"message": "malformed JSON body"})
        lag = body.get("foldinLagSeconds")
        if not isinstance(lag, (int, float)):
            return Response(400, {"message": "foldinLagSeconds required"})
        with self._lock:
            self.foldin_lag_s = float(lag)
        return Response(200, {"status": "ok"})

    def handle_models(self, request: Request) -> Response:
        """``GET /models.json``: the registry's retained versions plus the
        live one -- the operator's rollback menu."""
        with self._lock:
            current = self.model_version
        try:
            versions = [
                {
                    "version": v.version,
                    "source": v.source,
                    "engineInstanceId": v.instance_id,
                    "createdAt": v.manifest.get("created_at"),
                    "untilMs": v.manifest.get("until_ms"),
                }
                for v in self.registry().versions()
            ]
        except Exception as exc:
            return Response(500, {"message": f"registry unavailable: {exc}"})
        return Response(
            200, {"currentVersion": current, "versions": versions}
        )

    def handle_reload(self, request: Request) -> Response:
        # /reload re-resolves the LATEST completed instance (hot-swap), even
        # if the server was started pinned to an explicit instance id OR a
        # registry version -- un-pin both, or a pinned deploy would re-load
        # its startup version forever (and a GC'd one would 500 here)
        self.requested_instance_id = None
        self.requested_model_version = None
        self._load_models()
        return Response(200, {"status": "reloaded", "engineInstanceId": self.instance.id})

    def handle_stop(self, request: Request) -> Response:
        self._stop_event.set()
        return Response(200, {"status": "stopping"})

    def close(self) -> None:
        """Graceful drain: flush every in-flight batched query (their
        request threads are parked on futures and still get answers), then
        stop the flusher. Call AFTER the HTTP listener stops accepting.
        The async watchdog (if the multi-process fast path started one)
        exits within a tick, so a closed service is fully collectable."""
        if self._batcher is not None:
            self._batcher.close()
        with self._async_lock:
            # stop flag and watchdog handle share the async lock with
            # their writers (pio check C006); the join happens OUTSIDE
            # it -- the watchdog's loop takes this lock every tick
            self._async_stop = True
            watchdog = self._async_watchdog
            self._async_watchdog = None
        if watchdog is not None:
            watchdog.join(timeout=2.0)
        with self._async_lock:
            self._async_pending.clear()

    # -- feedback loop ------------------------------------------------------
    def _send_feedback(self, query: Any, prediction: Any, pr_id: str) -> None:
        """POST query/prediction back to the Event Server (reference
        --feedback). Failures are logged, never surfaced to the client."""
        import urllib.request

        event = {
            "event": "predict",
            "entityType": "pio_pr",
            "entityId": pr_id,
            "properties": {"query": query, "prediction": prediction},
            "prId": pr_id,
        }
        url = (
            f"{self.feedback.event_server_url}/events.json"
            f"?accessKey={self.feedback.access_key}"
        )
        try:
            req = urllib.request.Request(
                url,
                data=json.dumps(event).encode("utf-8"),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            urllib.request.urlopen(req, timeout=2)
        except Exception as exc:
            logger.warning("feedback event failed: %s", exc)


def create_query_server(
    variant: EngineVariant,
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    ssl_cert: str | None = None,
    ssl_key: str | None = None,
    **service_kwargs,
) -> tuple[ServiceThread, QueryService]:
    service = QueryService(variant, **service_kwargs)
    server = make_server(
        service.router, host, port, "pio-queryserver",
        ssl_cert=ssl_cert, ssl_key=ssl_key,
    )
    return ServiceThread(server), service


class MultiprocServiceHandle:
    """The multi-process analogue of :class:`ServiceThread`: same
    ``start()/stop()/port`` surface, so benches and tests treat both
    tiers uniformly. ``stop()`` drains the frontends (in-flight requests
    are answered) before the scorer bridge tears down."""

    def __init__(self, bridge, service: QueryService):
        self.bridge = bridge
        self.service = service

    @property
    def port(self) -> int:
        return self.bridge.port

    def start(self) -> "MultiprocServiceHandle":
        self.bridge.start()
        return self

    def stop(self) -> None:
        self.bridge.stop()


def create_multiproc_query_server(
    variant: EngineVariant,
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    frontend=None,
    **service_kwargs,
) -> tuple[MultiprocServiceHandle, QueryService]:
    """The multi-process serving tier: this process becomes the scorer
    (models + micro-batcher + router, exactly the single-process
    ``QueryService``); ``frontend`` (a ``FrontendConfig`` or a worker
    count) sizes the ``SO_REUSEPORT`` frontend processes that do the
    HTTP. Responses are byte-identical to the single-process server
    because every body is produced by the same router in the scorer.

    TLS is not supported at the frontend tier (terminate it in front, or
    deploy single-process with ``--ssl-cert``).
    """
    from predictionio_tpu.serving.procserver import FrontendConfig, ScorerBridge

    if service_kwargs.pop("ssl_cert", None) or service_kwargs.pop("ssl_key", None):
        raise ValueError(
            "--frontend-workers does not support --ssl-cert/--ssl-key; "
            "terminate TLS in front of the frontend tier"
        )
    if isinstance(frontend, int):
        frontend = FrontendConfig(workers=frontend)
    frontend = frontend or FrontendConfig()
    # the bridge exists only after the service (it needs the router), but
    # the service's /metrics hook needs the bridge: late-bind via a cell
    bridge_cell: list = []

    def worker_snapshots() -> list[dict]:
        return bridge_cell[0].metric_snapshots() if bridge_cell else []

    service = QueryService(
        variant, extra_metrics_snapshots=worker_snapshots, **service_kwargs
    )
    # the async fast path needs a future per query, i.e. the batcher; a
    # batching-disabled deploy (or an explicit dispatch="sync") keeps the
    # dispatcher-pool model
    async_query = None
    if frontend.dispatch == "async" and service._batcher is not None:
        async_query = service.submit_query_async
    bridge = ScorerBridge(
        service.router, host, port, frontend, registry=service.metrics,
        async_query=async_query,
    )
    bridge_cell.append(bridge)
    service.scorer_stats = bridge.wakeup_stats
    service.frontend_info = frontend.describe()
    return MultiprocServiceHandle(bridge, service), service


def create_sharded_query_server(
    variant: EngineVariant,
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    scorer_shards: int = 2,
    frontend=None,
    model_version: int | None = None,
    instance_id: str | None = None,
    batching=None,
):
    """The sharded serving fabric: ``scorer_shards`` scorer processes,
    each holding one hash partition of the user factor table (item-side
    state replicated), behind the same ``SO_REUSEPORT`` frontend tier.
    Returns an unstarted ``ShardFabric`` with the
    ``start()/stop()/port`` surface of :class:`MultiprocServiceHandle`.
    """
    from predictionio_tpu.serving.fabric import ShardFabric
    from predictionio_tpu.serving.procserver import FrontendConfig

    if isinstance(frontend, int):
        frontend = FrontendConfig(workers=frontend)
    return ShardFabric(
        variant,
        host=host,
        port=port,
        num_shards=scorer_shards,
        frontend=frontend,
        model_version=model_version,
        instance_id=instance_id,
        batch_window_ms=batching.window_ms if batching else None,
        max_batch_size=batching.max_batch_size if batching else None,
    )


def run_query_server(
    variant: EngineVariant,
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    frontend_workers: int = 0,
    frontend=None,
    scorer_shards: int = 0,
    **kw,
) -> None:
    """Blocking entry point used by ``pio deploy``. With
    ``frontend_workers`` > 0 (or an explicit ``frontend`` config) the
    server runs as the multi-process tier: N ``SO_REUSEPORT`` frontend
    processes feeding this process's scorer through shared-memory rings.
    ``scorer_shards`` > 1 instead runs the sharded fabric: the user
    factor table hash-partitioned across that many scorer processes.
    """
    if scorer_shards > 1:
        if kw.pop("ssl_cert", None) or kw.pop("ssl_key", None):
            raise ValueError(
                "--scorer-shards does not support --ssl-cert/--ssl-key;"
                " terminate TLS in front of the frontend tier"
            )
        if kw.pop("feedback", None) is not None:
            raise ValueError(
                "--scorer-shards does not support --feedback yet;"
                " run the feedback loop against an unsharded deploy"
            )
        dropped = {
            k: v
            for k in ("tracing", "trace_sample", "slow_query_ms")
            if (v := kw.pop(k, None)) is not None
        }
        if dropped:
            logger.info(
                "sharded deploy: shard processes use their own defaults"
                " for %s", sorted(dropped),
            )
        fabric = create_sharded_query_server(
            variant, host, port, scorer_shards=scorer_shards,
            frontend=frontend, **kw,
        )
        fabric.start()
        print(
            f"Query Server listening on http://{host}:{fabric.port}"
            f" ({scorer_shards} scorer shard(s),"
            f" {fabric.config.workers} frontend worker(s))"
        )
        try:
            fabric.wait()
        finally:
            fabric.stop()
        return
    if frontend_workers or frontend is not None:
        from predictionio_tpu.serving.procserver import FrontendConfig

        if frontend is None:
            frontend = FrontendConfig(workers=frontend_workers)
        handle, service = create_multiproc_query_server(
            variant, host, port, frontend=frontend, **kw
        )
        handle.start()
        print(
            f"Query Server listening on http://{host}:{handle.port}"
            f" ({frontend.workers} frontend worker(s),"
            f" engine instance {service.instance.id})"
        )
        try:
            service._stop_event.wait()
        except KeyboardInterrupt:
            pass
        handle.stop()   # frontends drain first (in-flight answered) ...
        service.close()  # ... then the micro-batcher flushes
        return
    thread, service = create_query_server(variant, host, port, **kw)
    scheme = "https" if kw.get("ssl_cert") else "http"
    thread.start()
    print(
        f"Query Server listening on {scheme}://{host}:{port}"
        f" (engine instance {service.instance.id})"
    )
    try:
        service._stop_event.wait()
    except KeyboardInterrupt:
        pass
    thread.stop()
    service.close()  # drain in-flight batches after the listener stops
