"""Query Server: low-latency REST serving of a deployed engine.

Behavioral model: reference ``core/.../workflow/CreateServer.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.3 #25, section
3.2 call stack). Contract kept:

- ``POST /queries.json``: free-form JSON query -> per-algorithm
  ``predict`` -> ``serving.serve`` -> JSON PredictedResult (+ ``prId`` echo
  when the feedback loop is on)
- ``GET /``: info/status page (JSON here rather than HTML)
- ``GET /reload``: re-resolve the latest COMPLETED instance and hot-swap
  models
- ``POST /stop``: shut the server down (how ``pio undeploy`` works)
- plugin hook points: output blockers / output sniffers
  (``EngineServerPlugin`` parity)
- optional feedback loop: writes query/prediction events back to the Event
  Server (``--feedback --event-server-ip/port --accesskey``)

Default port 8000. Serving stays off the training mesh: predict calls are
host-side (factor caches) or single-chip jitted functions prepared at load
time -- the <5 ms p50 path (SURVEY.md section 7.3).

Concurrent requests are coalesced into padded micro-batches
(``workflow/microbatch``): request threads park on futures while one
flusher drives the engines' vectorized ``batch_predict`` paths, so the
scorer sees batch sizes that grow with load instead of always 1. The
single-request response surface is preserved byte-for-byte; disable with
``--batch-window-ms 0``.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import threading
import uuid
from concurrent.futures import TimeoutError as _FutureTimeout
from dataclasses import dataclass
from typing import Any

from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.utils.http import (
    Request,
    Response,
    ServiceThread,
    instrumented_router,
    make_server,
)
from predictionio_tpu.workflow.context import RuntimeContext
from predictionio_tpu.workflow.microbatch import (
    BatchConfig,
    BatcherStopped,
    MicroBatcher,
)
from predictionio_tpu.workflow.core_workflow import (
    engine_params_from_instance,
    resolve_engine_instance,
)
from predictionio_tpu.workflow.json_extractor import EngineVariant, build_engine

logger = logging.getLogger("pio.server")

DEFAULT_PORT = 8000


class EngineServerPlugin:
    """Output blocker/sniffer hook points (reference EngineServerPlugin)."""

    def output_blocker(self, query: Any, prediction: Any) -> None:
        pass

    def output_sniffer(self, query: Any, prediction: Any) -> None:
        pass


class ServerRejection(Exception):
    def __init__(self, message: str, status: int = 403):
        super().__init__(message)
        self.status = status


@dataclass
class FeedbackConfig:
    event_server_url: str
    access_key: str


class QueryService:
    """Holds the deployed engine state; hot-swappable via /reload."""

    def __init__(
        self,
        variant: EngineVariant,
        engine: Engine | None = None,
        instance_id: str | None = None,
        feedback: FeedbackConfig | None = None,
        plugins: list[EngineServerPlugin] | None = None,
        batching: BatchConfig | None = None,
        tracing: bool | None = None,
        trace_sample: float | None = None,
        slow_query_ms: float | None = None,
        extra_metrics_snapshots=None,
    ):
        self.variant = variant
        self.engine = engine or build_engine(variant)
        self.requested_instance_id = instance_id
        self.feedback = feedback
        self.plugins = list(plugins or [])
        self.batching = BatchConfig() if batching is None else batching
        #: set by the multi-process tier: {"workers": N, ...} for the info
        #: page (``pio top``/operators see the process model at a glance)
        self.frontend_info: dict | None = None
        self._lock = threading.RLock()
        self._served = 0
        self._started = _dt.datetime.now(_dt.timezone.utc)
        self._load_models()

        # _served stays the single source of truth (handle_info reads it);
        # the registry only mirrors it at scrape time
        def mirror(registry):
            with self._lock:
                served = self._served
            registry.set_counter(
                "pio_queries_served_total", served,
                help="Queries answered successfully",
            )
            if self._batcher is not None:
                registry.set_gauge(
                    "pio_serving_queue_depth", self._batcher.depth(),
                    help="Queries waiting in the micro-batcher queue",
                )

        self.router, self.metrics = instrumented_router(
            before_scrape=mirror, tracing=tracing,
            trace_sample=trace_sample,
            extra_snapshots=extra_metrics_snapshots,
        )
        if slow_query_ms is not None:
            # one summary log line per query trace over the threshold
            self.router.tracer.set_slow_threshold(
                "POST /queries.json", slow_query_ms / 1000.0
            )
        self.router.add("GET", "/", self.handle_info)
        self.router.add("POST", "/queries.json", self.handle_query)
        self.router.add("GET", "/reload", self.handle_reload)
        self.router.add("POST", "/stop", self.handle_stop)
        self._stop_event = threading.Event()
        # the batcher captures engine state per flush (under self._lock),
        # so /reload hot-swaps apply to the very next batch; it fans
        # batch-level spans back out to each coalesced request's trace
        self._batcher = (
            MicroBatcher(
                self._predict_batch, self.batching,
                metrics=self.metrics, tracer=self.router.tracer,
            )
            if self.batching.enabled
            else None
        )

    # -- model lifecycle ----------------------------------------------------
    def _load_models(self) -> None:
        from predictionio_tpu.data import storage
        from predictionio_tpu.utils.platform import ensure_backend

        instance = resolve_engine_instance(self.variant, self.requested_instance_id)
        engine_params = engine_params_from_instance(instance)
        # resolve the instance FIRST so an explicit pio.platform in its
        # runtime conf wins; serving must come up even with a wedged
        # accelerator plugin, so this call site opts into the degradation
        # ladder (fallback=True) -- availability over pin fidelity here
        ensure_backend(
            (instance.runtime_conf or {}).get("pio.platform"), fallback=True
        )
        blob_record = storage.get_model_data_models().get(instance.id)
        ctx = RuntimeContext(instance.runtime_conf)
        models = self.engine.prepare_deploy(
            ctx, engine_params, instance.id,
            blob_record.models if blob_record else None,
        )
        algorithms = self.engine._algorithms(engine_params)
        serving = self.engine.serving(engine_params)
        with self._lock:
            self.instance = instance
            self.engine_params = engine_params
            self.models = models
            self.algorithms = algorithms
            self.serving_instance = serving
        logger.info(
            "deployed engine instance %s (%d algorithm(s))", instance.id, len(models)
        )

    # -- handlers -----------------------------------------------------------
    def handle_info(self, request: Request) -> Response:
        with self._lock:
            body = {
                "status": "alive",
                "engineInstance": {
                    "id": self.instance.id,
                    "engineVariant": self.variant.variant_id,
                    "startTime": self.instance.start_time.isoformat(),
                },
                "algorithms": [type(a).__name__ for a in self.algorithms],
                "startTime": self._started.isoformat(),
                "serverStats": {"queryCount": self._served},
                "batching": {
                    "enabled": self._batcher is not None,
                    "maxBatchSize": self.batching.max_batch_size,
                    "windowMs": self.batching.window_ms,
                    "buckets": list(self.batching.buckets),
                },
            }
            if self.frontend_info is not None:
                body["frontend"] = self.frontend_info
            return Response(200, body)

    def _predict_one(self, query_obj) -> Any:
        """The unbatched predict -> serve chain for one raw query dict."""
        with self._lock:
            algorithms = self.algorithms
            models = self.models
            serving = self.serving_instance
        predictions = []
        typed_query = algorithms[0].query_from_json(query_obj)
        for algorithm, model in zip(algorithms, models):
            query = algorithm.query_from_json(query_obj)
            predictions.append(algorithm.predict(model, query))
        # serving receives the typed query, matching Engine.eval's contract
        return serving.serve(typed_query, predictions)

    def _predict_batch(self, query_objs: list) -> list:
        """MicroBatcher execute callback: raw query dicts in, one result OR
        ``Exception`` per slot out (aligned). Per-request isolation: the
        batched hooks run optimistically for the whole batch; if one
        raises, the batch degrades to per-query scoring so only the
        failing queries carry their error (the ``workflow/batch_predict``
        chunk-fallback pattern, on the serving path)."""
        with self._lock:
            algorithms = self.algorithms
            models = self.models
            serving = self.serving_instance
        n = len(query_objs)
        errors: dict[int, Exception] = {}
        typed: dict[int, Any] = {}
        for i, obj in enumerate(query_objs):
            try:
                typed[i] = algorithms[0].query_from_json(obj)
            except Exception as exc:
                errors[i] = exc
        per_algo: list[dict[int, Any]] = []
        for algorithm, model in zip(algorithms, models):
            pairs = []
            for i in range(n):
                if i in errors:
                    continue
                try:
                    pairs.append((i, algorithm.query_from_json(query_objs[i])))
                except Exception as exc:
                    errors[i] = exc
            try:
                preds = dict(algorithm.batch_predict(model, pairs))
            except Exception:
                logger.warning(
                    "batched predict failed for a %d-query batch; "
                    "rescoring per query", len(pairs), exc_info=True,
                )
                preds = {}
                for i, q in pairs:
                    try:
                        preds[i] = algorithm.predict(model, q)
                    except Exception as exc:
                        errors[i] = exc
            for i, _ in pairs:
                if i not in preds and i not in errors:
                    errors[i] = RuntimeError(
                        f"{type(algorithm).__name__}.batch_predict returned "
                        f"no result for query {i}"
                    )
            per_algo.append(preds)
        ok = [i for i in range(n) if i not in errors]
        served: dict[int, Any] = {}
        if ok:
            try:
                out = serving.serve_batch(
                    [typed[i] for i in ok],
                    [[preds[i] for preds in per_algo] for i in ok],
                )
                if len(out) != len(ok):
                    raise RuntimeError(
                        f"serve_batch returned {len(out)} results for "
                        f"{len(ok)} queries"
                    )
                served = dict(zip(ok, out))
            except Exception:
                served = {}
                for i in ok:
                    try:
                        served[i] = serving.serve(
                            typed[i], [preds[i] for preds in per_algo]
                        )
                    except Exception as exc:
                        errors[i] = exc
        return [errors[i] if i in errors else served[i] for i in range(n)]

    def handle_query(self, request: Request) -> Response:
        tracer = self.router.tracer
        try:
            with tracer.span("query.parse"):
                query_obj = request.json()
        except json.JSONDecodeError:
            return Response(400, {"message": "malformed JSON query"})
        try:
            if self._batcher is not None:
                # the window is how long a query may WAIT; the allowance on
                # top covers execution (first-bucket jit compiles included)
                wait_s = self.batching.window_ms / 1000.0 + 30.0
                try:
                    result = self._batcher.submit(query_obj).result(wait_s)
                except BatcherStopped:
                    return Response(503, {"message": "server is stopping"})
                except _FutureTimeout:
                    return Response(
                        503, {"message": "batched predict timed out"}
                    )
            else:
                with tracer.span("query.predict"):
                    result = self._predict_one(query_obj)
            for plugin in self.plugins:
                plugin.output_blocker(query_obj, result)
        except ServerRejection as exc:
            return Response(exc.status, {"message": str(exc)})
        except (KeyError, TypeError, ValueError) as exc:
            return Response(400, {"message": f"bad query: {exc}"})
        for plugin in self.plugins:
            plugin.output_sniffer(query_obj, result)
        with self._lock:
            serializer = self.algorithms[0]
        with tracer.span("query.respond"):
            result_json = serializer.result_to_json(result)
            if not isinstance(result_json, (dict, list)):
                result_json = {"result": result_json}
        if self.feedback:
            pr_id = uuid.uuid4().hex
            if isinstance(result_json, dict):
                result_json = {**result_json, "prId": pr_id}
            # off the request path: feedback latency must not touch query p50
            threading.Thread(
                target=self._send_feedback,
                args=(query_obj, result_json, pr_id),
                daemon=True,
            ).start()
        with self._lock:
            self._served += 1
        return Response(200, result_json)

    def handle_reload(self, request: Request) -> Response:
        # /reload re-resolves the LATEST completed instance (hot-swap), even
        # if the server was started pinned to an explicit instance id
        self.requested_instance_id = None
        self._load_models()
        return Response(200, {"status": "reloaded", "engineInstanceId": self.instance.id})

    def handle_stop(self, request: Request) -> Response:
        self._stop_event.set()
        return Response(200, {"status": "stopping"})

    def close(self) -> None:
        """Graceful drain: flush every in-flight batched query (their
        request threads are parked on futures and still get answers), then
        stop the flusher. Call AFTER the HTTP listener stops accepting."""
        if self._batcher is not None:
            self._batcher.close()

    # -- feedback loop ------------------------------------------------------
    def _send_feedback(self, query: Any, prediction: Any, pr_id: str) -> None:
        """POST query/prediction back to the Event Server (reference
        --feedback). Failures are logged, never surfaced to the client."""
        import urllib.request

        event = {
            "event": "predict",
            "entityType": "pio_pr",
            "entityId": pr_id,
            "properties": {"query": query, "prediction": prediction},
            "prId": pr_id,
        }
        url = (
            f"{self.feedback.event_server_url}/events.json"
            f"?accessKey={self.feedback.access_key}"
        )
        try:
            req = urllib.request.Request(
                url,
                data=json.dumps(event).encode("utf-8"),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            urllib.request.urlopen(req, timeout=2)
        except Exception as exc:
            logger.warning("feedback event failed: %s", exc)


def create_query_server(
    variant: EngineVariant,
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    ssl_cert: str | None = None,
    ssl_key: str | None = None,
    **service_kwargs,
) -> tuple[ServiceThread, QueryService]:
    service = QueryService(variant, **service_kwargs)
    server = make_server(
        service.router, host, port, "pio-queryserver",
        ssl_cert=ssl_cert, ssl_key=ssl_key,
    )
    return ServiceThread(server), service


class MultiprocServiceHandle:
    """The multi-process analogue of :class:`ServiceThread`: same
    ``start()/stop()/port`` surface, so benches and tests treat both
    tiers uniformly. ``stop()`` drains the frontends (in-flight requests
    are answered) before the scorer bridge tears down."""

    def __init__(self, bridge, service: QueryService):
        self.bridge = bridge
        self.service = service

    @property
    def port(self) -> int:
        return self.bridge.port

    def start(self) -> "MultiprocServiceHandle":
        self.bridge.start()
        return self

    def stop(self) -> None:
        self.bridge.stop()


def create_multiproc_query_server(
    variant: EngineVariant,
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    frontend=None,
    **service_kwargs,
) -> tuple[MultiprocServiceHandle, QueryService]:
    """The multi-process serving tier: this process becomes the scorer
    (models + micro-batcher + router, exactly the single-process
    ``QueryService``); ``frontend`` (a ``FrontendConfig`` or a worker
    count) sizes the ``SO_REUSEPORT`` frontend processes that do the
    HTTP. Responses are byte-identical to the single-process server
    because every body is produced by the same router in the scorer.

    TLS is not supported at the frontend tier (terminate it in front, or
    deploy single-process with ``--ssl-cert``).
    """
    from predictionio_tpu.serving.procserver import FrontendConfig, ScorerBridge

    if service_kwargs.pop("ssl_cert", None) or service_kwargs.pop("ssl_key", None):
        raise ValueError(
            "--frontend-workers does not support --ssl-cert/--ssl-key; "
            "terminate TLS in front of the frontend tier"
        )
    if isinstance(frontend, int):
        frontend = FrontendConfig(workers=frontend)
    frontend = frontend or FrontendConfig()
    # the bridge exists only after the service (it needs the router), but
    # the service's /metrics hook needs the bridge: late-bind via a cell
    bridge_cell: list = []

    def worker_snapshots() -> list[dict]:
        return bridge_cell[0].metric_snapshots() if bridge_cell else []

    service = QueryService(
        variant, extra_metrics_snapshots=worker_snapshots, **service_kwargs
    )
    bridge = ScorerBridge(
        service.router, host, port, frontend, registry=service.metrics
    )
    bridge_cell.append(bridge)
    service.frontend_info = frontend.describe()
    return MultiprocServiceHandle(bridge, service), service


def run_query_server(
    variant: EngineVariant,
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    frontend_workers: int = 0,
    frontend=None,
    **kw,
) -> None:
    """Blocking entry point used by ``pio deploy``. With
    ``frontend_workers`` > 0 (or an explicit ``frontend`` config) the
    server runs as the multi-process tier: N ``SO_REUSEPORT`` frontend
    processes feeding this process's scorer through shared-memory rings.
    """
    if frontend_workers or frontend is not None:
        from predictionio_tpu.serving.procserver import FrontendConfig

        if frontend is None:
            frontend = FrontendConfig(workers=frontend_workers)
        handle, service = create_multiproc_query_server(
            variant, host, port, frontend=frontend, **kw
        )
        handle.start()
        print(
            f"Query Server listening on http://{host}:{handle.port}"
            f" ({frontend.workers} frontend worker(s),"
            f" engine instance {service.instance.id})"
        )
        try:
            service._stop_event.wait()
        except KeyboardInterrupt:
            pass
        handle.stop()   # frontends drain first (in-flight answered) ...
        service.close()  # ... then the micro-batcher flushes
        return
    thread, service = create_query_server(variant, host, port, **kw)
    scheme = "https" if kw.get("ssl_cert") else "http"
    thread.start()
    print(
        f"Query Server listening on {scheme}://{host}:{port}"
        f" (engine instance {service.instance.id})"
    )
    try:
        service._stop_event.wait()
    except KeyboardInterrupt:
        pass
    thread.stop()
    service.close()  # drain in-flight batches after the listener stops
