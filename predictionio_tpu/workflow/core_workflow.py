"""CoreWorkflow: train + evaluation runs with the instance status machine.

Behavioral model: reference ``core/.../workflow/{CreateWorkflow,CoreWorkflow,
EvaluationWorkflow}.scala`` (apache/predictionio layout, unverified --
SURVEY.md section 2.3 #24 and section 3.1/3.4 call stacks):

- train: EngineInstance QUEUED -> RUNNING -> COMPLETED (FAILED on error),
  models serialized into the Models blob store keyed by instance id
- evaluation: EvaluationInstance lifecycle + MetricEvaluator leaderboard
  persisted for the dashboard
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import traceback

from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.controller.metrics import (
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
)
from predictionio_tpu.data import storage
from predictionio_tpu.data.storage.base import (
    STATUS_COMPLETED,
    STATUS_FAILED,
    STATUS_RUNNING,
    EngineInstance,
    EvaluationInstance,
    Model,
)
from predictionio_tpu.parallel.distributed import (
    LAUNCH_SCOPED_ENV,
    strip_launch_conf,
)
from predictionio_tpu.workflow.context import RuntimeContext, WorkflowParams
from predictionio_tpu.workflow.json_extractor import EngineVariant, build_engine

logger = logging.getLogger("pio.workflow")


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def _pio_env() -> dict[str, str]:
    """PIO_* env snapshot persisted on instances -- minus launch identity
    (coordinator/rank vars must not be replayed, distributed.py invariant)."""
    return {
        k: v
        for k, v in os.environ.items()
        if k.startswith("PIO_") and k not in LAUNCH_SCOPED_ENV
    }


def _run_key(variant: EngineVariant, params_jsons: tuple[str, ...]) -> str:
    """Stable checkpoint key: same variant + same FULL params (datasource,
    preparator, algorithms, serving) -> same key, so a rerun after
    preemption locates the crashed attempt's checkpoints (the round-1
    instance-id key made resume dead code: every rerun got a fresh
    checkpoint dir). Any params change -> different key: checkpoints from
    different data or hyperparameters must never cross-resume."""
    import hashlib

    material = "\x1f".join(
        (variant.variant_id, variant.engine_version, variant.path, *params_jsons)
    )
    return hashlib.sha256(material.encode()).hexdigest()[:16]


def run_train(
    variant: EngineVariant,
    workflow_params: WorkflowParams | None = None,
    engine: Engine | None = None,
) -> EngineInstance:
    """The `pio train` core: returns the COMPLETED EngineInstance.

    Raises after recording FAILED status if any DASE stage throws.
    With ``workflow_params.resume`` the variant's latest non-COMPLETED
    instance is reused and algorithms continue from their step checkpoints.
    """
    workflow_params = workflow_params or WorkflowParams()
    engine = engine or build_engine(variant)
    engine_params = variant.engine_params
    instances = storage.get_meta_data_engine_instances()

    params_jsons = (
        json.dumps(dict(engine_params.data_source_params)),
        json.dumps(dict(engine_params.preparator_params)),
        json.dumps(
            [
                {"name": n, "params": dict(p)}
                for n, p in engine_params.algorithm_params_list
            ]
        ),
        json.dumps(dict(engine_params.serving_params)),
    )
    run_key = _run_key(variant, params_jsons)

    from predictionio_tpu.parallel.distributed import launch_process_id

    if launch_process_id(variant.runtime_conf) != 0:
        # multi-process launch, non-primary rank: run the training compute
        # (every rank must participate in the collectives) but own NO
        # persistence side effects -- no run lock (ranks on one host share
        # PIO_FS_BASEDIR), no instance row, no step checkpoints, no model
        # blob. Rank 0 is the system of record.
        ctx = RuntimeContext(variant.runtime_conf, resume=workflow_params.resume)
        engine.train(
            ctx, engine_params, skip_sanity_check=workflow_params.skip_sanity_check
        )
        return EngineInstance(
            status=STATUS_COMPLETED,
            start_time=_utcnow(),
            end_time=_utcnow(),
            engine_id=variant.variant_id,
            engine_version=variant.engine_version,
            engine_variant=variant.path,
            engine_factory=variant.engine_factory,
        )

    # serialize trains sharing this run_key: a second identical train would
    # wipe the first's live step checkpoints (fresh=True) and --resume would
    # adopt its still-RUNNING instance. Raises RunLockHeld when the holder
    # is alive; a crashed holder's stale lock is taken over silently.
    from predictionio_tpu.workflow.checkpoint import RunLock

    run_lock = RunLock(run_key).acquire()
    try:
        return _run_train_locked(
            variant, workflow_params, engine, engine_params, instances,
            params_jsons, run_key,
        )
    finally:
        run_lock.release()


def _run_train_locked(
    variant: EngineVariant,
    workflow_params: WorkflowParams,
    engine: Engine,
    engine_params: EngineParams,
    instances,
    params_jsons: tuple[str, ...],
    run_key: str,
) -> EngineInstance:
    ds_json, prep_json, algorithms_params_json, serving_json = params_jsons
    instance = None
    resume = False
    if workflow_params.resume:
        prior = instances.get_latest(
            variant.variant_id, variant.engine_version, variant.path
        )
        if prior is not None and prior.status != STATUS_COMPLETED:
            # the FULL params must match: resuming ALS factors checkpointed
            # against a different dataset (changed datasource params) would
            # silently misalign factors with the new id vocabulary
            prior_params = (
                prior.data_source_params,
                prior.preparator_params,
                prior.algorithms_params,
                prior.serving_params,
            )
            if prior_params == params_jsons:
                instance = prior
                instance.status = STATUS_RUNNING
                instance.end_time = None
                instances.update(instance)
                resume = True
                logger.info(
                    "resuming engine instance %s (was %s)", prior.id, prior.status
                )
            else:
                logger.warning(
                    "--resume requested but params changed since instance %s;"
                    " starting fresh",
                    prior.id,
                )
    if instance is None:
        instance = EngineInstance(
            status=STATUS_RUNNING,
            start_time=_utcnow(),
            engine_id=variant.variant_id,
            engine_version=variant.engine_version,
            engine_variant=variant.path,
            engine_factory=variant.engine_factory,
            batch=workflow_params.batch,
            env=_pio_env(),
            runtime_conf=strip_launch_conf(variant.runtime_conf),
            data_source_params=ds_json,
            preparator_params=prep_json,
            algorithms_params=algorithms_params_json,
            serving_params=serving_json,
        )
        instances.insert(instance)
    instance_id = instance.id
    ctx = RuntimeContext(
        variant.runtime_conf,
        instance_id=instance_id,
        run_key=run_key,
        resume=resume,
    )
    profile_dir = variant.runtime_conf.get("pio.profile")
    from predictionio_tpu.obs.trace import global_tracer

    tracer = global_tracer()
    try:
        if profile_dir:
            # jax profiler trace (xplane, viewable in tensorboard/xprof) --
            # the Spark-UI replacement for training observability; the
            # per-step telemetry journal (obs.telemetry) lands in the same
            # directory via the algorithms' fit_with_checkpoint hook
            import jax

            os.makedirs(str(profile_dir), exist_ok=True)
            trace_ctx = jax.profiler.trace(str(profile_dir))
        else:
            import contextlib

            trace_ctx = contextlib.nullcontext()
        with trace_ctx:
            with tracer.span(
                "train.run",
                attrs={"instance": instance_id, "engine": variant.variant_id},
            ):
                models = engine.train(
                    ctx, engine_params,
                    skip_sanity_check=workflow_params.skip_sanity_check,
                )
        with tracer.span("train.persist", attrs={"instance": instance_id}):
            blob = engine.serialize_models(ctx, engine_params, instance_id, models)
            storage.get_model_data_models().insert(
                Model(id=instance_id, models=blob)
            )
        instance.status = STATUS_COMPLETED
        instance.end_time = _utcnow()
        instances.update(instance)
        # model persisted -> step checkpoints are dead weight (and must not
        # silently resume into a later from-scratch retrain)
        from predictionio_tpu.workflow.checkpoint import clear_run_checkpoints

        clear_run_checkpoints(ctx.run_key)
        logger.info("training finished: instance %s", instance_id)
        return instance
    except Exception:
        instance.status = STATUS_FAILED
        instance.end_time = _utcnow()
        instances.update(instance)
        logger.error("training FAILED: instance %s\n%s", instance_id, traceback.format_exc())
        raise


def run_evaluation(
    evaluation: Evaluation,
    generator: EngineParamsGenerator,
    evaluation_class: str = "",
    generator_class: str = "",
    runtime_conf: dict | None = None,
    batch: str = "",
) -> EvaluationInstance:
    """The `pio eval` core: grid-run + leaderboard, persisted for dashboard."""
    instances = storage.get_meta_data_evaluation_instances()
    instance = EvaluationInstance(
        status=STATUS_RUNNING,
        start_time=_utcnow(),
        evaluation_class=evaluation_class,
        engine_params_generator_class=generator_class,
        batch=batch,
        env=_pio_env(),
    )
    instance_id = instances.insert(instance)
    ctx = RuntimeContext(runtime_conf)
    try:
        result = MetricEvaluator(evaluation).run(ctx, generator)
        metric, extras = evaluation.metric, evaluation.metrics
        instance.status = STATUS_COMPLETED
        instance.end_time = _utcnow()
        instance.evaluator_results = result.leaderboard(metric, extras)
        instance.evaluator_results_json = result.to_json(metric, extras)
        instance.evaluator_results_html = (
            "<pre>" + result.leaderboard(metric, extras) + "</pre>"
        )
        instances.update(instance)
        logger.info("evaluation finished: instance %s", instance_id)
        return instance
    except Exception:
        instance.status = STATUS_FAILED
        instance.end_time = _utcnow()
        instances.update(instance)
        raise


def resolve_engine_instance(
    variant: EngineVariant, instance_id: str | None = None
) -> EngineInstance:
    """Latest COMPLETED instance for this variant (or an explicit id) --
    the deploy-time resolution step of reference CreateServer (SURVEY 3.2)."""
    instances = storage.get_meta_data_engine_instances()
    if instance_id:
        instance = instances.get(instance_id)
        if instance is None:
            raise LookupError(f"engine instance {instance_id!r} not found")
        return instance
    instance = instances.get_latest_completed(
        variant.variant_id, variant.engine_version, variant.path
    )
    if instance is None:
        raise LookupError(
            f"no COMPLETED training of engine variant {variant.variant_id!r}"
            f" ({variant.path}); run `pio train` first"
        )
    return instance


def engine_params_from_instance(instance: EngineInstance) -> EngineParams:
    """Reconstruct the EngineParams a training run used (deploy fidelity)."""
    return EngineParams.from_json_obj(
        {
            "datasource": {"params": json.loads(instance.data_source_params)},
            "preparator": {"params": json.loads(instance.preparator_params)},
            "algorithms": json.loads(instance.algorithms_params),
            "serving": {"params": json.loads(instance.serving_params)},
        }
    )
