"""engine.json loading + engine factory resolution.

Behavioral model: reference ``core/.../workflow/{JsonExtractor,WorkflowUtils}
.scala`` (apache/predictionio layout, unverified -- SURVEY.md section 2.3 #24,
section 5.6, Appendix B). engine.json shape kept byte-compatible; the
``sparkConf`` section becomes the runtime conf passed to RuntimeContext
(``runtimeConf`` accepted as an alias). ``engineFactory`` is a dotted Python
path to a callable returning an :class:`~predictionio_tpu.controller.Engine`
(replacing JVM reflection on an EngineFactory class).
"""

from __future__ import annotations

import importlib
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Any

from predictionio_tpu.controller.engine import Engine, EngineParams


class EngineConfigError(ValueError):
    pass


@dataclass
class EngineVariant:
    """Parsed engine.json."""

    path: str
    engine_dir: str
    variant_id: str
    description: str
    engine_factory: str
    engine_params: EngineParams
    runtime_conf: dict[str, Any] = field(default_factory=dict)

    @property
    def engine_version(self) -> str:
        return "1"


def load_engine_variant(path: str) -> EngineVariant:
    if not os.path.exists(path):
        raise EngineConfigError(f"engine variant file not found: {path}")
    with open(path) as f:
        try:
            obj = json.load(f)
        except json.JSONDecodeError as exc:
            raise EngineConfigError(f"{path} is not valid JSON: {exc}") from exc
    if "engineFactory" not in obj:
        raise EngineConfigError(f"{path} is missing required key 'engineFactory'")
    runtime_conf = obj.get("sparkConf", {}) | obj.get("runtimeConf", {})
    return EngineVariant(
        path=os.path.abspath(path),
        engine_dir=os.path.dirname(os.path.abspath(path)),
        variant_id=obj.get("id", "default"),
        description=obj.get("description", ""),
        engine_factory=obj["engineFactory"],
        engine_params=EngineParams.from_json_obj(obj),
        runtime_conf=runtime_conf,
    )


def resolve_dotted(dotted: str, engine_dir: str | None = None):
    """The one dotted-path resolver (factories, persistent model classes,
    evaluations): walks nested qualnames, prepends the engine directory to
    ``sys.path`` (parity role of the reference's engine-assembly classpath
    assembly in Runner.scala), raises EngineConfigError on failure.
    """
    if engine_dir and engine_dir not in sys.path:
        sys.path.insert(0, engine_dir)
    module_path, _, attr_path = dotted.rpartition(".")
    if not module_path:
        raise EngineConfigError(f"{dotted!r} must be a dotted module path")
    # qualnames may nest (Outer.Inner): retry shorter module prefixes
    probe = module_path
    while True:
        try:
            obj = importlib.import_module(probe)
            break
        except ModuleNotFoundError as exc:
            if "." not in probe:
                raise EngineConfigError(
                    f"cannot import module for {dotted!r}: {exc}"
                ) from exc
            probe, _, rest = probe.rpartition(".")
            attr_path = f"{rest}.{attr_path}"
    for part in attr_path.split("."):
        try:
            obj = getattr(obj, part)
        except AttributeError:
            raise EngineConfigError(
                f"{probe!r} has no attribute path {attr_path!r}"
            ) from None
    return obj


def resolve_engine_factory(dotted: str, engine_dir: str | None = None):
    return resolve_dotted(dotted, engine_dir)


def build_engine(variant: EngineVariant) -> Engine:
    factory = resolve_engine_factory(variant.engine_factory, variant.engine_dir)
    engine = factory() if callable(factory) else factory
    if hasattr(engine, "apply") and not isinstance(engine, Engine):
        engine = engine.apply()
    if not isinstance(engine, Engine):
        raise EngineConfigError(
            f"engineFactory {variant.engine_factory!r} returned"
            f" {type(engine).__name__}, expected Engine"
        )
    return engine
