"""RuntimeContext: the SparkContext replacement.

Behavioral model: reference ``core/.../workflow/WorkflowContext.scala`` +
``WorkflowParams.scala`` (apache/predictionio layout, unverified -- SURVEY.md
section 2.3 #24). Where the reference builds a SparkContext from ``sparkConf``
passthrough, we build a :class:`jax.sharding.Mesh` from the engine.json
runtime section (kept under the ``sparkConf`` key for byte-compatibility,
also accepted as ``runtimeConf``).

Mesh conventions: axes named ``("data", "model")``. ``mesh_shape`` of
``[-1, 1]`` (default) puts all devices on the data axis. Multi-host entry
uses ``jax.distributed.initialize`` when ``PIO_COORDINATOR`` is set.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Any, Mapping

logger = logging.getLogger("pio.workflow")


@dataclass
class WorkflowParams:
    """Train-workflow knobs (reference WorkflowParams)."""

    batch: str = ""
    verbose: int = 2
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False


class RuntimeContext:
    """Carries the device mesh + runtime conf through DASE calls.

    Built lazily: importing jax is deferred until a mesh is actually needed
    so storage/CLI paths stay fast.
    """

    def __init__(
        self,
        runtime_conf: Mapping[str, Any] | None = None,
        instance_id: str | None = None,
    ):
        self.runtime_conf: dict[str, Any] = dict(runtime_conf or {})
        #: engine-instance id of the current run (set by the train workflow;
        #: algorithms key step checkpoints on it)
        self.instance_id = instance_id
        #: per-stage wall-clock seconds, filled by Engine.train (the
        #: observability the reference delegated to the Spark UI, SURVEY 5.1)
        self.timings: dict[str, float] = {}
        self._mesh = None

    # -- mesh construction --------------------------------------------------
    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = self._build_mesh()
        return self._mesh

    def _build_mesh(self):
        import jax
        import numpy as np
        from jax.sharding import Mesh

        if os.environ.get("PIO_COORDINATOR"):
            # multi-host pod: one process per host, XLA collectives over ICI/DCN
            jax.distributed.initialize(
                coordinator_address=os.environ["PIO_COORDINATOR"],
                num_processes=int(os.environ.get("PIO_NUM_PROCESSES", "1")),
                process_id=int(os.environ.get("PIO_PROCESS_ID", "0")),
            )
        from predictionio_tpu.utils.platform import ensure_backend

        # a wedged or unregistered accelerator plugin must not take the
        # whole training CLI down -- ensure_backend falls back to CPU
        ensure_backend(self.runtime_conf.get("pio.platform"))
        devices = jax.devices()
        shape = self.runtime_conf.get("pio.mesh_shape", [-1, 1])
        axes = tuple(self.runtime_conf.get("pio.mesh_axes", ("data", "model")))
        if len(shape) != len(axes):
            raise ValueError(
                f"mesh_shape {shape} and mesh_axes {axes} have different ranks"
            )
        resolved = list(shape)
        if -1 in resolved:
            known = 1
            for s in resolved:
                if s != -1:
                    known *= s
            resolved[resolved.index(-1)] = len(devices) // known
        total = 1
        for s in resolved:
            total *= s
        if total > len(devices):
            raise ValueError(
                f"mesh shape {resolved} needs {total} devices, have {len(devices)}"
            )
        device_grid = np.array(devices[:total]).reshape(resolved)
        mesh = Mesh(device_grid, axes)
        logger.info("mesh: %s over %d %s device(s)",
                    dict(zip(axes, resolved)), total, devices[0].platform)
        return mesh

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    def conf(self, key: str, default: Any = None) -> Any:
        return self.runtime_conf.get(key, default)
