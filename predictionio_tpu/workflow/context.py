"""RuntimeContext: the SparkContext replacement.

Behavioral model: reference ``core/.../workflow/WorkflowContext.scala`` +
``WorkflowParams.scala`` (apache/predictionio layout, unverified -- SURVEY.md
section 2.3 #24). Where the reference builds a SparkContext from ``sparkConf``
passthrough, we build a :class:`jax.sharding.Mesh` from the engine.json
runtime section (kept under the ``sparkConf`` key for byte-compatibility,
also accepted as ``runtimeConf``).

Mesh conventions: axes named ``("data", "model")``. ``mesh_shape`` of
``[-1, 1]`` (default) puts all devices on the data axis. Multi-host entry
uses ``jax.distributed.initialize`` when ``PIO_COORDINATOR`` is set.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Mapping

logger = logging.getLogger("pio.workflow")


def _maybe_int(value) -> int | None:
    return None if value is None else int(value)


@dataclass
class WorkflowParams:
    """Train-workflow knobs (reference WorkflowParams)."""

    batch: str = ""
    verbose: int = 2
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    #: `pio train --resume`: reuse the variant's latest non-COMPLETED
    #: EngineInstance and continue from its step checkpoints instead of
    #: starting over (SURVEY.md section 5.3/5.4 -- the reference has no
    #: mid-training resume; on TPU preemption safety requires it)
    resume: bool = False


class RuntimeContext:
    """Carries the device mesh + runtime conf through DASE calls.

    Built lazily: importing jax is deferred until a mesh is actually needed
    so storage/CLI paths stay fast.
    """

    def __init__(
        self,
        runtime_conf: Mapping[str, Any] | None = None,
        instance_id: str | None = None,
        run_key: str | None = None,
        resume: bool = False,
    ):
        self.runtime_conf: dict[str, Any] = dict(runtime_conf or {})
        #: engine-instance id of the current run (set by the train workflow)
        self.instance_id = instance_id
        #: stable checkpoint key: hash of (variant id, version, params) --
        #: UNLIKE instance_id it survives re-running `pio train`, so a
        #: resumed run finds the crashed run's checkpoints
        self.run_key = run_key
        #: True on `pio train --resume`: checkpoint_manager keeps existing
        #: checkpoints; a fresh train wipes them (stale checkpoints must not
        #: silently short-circuit a from-scratch retrain)
        self.resume = resume
        #: per-stage wall-clock seconds, filled by Engine.train (the
        #: observability the reference delegated to the Spark UI, SURVEY 5.1)
        self.timings: dict[str, float] = {}
        self._mesh = None

    def checkpoint_manager(self, name: str):
        """Step-checkpoint manager for an algorithm (orbax-backed), or None.

        Keyed on the stable run_key so `pio train --resume` after a crash
        finds the previous attempt's checkpoints. On a NON-resume run any
        existing checkpoints under the key are deleted first. Contexts
        without a run key (evaluation grid candidates, ad-hoc programmatic
        trains) get None -- those runs are not resumable, and a shared
        fallback key would make concurrent trains race on one directory.
        Programmatic callers who want checkpoints pass an explicit
        ``run_key`` to RuntimeContext.
        """
        key = self.run_key or self.instance_id
        if key is None:
            return None
        from predictionio_tpu.parallel.distributed import launch_process_id

        if launch_process_id(self.runtime_conf) != 0:
            # multi-process launch: rank 0 owns the checkpoint dir; a
            # second writer on the same key would corrupt its steps
            return None
        from predictionio_tpu.workflow.checkpoint import CheckpointManager

        return CheckpointManager(f"{name}-{key}", fresh=not self.resume)

    # -- mesh construction --------------------------------------------------
    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = self._build_mesh()
        return self._mesh

    def _build_mesh(self):
        from predictionio_tpu.parallel.distributed import build_mesh, init_distributed

        # multi-host pod: one process per host, coordinator from runtime
        # conf (-- --coordinator host:port) or PIO_COORDINATOR env; XLA
        # collectives over ICI/DCN (parallel.distributed)
        init_distributed(
            coordinator=self.runtime_conf.get("pio.coordinator"),
            num_processes=_maybe_int(self.runtime_conf.get("pio.num_processes")),
            process_id=_maybe_int(self.runtime_conf.get("pio.process_id")),
        )
        from predictionio_tpu.utils.platform import ensure_backend

        # a wedged or unregistered accelerator plugin must not take the
        # whole training CLI down -- this call site opts into the
        # degradation ladder (fallback=True; a warning still records the
        # pin that was abandoned)
        ensure_backend(self.runtime_conf.get("pio.platform"), fallback=True)
        return build_mesh(
            self.runtime_conf.get("pio.mesh_shape", [-1, 1]),
            tuple(self.runtime_conf.get("pio.mesh_axes", ("data", "model"))),
            dcn_mesh_shape=self.runtime_conf.get("pio.dcn_mesh_shape"),
        )

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    def conf(self, key: str, default: Any = None) -> Any:
        return self.runtime_conf.get(key, default)
