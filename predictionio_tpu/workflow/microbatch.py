"""Dynamic micro-batching: coalesce concurrent queries into padded batches.

The query server dispatches every HTTP request as an individual model call,
so under concurrent load the accelerator (or the vectorized host path) sees
batch size 1 no matter the offered traffic. ALX (arXiv:2112.02194) wins TPU
matrix-factorization throughput by keeping work in large padded batches
with static shapes; this module applies the same principle to the serving
hot path.

``MicroBatcher`` owns a queue and one flush thread. Request threads
``submit()`` a query and block on a future; the flusher coalesces whatever
is in flight into one batch and hands it to the ``execute`` callback, then
scatters results back to the per-request futures. A batch closes on
whichever comes first:

- **size**: ``max_batch_size`` queries are waiting, or
- **deadline**: ``window_ms`` elapsed since the batch's FIRST query was
  enqueued (the latency budget a request can pay for batching), or
- **idle**: no new query arrived for ``idle_ms`` -- the burst that is
  going to coalesce has coalesced, and waiting out the rest of the
  window would buy nothing but latency (closed-loop clients park until
  this batch answers, so nothing else is coming), or
- **drain**: the server is stopping and flushes everything in flight.

Batches are padded up to a fixed ladder of **bucket sizes** (default
1/4/16/64/128) by repeating the last query, so jitted batched scorers see
one static shape per bucket and compile once per bucket instead of once
per distinct batch length. Padding results are dropped on scatter.

Per-request error isolation is the ``execute`` callback's contract: it
returns one entry per query, and an entry that is an ``Exception`` instance
fails only its own future (one bad query must not fail its batchmates).
If ``execute`` itself raises, every future in the batch gets the exception
-- callbacks that can fail partially should catch and degrade internally
(see ``QueryService._predict_batch``).

With a ``MetricsRegistry`` attached, every flush records:

- ``pio_serving_batch_size`` (histogram): real (unpadded) batch sizes,
- ``pio_serving_batch_queue_wait_seconds`` (histogram): per-query wait
  between enqueue and flush,
- ``pio_serving_batch_flush_total{reason="size"|"deadline"|"idle"|"drain"}``,
- ``pio_serving_batch_padding_rows_total``: padded slots executed.

With a ``Tracer`` attached (``obs.trace``), every flush fans spans out to
each coalesced request's trace: a per-request ``batch.queue_wait`` span
(enqueue -> flush) plus batch-level ``batch.assemble`` and
``batch.execute`` spans whose span ids are SHARED across the batch -- the
join key that answers "which requests rode the batch my request rode".

**Done-callback contract (the async serving fast path).** ``submit``'s
future supports ``add_done_callback``; the multi-process scorer uses it
to serialize and push each response from the flusher thread with ZERO
dispatcher threads on the query path. Callbacks fire synchronously
inside ``_flush`` as each future resolves, ON THE FLUSHER THREAD: a
callback that blocks (fsync, SQL, socket I/O, another future's
``.result()``, a timeout-less queue op) stalls every in-flight and
future batch, not one request. ``pio check`` C005 statically enforces
this; overflow work (e.g. a full completion ring) must be parked on
another thread, never waited for here.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from queue import Empty, Queue
from typing import Any, Callable, Sequence

from predictionio_tpu.obs.trace import NULL_TRACER, current_context

logger = logging.getLogger("pio.microbatch")

#: compile-once bucket ladder (see module docstring)
DEFAULT_BUCKETS = (1, 4, 16, 64, 128)

#: histogram buckets for batch-size observations (powers of two up to the
#: largest default bucket ladder entry x2)
SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)

#: histogram buckets for queue-wait observations (sub-ms up to a slow
#: window; anything beyond means the flusher itself was busy)
WAIT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 1.0,
)


class BatcherStopped(RuntimeError):
    """Raised by ``submit`` after ``close()``: the server is draining."""


@dataclass
class BatchConfig:
    """Serving-side micro-batching knobs (CLI: ``pio deploy
    --batch-window-ms/--max-batch-size/--batch-buckets``)."""

    max_batch_size: int = 64
    window_ms: float = 2.0
    buckets: tuple = DEFAULT_BUCKETS
    #: early-flush threshold: a batch closes once the queue has been quiet
    #: this long (<= window_ms; the window stays the hard latency cap)
    idle_ms: float = 0.5

    @property
    def enabled(self) -> bool:
        # a 1-query "batch" or a zero window degenerates to the unbatched
        # path with extra queue hops; treat both as explicit opt-outs
        return self.max_batch_size > 1 and self.window_ms > 0


@dataclass
class _Pending:
    query: Any
    future: Future = field(default_factory=Future)
    enqueued: float = field(default_factory=time.perf_counter)
    #: (trace_id, span_id) captured on the request thread at submit; the
    #: flusher fans batch-level spans out to these traces
    trace_ctx: tuple | None = None
    #: the live trace's span list, captured at submit while the root is
    #: guaranteed open -- lets the fan-out run AFTER the future resolves
    #: (off the ack latency path) and still land in the right trace
    trace_spans: list | None = None


class MicroBatcher:
    """Coalesce concurrent ``submit`` calls into padded ``execute`` batches.

    ``execute(queries)`` receives the padded query list and must return one
    result per entry (aligned); ``Exception`` instances as entries are
    delivered as per-request failures.
    """

    def __init__(
        self,
        execute: Callable[[Sequence[Any]], Sequence[Any]],
        config: BatchConfig | None = None,
        metrics=None,
        tracer=None,
    ):
        self._execute = execute
        self._config = config = config or BatchConfig()
        self._metrics = metrics
        self._tracer = tracer if tracer is not None else NULL_TRACER
        if config.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        # the effective ladder: configured buckets capped by max_batch_size,
        # which is always itself a bucket (the "size" flush shape)
        self._buckets = tuple(
            sorted(
                {int(b) for b in config.buckets if 0 < b < config.max_batch_size}
                | {int(config.max_batch_size)}
            )
        )
        self._window_s = config.window_ms / 1000.0
        self._idle_s = min(config.idle_ms, config.window_ms) / 1000.0
        self._queue: Queue = Queue()
        self._closed = False
        #: serializes submit's check-then-put against close's transition:
        #: without it a submit racing close() could enqueue into a queue
        #: whose flusher already drained and exited, stranding the future
        self._submit_lock = threading.Lock()
        self._worker = threading.Thread(
            target=self._run, name="pio-microbatcher", daemon=True
        )
        self._worker.start()

    # -- client side --------------------------------------------------------
    def submit(self, query: Any) -> Future:
        """Enqueue one query; the returned future resolves to its result
        (or raises its per-request error)."""
        with self._submit_lock:
            if self._closed:
                raise BatcherStopped(
                    "micro-batcher is draining; server stopping"
                )
            # put_nowait: the queue is unbounded, so this can never block,
            # and saying so keeps the enqueue-under-lock visibly
            # non-blocking (pio check C002)
            item = _Pending(query)
            if self._tracer.enabled:
                item.trace_ctx = current_context()
                if item.trace_ctx is not None:
                    item.trace_spans = self._tracer.live_spans(
                        item.trace_ctx[0]
                    )
            self._queue.put_nowait(item)
        return item.future

    def depth(self) -> int:
        """Approximate queries waiting for a flush -- the serving-tier
        backlog gauge (``pio_serving_queue_depth``) mirrored into
        ``/metrics`` at scrape time. Approximate by design: ``qsize`` is
        racy, and a gauge read between enqueue and flush needs no lock."""
        return self._queue.qsize()

    def close(self) -> None:
        """Stop accepting queries, flush everything in flight, join the
        flusher. Idempotent; safe to call from any thread."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
            # under the lock: every accepted submit has already put its
            # item, so the sentinel is guaranteed to sit behind all of them
            # (put_nowait: unbounded queue, cannot block)
            self._queue.put_nowait(None)
        self._worker.join(timeout=30.0)

    # -- flusher ------------------------------------------------------------
    def pad_to(self, n: int) -> int:
        """The bucket the batch pads up to: smallest ladder entry >= n."""
        for b in self._buckets:
            if n <= b:
                return b
        return n  # n > max_batch_size never happens; defensive only

    def _drain_queue(self) -> list[_Pending]:
        out: list[_Pending] = []
        while True:
            try:
                item = self._queue.get_nowait()
            except Empty:
                return out
            if item is not None:
                out.append(item)

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                # drain: everything still queued goes out as one final batch
                leftovers = self._drain_queue()
                if leftovers:
                    self._flush(leftovers, reason="drain")
                return
            batch = [item]
            stopping = False
            try:
                reason, stopping = self._collect(batch)
            except Exception:
                # the flusher is the ONLY serving thread: an unexpected
                # collection bug must flush what it has and keep running,
                # never die silently and wedge every future request
                logger.exception(
                    "batch collection failed; flushing %d queries", len(batch)
                )
                reason = "deadline"
            self._flush(batch, "drain" if stopping else reason)
            if stopping:
                return

    def _collect(self, batch: list[_Pending]) -> tuple[str, bool]:
        """Grow ``batch`` until a flush condition; returns (reason,
        stopping) where stopping means the close() sentinel was seen (the
        remaining queue is already swept into ``batch``)."""
        # sweep the backlog WITHOUT waiting first: if the flusher fell
        # behind (previous batch still executing while traffic queued),
        # everything already waiting coalesces into this batch -- the
        # window bounds waiting for FUTURE arrivals, it must never make
        # an existing backlog trickle out one query at a time
        while len(batch) < self._config.max_batch_size:
            try:
                nxt = self._queue.get_nowait()
            except Empty:
                break
            if nxt is None:
                batch.extend(self._drain_queue())
                return "drain", True
            batch.append(nxt)
        if len(batch) >= self._config.max_batch_size:
            return "size", False
        # the deadline is anchored on the FIRST query's enqueue time, not
        # on "now": if queries already spent their latency budget waiting,
        # the batch they formed flushes immediately
        deadline = batch[0].enqueued + self._window_s
        while True:
            if len(batch) >= self._config.max_batch_size:
                return "size", False
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                return "deadline", False
            try:
                nxt = self._queue.get(timeout=min(remaining, self._idle_s))
            except Empty:
                # the arrival gap exceeded idle_ms before the window
                # closed: the coalescing burst is over, flush early
                if deadline - time.perf_counter() <= 0:
                    return "deadline", False
                return "idle", False
            if nxt is None:
                batch.extend(self._drain_queue())
                return "drain", True
            batch.append(nxt)

    def _flush(self, batch: list[_Pending], reason: str) -> None:
        flush_pc = time.perf_counter()
        try:
            self._observe(batch, reason, flush_pc)
        except Exception:
            # telemetry must never take serving down (or kill the flusher)
            logger.warning("batch metrics recording failed", exc_info=True)
        exec_pc = flush_pc
        pad = 0
        try:
            padded = [p.query for p in batch]
            pad = self.pad_to(len(batch)) - len(batch)
            if pad > 0:
                padded.extend([batch[-1].query] * pad)
            exec_pc = time.perf_counter()
            results = self._execute(padded)
            if len(results) != len(padded):
                raise RuntimeError(
                    f"batch execute returned {len(results)} results for "
                    f"{len(padded)} queries"
                )
        except Exception as exc:
            # the execute callback is expected to isolate per-request
            # failures itself; reaching here is a systemic failure and the
            # whole batch reports it
            logger.warning("batch execution failed wholesale", exc_info=True)
            for p in batch:
                p.future.set_exception(exc)
            # the error traces are exactly the ones tail-based retention
            # exists to keep: they still get their queue-wait and batch
            # spans, with the execute stage marked as the failure
            self._trace_fanout(
                batch, reason, pad, flush_pc, exec_pc, status="error"
            )
            return
        # set_result/set_exception run any add_done_callback INLINE on
        # this flusher thread (the async serving tier's completion push
        # rides exactly this); callbacks must follow the module's
        # no-blocking contract or they stall every batch behind them
        for p, result in zip(batch, results):  # padding tail dropped
            if isinstance(result, Exception):
                p.future.set_exception(result)
            else:
                p.future.set_result(result)
        # AFTER the futures: every waiting request thread is already
        # woken; the fan-out's python burns flusher time, not ack latency
        self._trace_fanout(batch, reason, pad, flush_pc, exec_pc)

    def _trace_fanout(
        self,
        batch: list[_Pending],
        reason: str,
        pad: int,
        flush_pc: float,
        exec_pc: float,
        status: str = "ok",
    ) -> None:
        """Write the batch-level spans into every coalesced request's
        trace (shared span ids). Called right after execute returns;
        internally exception-safe -- tracing must never fail a batch."""
        tracer = self._tracer
        if not tracer.enabled:
            return
        try:
            done_pc = time.perf_counter()
            traced = [
                (p.trace_ctx, p.enqueued, p.trace_spans)
                for p in batch if p.trace_ctx is not None
            ]
            if not traced:
                return
            attrs = {
                "batch_size": len(batch),
                "padded_to": len(batch) + pad,
                "reason": reason,
            }
            tracer.record_fanout(
                traced,
                [
                    ("batch.assemble", flush_pc, exec_pc),
                    ("batch.execute", exec_pc, done_pc),
                ],
                attrs=attrs,
                status=status,
            )
        except Exception:
            logger.warning("batch trace recording failed", exc_info=True)

    def _observe(self, batch: list[_Pending], reason: str, now: float) -> None:
        if self._metrics is None:
            return
        self._metrics.observe(
            "pio_serving_batch_size", len(batch), buckets=SIZE_BUCKETS,
            help="Coalesced queries per flush (before bucket padding)",
        )
        for p in batch:
            self._metrics.observe(
                "pio_serving_batch_queue_wait_seconds",
                max(now - p.enqueued, 0.0),
                buckets=WAIT_BUCKETS,
                help="Per-query wait between enqueue and batch flush",
            )
        self._metrics.inc(
            "pio_serving_batch_flush_total", {"reason": reason},
            help="Batch flushes by closing reason (size|deadline|idle|drain)",
        )
        pad = self.pad_to(len(batch)) - len(batch)
        if pad:
            self._metrics.inc(
                "pio_serving_batch_padding_rows_total", amount=pad,
                help="Padded (wasted) slots executed to hit a bucket shape",
            )
