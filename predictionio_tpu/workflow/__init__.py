"""L4 Workflow: train/eval/deploy lifecycle around the controller API.

Behavioral model: reference ``core/.../workflow/`` (apache/predictionio
layout, unverified -- SURVEY.md section 2.3 #24-#26). ``WorkflowContext``'s
SparkContext construction is replaced by :class:`RuntimeContext` carrying a
JAX device mesh.
"""

from predictionio_tpu.workflow.context import RuntimeContext, WorkflowParams
from predictionio_tpu.workflow.core_workflow import run_train, run_evaluation

__all__ = ["RuntimeContext", "WorkflowParams", "run_train", "run_evaluation"]
