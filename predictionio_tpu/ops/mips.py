"""Fused quantized MIPS top-k: two-stage sub-linear retrieval on device.

Serving today is a full scan: ``batch_score_known_users`` materializes a
host-side ``[rows, items]`` f32 score buffer and argpartitions it per
request -- O(items) memory traffic per query, which caps the catalog far
below production scale. This module is the scale tentpole that replaces
the scan with the ALX-style device-resident layout (arxiv 2112.02194):

- **Stage 1** (``mips_block_topk``, a Pallas kernel in the
  ``ops/als_gram`` / ``ops/flash_attention`` house style): scan the int8
  block-quantized item table (``ops/quantize``) tile by tile, fusing the
  dequantize, the query dot-product, and a per-tile top-R selection. The
  ``[B, items]`` score matrix lives only as one ``[BB, block_items]``
  VMEM tile per grid step -- it NEVER exists in HBM; what leaves the
  kernel is ``[B, num_blocks, R]`` candidates, ``items * R/block_items``
  entries instead of ``items``.
- **Stage 2** (``RetrievalIndex.search``): merge the per-block candidates
  with one ``top_k`` over the small candidate tensor, sort the shortlist
  by catalog index (so downstream stable ranking tie-breaks by global
  index, like the scan), and re-score exactly in f32 against the resident
  table. Responses format through the existing ``topk_order`` /
  ``topk_item_scores`` tail, so whenever the shortlist contains the true
  top-k the bytes on the wire are identical to scan mode.

Containment contract: a tile's top-R is selected on the QUANTIZED scores
with padding rows masked below any real score (their zero rows would
otherwise outrank real negative scores), so the quantized global
top-``min(R, shortlist)`` is always inside the candidate set (the global
top-k of any score vector is contained in the union of per-tile top-k
for R >= k). Recall vs the exact scan is then
bounded only by quantization reorderings inside the
``score_error_bound`` window, which the shortlist margin oversamples
against -- measured >= 0.99 recall@10 at 1M items with the defaults
(bench ``mips_topk``).

Layout/VMEM budget (mirrors ``ops/als_gram``):

- Query block ``[BB, K]`` f32 and item tile ``[BI, K]`` int8 are
  exact-dim blocks (K is far below a lane and pads internally); the
  per-tile scale rides SMEM as a (1, 1) scalar.
- VMEM per program ~= BB*K*4 + BI*K*1 + BB*BI*4 (the score tile) +
  BB*R*8 (outputs): ~25 KB at the defaults (BB=8, BI=512, K=16, R=16) --
  far under the ~16 MB/core budget, leaving the auto-pipeliner room to
  stream tiles ahead of the VPU selection.
- The top-R selection is R unrolled max/first-match-argmin passes over
  the VMEM score tile (pure VPU ops: Mosaic has no in-kernel sort);
  R is static so the loop unrolls like ``als_gram``'s chunk loop.
- On CPU meshes the kernel runs in interpret mode (the
  ``ops/flash_attention`` precedent), so tier-1 CPU tests exercise this
  exact kernel code.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

from predictionio_tpu.ops.quantize import (
    BLOCK_ITEMS,
    PackedFactors,
    pack_int8_blockwise,
)

#: query rows per grid step (f32 sublane multiple)
BLOCK_QUERIES = 8

#: matches plain_attention/flash_attention's finite masked-score constant:
#: masking stays finite inside the kernel; -inf sentinels are applied at
#: the (host/XLA) merge where they are cheap and safe. Padding rows mask
#: to _NEG; already-selected columns mask STRICTLY BELOW it (_SEL), so
#: once real scores are exhausted the selection drains distinct padding
#: columns (-> merge sentinels) instead of re-emitting a selected column
#: as a duplicate candidate with a real catalog index.
_NEG = -1e30
_SEL = -2e30


def mips_block_topk(
    queries,
    q_table,
    scales,
    *,
    block_topk: int,
    num_items: int,
    interpret: bool = False,
):
    """Stage 1: per-quantization-block top-``block_topk`` candidates.

    ``queries`` f32 [B, K] (B a ``BLOCK_QUERIES`` multiple), ``q_table``
    int8 [padded_items, K], ``scales`` f32 [num_blocks, 1]. Returns
    ``(scores [B, num_blocks * R] f32, indices [B, num_blocks * R] i32)``
    with indices already global catalog indices. Padding rows of the last
    block (global index >= ``num_items``) are masked to ``_NEG`` BEFORE
    the per-tile selection: their dequantized score is exactly 0, which
    would otherwise outrank real items with negative scores and evict
    them from the candidate set, breaking the containment contract. They
    can still surface as candidates when the tile holds fewer than R real
    rows -- the merge maps any remaining index >= num_items to the
    ``(num_items, -inf)`` sentinel.
    """
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.utils.jax_compat import (
        pallas as pl,
        pallas_tpu as pltpu,
        shape_struct,
    )

    b, k = queries.shape
    padded_items = q_table.shape[0]
    nb = scales.shape[0]
    bi = padded_items // nb
    if b % BLOCK_QUERIES:
        raise ValueError(
            f"batch {b} must be a multiple of {BLOCK_QUERIES} "
            "(RetrievalIndex.search pads)"
        )
    r = block_topk
    if not 0 < r <= bi:
        raise ValueError(f"block_topk {r} must be in [1, {bi}]")
    if not 0 < num_items <= padded_items:
        raise ValueError(
            f"num_items {num_items} must be in [1, {padded_items}]"
        )

    def kernel(
        q_ref,       # VMEM [BB, K] f32
        table_ref,   # VMEM [BI, K] int8 (one quantization block)
        scale_ref,   # SMEM [1, 1] f32
        score_ref,   # VMEM [BB, 1, R] f32 out
        idx_ref,     # VMEM [BB, 1, R] i32 out
    ):
        bb = q_ref.shape[0]
        g = table_ref[...].astype(jnp.float32) * scale_ref[0, 0]  # [BI, K]
        s = jax.lax.dot_general(
            q_ref[...], g,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                         # [BB, BI]
        col = jax.lax.broadcasted_iota(jnp.int32, (bb, bi), 1)
        base = pl.program_id(1) * bi
        # padding rows dequantize to score 0, which would outrank real
        # negative scores -- mask them below any real score pre-selection
        s = jnp.where(base + col < num_items, s, _NEG)
        # R unrolled select-and-mask passes (pure VPU: Mosaic has no
        # in-kernel sort); first-match (min index) argmax so ties inside
        # a tile resolve to the lowest catalog index, like argsort
        for step in range(r):
            m = jnp.max(s, axis=1)                                # [BB]
            hit = s == m[:, None]
            local = jnp.min(jnp.where(hit, col, bi), axis=1)      # [BB]
            score_ref[:, 0, step] = m
            idx_ref[:, 0, step] = base + local
            s = jnp.where(col == local[:, None], _SEL, s)

    scores, idx = pl.pallas_call(
        kernel,
        grid=(b // BLOCK_QUERIES, nb),
        in_specs=[
            pl.BlockSpec((BLOCK_QUERIES, k), lambda i, j: (i, 0)),
            pl.BlockSpec((bi, k), lambda i, j: (j, 0)),
            pl.BlockSpec((1, 1), lambda i, j: (j, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((BLOCK_QUERIES, 1, r), lambda i, j: (i, j, 0)),
            pl.BlockSpec((BLOCK_QUERIES, 1, r), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            shape_struct((b, nb, r), jnp.float32, queries),
            shape_struct((b, nb, r), jnp.int32, queries),
        ],
        interpret=interpret,
    )(queries, q_table, scales)
    return scores.reshape(b, nb * r), idx.reshape(b, nb * r)


def _search_program(
    queries,
    q_table,
    scales,
    table_f32,
    *,
    block_topk: int,
    shortlist: int,
    num_items: int,
    interpret: bool,
):
    """Stage 1 + merge + stage-2 exact re-rank, one jitted program.

    When the whole catalog fits the stage-2 budget (``num_items <=
    shortlist``) stage 1 is skipped entirely: the shortlist IS the
    catalog and retrieval is exact by construction. Without this
    degeneration, tiny catalogs inherit stage 1's per-block candidate
    cap (``num_blocks * block_topk``, e.g. 16 for a single-block
    catalog), and a query whose seen/blackList filters eat into those
    candidates comes back short -- the replay eval's scan-vs-mips guard
    caught exactly that.
    """
    import jax
    import jax.numpy as jnp

    if num_items <= shortlist:
        width = min(shortlist, q_table.shape[0])
        base = jnp.arange(width, dtype=jnp.int32)
        sel = jnp.where(base < num_items, base, num_items)
        sel = jnp.broadcast_to(sel, (queries.shape[0], width))
        gathered = table_f32[jnp.clip(sel, 0, num_items - 1)]
        exact = jnp.einsum(
            "bk,bsk->bs", queries, gathered,
            preferred_element_type=jnp.float32,
        )
        exact = jnp.where(sel < num_items, exact, -jnp.inf)
        return sel, exact

    cand_s, cand_i = mips_block_topk(
        queries, q_table, scales,
        block_topk=block_topk, num_items=num_items, interpret=interpret,
    )
    valid = cand_i < num_items
    cand_s = jnp.where(valid, cand_s, -jnp.inf)
    cand_i = jnp.where(valid, cand_i, num_items)   # sentinel sorts last
    s = min(shortlist, cand_s.shape[1])
    _, pos = jax.lax.top_k(cand_s, s)
    sel = jnp.take_along_axis(cand_i, pos, axis=1)
    # ascending catalog order: the host tail's stable ranking then breaks
    # score ties by global index, byte-matching the full scan's order
    sel = jnp.sort(sel, axis=1)
    gathered = table_f32[jnp.clip(sel, 0, num_items - 1)]        # [B, S, K]
    exact = jnp.einsum(
        "bk,bsk->bs", queries, gathered,
        preferred_element_type=jnp.float32,
    )
    exact = jnp.where(sel < num_items, exact, -jnp.inf)
    return sel, exact


@dataclass(frozen=True)
class RetrievalConfig:
    """The ``retrieval`` engine-param block (``docs/templates.md``).

    ``mode``: "scan" (full [rows, items] host matmul, the default) or
    "mips" (this module). ``shortlist`` is the stage-2 candidate count per
    query -- the recall margin over ``num``; ``block_items`` the
    quantization/tile granularity; ``block_topk`` the per-tile candidates
    (must stay >= the largest ``num`` served for the containment
    contract). Catalogs no larger than ``shortlist`` skip stage 1 and
    retrieve exactly (the shortlist is the catalog), so the containment
    caveats only bind past that size.
    """

    mode: str = "scan"
    shortlist: int = 512
    block_items: int = BLOCK_ITEMS
    block_topk: int = 16

    def __post_init__(self) -> None:
        if self.mode not in ("scan", "mips"):
            raise ValueError(
                f"retrieval.mode must be 'scan' or 'mips', got {self.mode!r}"
            )
        if self.shortlist < 1:
            raise ValueError("retrieval.shortlist must be >= 1")
        if self.block_topk < 1:
            raise ValueError("retrieval.blockTopk must be >= 1")

    @staticmethod
    def from_params(raw) -> "RetrievalConfig":
        """Parse the engine.json ``"retrieval": {...}`` block (camelCase
        knobs, template convention); None/{} -> scan defaults."""
        if not raw:
            return RetrievalConfig()
        if not isinstance(raw, dict):
            raise ValueError(
                f'"retrieval" must be an object like {{"mode": "mips"}}, '
                f"got {raw!r}"
            )
        known = {"mode", "shortlist", "blockItems", "blockTopk"}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(
                f"unknown retrieval params {sorted(unknown)}; "
                f"expected a subset of {sorted(known)}"
            )
        return RetrievalConfig(
            mode=raw.get("mode", "scan"),
            shortlist=int(raw.get("shortlist", 512)),
            block_items=int(raw.get("blockItems", BLOCK_ITEMS)),
            block_topk=int(raw.get("blockTopk", 16)),
        )


class RetrievalIndex:
    """Device-resident two-stage retrieval index over one factor table.

    Holds the int8 packed table, its scales, and the f32 re-rank table on
    device, plus the jitted stage-1 + stage-2 program. Built lazily at
    serving time (models pickle without device state) and cached per
    (table, config) by ``models/_als_common.retrieval_index``.
    """

    def __init__(
        self,
        factors: np.ndarray,
        config: RetrievalConfig,
        *,
        interpret: bool | None = None,
    ) -> None:
        import jax

        self.config = config
        packed = pack_int8_blockwise(
            np.asarray(factors, np.float32), config.block_items
        )
        self.num_items = packed.num_items
        self.packed_bytes = packed.packed_bytes
        if interpret is None:
            # the flash_attention/als_gram precedent: CPU backends run the
            # same kernel code through the Pallas interpreter
            interpret = jax.devices()[0].platform == "cpu"
        self._q = jax.device_put(packed.q)
        self._scales = jax.device_put(packed.scales)
        self._table = jax.device_put(np.asarray(factors, np.float32))
        self._program = jax.jit(
            functools.partial(
                _search_program,
                block_topk=config.block_topk,
                shortlist=config.shortlist,
                num_items=self.num_items,
                interpret=interpret,
            )
        )

    def search(self, queries: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Top-``shortlist`` candidates for each query row.

        Returns ``(indices [B, S] i32 ascending per row, exact_scores
        [B, S] f32)``; slots past the catalog (tiny catalogs, padding)
        come back as ``(num_items, -inf)`` and drop in the format tail.
        Batches pad to the next power-of-two block multiple so serving
        sees a bounded set of compiled shapes (the micro-batching
        precedent).
        """
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        b = queries.shape[0]
        padded = BLOCK_QUERIES
        while padded < b:
            padded *= 2
        if padded != b:
            queries = np.concatenate(
                [queries, np.zeros((padded - b, queries.shape[1]), np.float32)]
            )
        idx, scores = self._program(queries, self._q, self._scales, self._table)
        return np.asarray(idx[:b]), np.asarray(scores[:b])


def reference_shortlist(
    factors: np.ndarray, queries: np.ndarray, config: RetrievalConfig
) -> np.ndarray:
    """Numpy reference of the two-stage candidate selection: the same
    quantized stage-1 arithmetic and merge the kernel fuses, as plain
    host math. This is the recall oracle -- the bench's off-hardware
    recall@k measurement runs through it (timing the interpret-mode
    kernel at catalog scale would benchmark the Pallas interpreter, the
    ``als_half_step_gbps`` precedent) and the slow tier-2 test checks the
    1M-item recall contract against it. Returns ``[B, shortlist]``
    ascending candidate catalog indices (padding slots carry
    ``padded_items`` sentinels past tiny catalogs)."""
    packed = pack_int8_blockwise(
        np.asarray(factors, np.float32), config.block_items
    )
    if packed.num_items <= config.shortlist:
        # mirror the program's exhaustive degeneration: the shortlist is
        # the catalog (sentinels normalized to num_items, like search)
        width = min(config.shortlist, packed.q.shape[0])
        base = np.arange(width, dtype=np.int32)
        sel = np.where(base < packed.num_items, base, packed.num_items)
        return np.broadcast_to(
            sel, (np.atleast_2d(queries).shape[0], width)
        ).copy()
    deq = packed.q.astype(np.float32) * np.repeat(
        packed.scales[:, 0], config.block_items
    )[:, None]
    qs = np.asarray(queries, np.float32) @ deq.T          # [B, padded]
    b, padded = qs.shape
    # mirror the kernel: padding rows masked BEFORE per-tile selection,
    # so they never evict real negative-scored items from the candidates
    qs = np.where(np.arange(padded)[None, :] < packed.num_items, qs, _NEG)
    nb = packed.num_blocks
    r = min(config.block_topk, config.block_items)
    tiles = qs.reshape(b, nb, config.block_items)
    if r < config.block_items:
        part = np.argpartition(-tiles, r - 1, axis=2)[:, :, :r]
    else:
        part = np.broadcast_to(
            np.arange(config.block_items), tiles.shape
        )[:, :, :r]
    cand_i = (
        part + (np.arange(nb) * config.block_items)[None, :, None]
    ).reshape(b, -1)
    cand_s = np.take_along_axis(qs, cand_i, axis=1)
    cand_s = np.where(cand_i < packed.num_items, cand_s, -np.inf)
    s = min(config.shortlist, cand_s.shape[1])
    if s < cand_s.shape[1]:
        top = np.argpartition(-cand_s, s - 1, axis=1)[:, :s]
    else:
        top = np.broadcast_to(np.arange(cand_s.shape[1]), cand_s.shape)
    return np.sort(np.take_along_axis(cand_i, top, axis=1), axis=1)


def mips_bytes(
    num_items: int,
    rank: int,
    batch: int,
    block_items: int = BLOCK_ITEMS,
    block_topk: int = 16,
    shortlist: int = 512,
) -> float:
    """HBM bytes the two-stage path moves for one query batch (the bench
    ``mips_topk`` GB/s denominator; the scan is bandwidth-bound, so GB/s
    on the PACKED table is the efficiency axis).

    Stage 1 reads the int8 table + scales once and re-reads the query
    block per item tile; it writes the [B, nb, R] candidate pair. Stage 2
    gathers shortlist f32 rows and writes the [B, S] pair.
    """
    padded = -(-num_items // block_items) * block_items
    nb = padded // block_items
    stage1 = (
        padded * rank                      # int8 table, one pass
        + nb * 4                           # scales
        + batch * rank * 4 * nb            # query block per tile
        + batch * nb * block_topk * 8      # candidate scores + indices
    )
    shortlist_rows = min(shortlist, nb * block_topk)
    stage2 = batch * shortlist_rows * (rank * 4 + 8 + 4)
    return float(stage1 + stage2)


def scan_bytes(num_items: int, rank: int, batch: int) -> float:
    """The full-scan counterpart: one f32 table pass plus the [B, items]
    score buffer write + the selection's read-back."""
    return float(
        num_items * rank * 4 + batch * rank * 4 + 2 * batch * num_items * 4
    )
