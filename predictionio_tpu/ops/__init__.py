"""TPU compute kernels: ragged packing, segment ops, batched linear algebra.

This package is the rebuild's "native layer": where the reference delegates
math to Spark/MLlib (SURVEY.md section 2.9 -- it has no native code of its
own), the hot ops here are jitted XLA computations and Pallas kernels
designed for the MXU: static shapes, batched matmuls, masked instead of
ragged control flow.
"""
