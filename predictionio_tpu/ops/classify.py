"""Classification kernels: multinomial Naive Bayes + logistic regression.

TPU-native replacements for the MLlib algorithms the stock classification
template invokes (``org.apache.spark.mllib.classification.{NaiveBayes,
LogisticRegressionWithLBFGS}`` -- Spark deps, SURVEY.md section 2.8):

- NB training is ONE matmul: ``onehot(labels).T @ X`` gives the class-
  conditional count matrix on the MXU; smoothing + log happens elementwise.
- LogReg trains full-batch with optax (L-BFGS when available, matching
  MLlib's optimizer; Adam fallback), all jitted.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import optax


@dataclass
class NaiveBayesModel:
    log_prior: np.ndarray       # [C]
    log_likelihood: np.ndarray  # [C, D]

    def scores(self, x: np.ndarray) -> np.ndarray:
        """Log-posterior (unnormalized) per class: [n, C]."""
        return x @ self.log_likelihood.T + self.log_prior


def train_naive_bayes(
    x: np.ndarray,
    y: np.ndarray,
    num_classes: int,
    smoothing: float = 1.0,
    mesh=None,
) -> NaiveBayesModel:
    """Multinomial NB: the count matrix is ONE matmul.

    With ``mesh``, examples shard over the ``data`` axis (zero-weight
    padding rows are masked out of the one-hot, so they contribute no
    counts) and the count matmul's cross-example reduction becomes an
    XLA-inserted psum -- MLlib NaiveBayes' per-partition aggregate+combine,
    as GSPMD sharding.
    """
    # multinomial NB is defined over counts; negative features would poison
    # the log with NaNs (MLlib's NaiveBayes rejects them the same way)
    if np.min(x) < 0:
        raise ValueError(
            "NaiveBayes requires non-negative features (multinomial counts);"
            " use logistic-regression for signed features"
        )
    from predictionio_tpu.parallel.mesh import shard_examples

    x_j, y_j, w_j, mesh = shard_examples(mesh, x, y)

    @jax.jit
    def _fit(x, y, w):
        onehot = jax.nn.one_hot(y, num_classes, dtype=x.dtype)       # [n, C]
        onehot = onehot * w[:, None]        # padding rows count nothing
        counts = onehot.T @ x                                        # [C, D] one MXU pass
        class_counts = onehot.sum(axis=0)                            # [C]
        log_prior = jnp.log(class_counts + smoothing) - jnp.log(
            w.sum() + num_classes * smoothing
        )
        smoothed = counts + smoothing
        log_likelihood = jnp.log(smoothed) - jnp.log(
            smoothed.sum(axis=1, keepdims=True)
        )
        return log_prior, log_likelihood

    log_prior, log_likelihood = _fit(x_j, y_j, w_j)
    return NaiveBayesModel(np.asarray(log_prior), np.asarray(log_likelihood))


@dataclass
class LogisticRegressionModel:
    weights: np.ndarray  # [D, C]
    bias: np.ndarray     # [C]

    def scores(self, x: np.ndarray) -> np.ndarray:
        logits = x @ self.weights + self.bias
        e = np.exp(logits - logits.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)


def train_logistic_regression(
    x: np.ndarray,
    y: np.ndarray,
    num_classes: int,
    reg: float = 1e-4,
    iterations: int = 100,
    learning_rate: float = 0.1,
    mesh=None,
) -> LogisticRegressionModel:
    """Full-batch multinomial logistic regression.

    With ``mesh``, examples shard over the ``data`` axis (rows padded to
    the axis size with zero-weight samples so the mean is exact) and
    parameters replicate; the gradient's cross-example reductions become
    XLA-inserted psums over ICI -- the Spark-executor data parallelism of
    MLlib's LogisticRegressionWithLBFGS, rebuilt as GSPMD sharding.
    """
    from predictionio_tpu.parallel.mesh import replicated, shard_examples

    x_j, y_j, w_j, mesh = shard_examples(mesh, x, y)
    if mesh is not None:
        rep = replicated(mesh)
        put_params = lambda p: jax.device_put(p, rep)
    else:
        put_params = lambda p: p
    dim = x.shape[1]
    params = put_params({
        "w": jnp.zeros((dim, num_classes), dtype=jnp.float32),
        "b": jnp.zeros((num_classes,), dtype=jnp.float32),
    })

    def loss_fn(p):
        logits = x_j @ p["w"] + p["b"]
        nll = optax.softmax_cross_entropy_with_integer_labels(logits, y_j)
        nll = (nll * w_j).sum() / w_j.sum()
        return nll + reg * (p["w"] ** 2).sum()

    if hasattr(optax, "lbfgs"):
        opt = optax.lbfgs()
        value_and_grad = optax.value_and_grad_from_state(loss_fn)

        def step(p, state):
            value, grad = value_and_grad(p, state=state)
            updates, state = opt.update(
                grad, state, p, value=value, grad=grad, value_fn=loss_fn
            )
            return optax.apply_updates(p, updates), state
    else:  # pragma: no cover - older optax
        opt = optax.adam(learning_rate)

        def step(p, state):
            grad = jax.grad(loss_fn)(p)
            updates, state = opt.update(grad, state, p)
            return optax.apply_updates(p, updates), state

    # ONE dispatch for the whole optimization: a Python loop of jitted
    # steps pays a host->device round trip per iteration (~2 s/step over a
    # remote-tunnel backend -- 100 L-BFGS iterations took 198 s; fused,
    # the same run is a few seconds)
    @jax.jit
    def run(p, state):
        return jax.lax.fori_loop(
            0,
            iterations,
            lambda _, carry: step(*carry),
            (p, state),
        )

    params, _ = run(params, opt.init(params))
    return LogisticRegressionModel(np.asarray(params["w"]), np.asarray(params["b"]))
