"""Ragged -> padded-block layout: the host-side packing step.

SURVEY.md section 7.3 flags sparse/ragged event data as the real TPU
engineering problem (per the ALX paper, arxiv 2112.02194 in PAPERS.md):
per-entity variable-length histories must become static-shape device arrays.
This module converts COO interaction triples into padded CSR blocks whose
shapes XLA can tile onto the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class PaddedCSR:
    """Padded row-major interactions.

    ``indices[r, l]`` is the column id of row ``r``'s ``l``-th interaction,
    ``values[r, l]`` its value; ``mask`` marks real entries. Rows with more
    than ``max_len`` interactions are truncated (most recent kept if
    timestamps were provided). ``indices`` of padding slots point at column
    ``num_cols`` -- callers append a zero row to factor matrices so gathers
    stay in-bounds without branching.
    """

    indices: np.ndarray  # int32 [rows, L]
    values: np.ndarray   # float32 [rows, L]
    mask: np.ndarray     # float32 [rows, L] (1.0 real, 0.0 pad)
    num_rows: int
    num_cols: int
    truncated: int       # number of interactions dropped by the cap

    @property
    def max_len(self) -> int:
        return self.indices.shape[1]


def round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def pack_padded_csr(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    num_cols: int,
    max_len: int | None = None,
    times: np.ndarray | None = None,
    len_multiple: int = 8,
    row_multiple: int = 8,
    pad_len: int | None = None,
) -> PaddedCSR:
    """COO (rows, cols, vals) -> PaddedCSR.

    - ``max_len`` caps per-row history (None = longest row).
    - ``times`` (same length) lets truncation keep the most recent entries.
    - lengths round up to ``len_multiple`` and rows to ``row_multiple`` so
      the arrays tile cleanly (TPU lanes want the trailing dims aligned).
    - ``pad_len`` forces the padded length instead of deriving it from the
      data: multi-process builds pack only local rows, and every process
      must agree on the block shape even when its local maximum is shorter.
    """
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float32)
    if rows.size == 0:
        padded_rows = max(round_up(max(num_rows, 1), row_multiple), row_multiple)
        length = pad_len or len_multiple
        return PaddedCSR(
            indices=np.full((padded_rows, length), num_cols, dtype=np.int32),
            values=np.zeros((padded_rows, length), dtype=np.float32),
            mask=np.zeros((padded_rows, length), dtype=np.float32),
            num_rows=num_rows,
            num_cols=num_cols,
            truncated=0,
        )

    counts = np.bincount(rows, minlength=num_rows)
    natural_max = int(counts.max())
    if pad_len is not None:
        if natural_max > pad_len and not max_len:
            raise ValueError(
                f"pad_len={pad_len} is shorter than the longest row "
                f"({natural_max}) and no max_len truncation was requested"
            )
        length = pad_len
    else:
        length = min(natural_max, max_len) if max_len else natural_max
        length = max(round_up(length, len_multiple), len_multiple)

    padded_rows = max(round_up(num_rows, row_multiple), row_multiple)
    indices = np.full((padded_rows, length), num_cols, dtype=np.int32)
    values = np.zeros((padded_rows, length), dtype=np.float32)
    mask = np.zeros((padded_rows, length), dtype=np.float32)

    # native C++ pack: row-bucket counting sort, O(n) vs lexsort's O(n log n)
    from predictionio_tpu import native

    truncated = native.pack_padded_csr_native(
        rows, cols, vals, times, num_rows, length, padded_rows, num_cols,
        indices, values, mask,
    )
    if truncated is not None:
        return PaddedCSR(
            indices=indices,
            values=values,
            mask=mask,
            num_rows=num_rows,
            num_cols=num_cols,
            truncated=truncated,
        )

    # numpy fallback (no toolchain / PIO_NATIVE=0)
    order = np.lexsort(
        (times if times is not None else np.zeros_like(rows), rows)
    )
    rows, cols, vals = rows[order], cols[order], vals[order]

    # within-row position of each (already row-sorted, time-ascending) entry
    row_starts = np.zeros(num_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=row_starts[1:])
    pos_in_row = np.arange(rows.size) - row_starts[rows]
    # truncation keeps the LAST (most recent) `length` entries of each row
    keep_from = np.maximum(counts[rows] - length, 0)
    keep = pos_in_row >= keep_from
    slot = (pos_in_row - keep_from)[keep]
    r_kept, c_kept, v_kept = rows[keep], cols[keep], vals[keep]
    indices[r_kept, slot] = c_kept.astype(np.int32)
    values[r_kept, slot] = v_kept
    mask[r_kept, slot] = 1.0

    return PaddedCSR(
        indices=indices,
        values=values,
        mask=mask,
        num_rows=num_rows,
        num_cols=num_cols,
        truncated=int(rows.size - keep.sum()),
    )
