"""Lloyd's K-Means on the device mesh.

Parity target: MLlib ``KMeans``, invoked by several reference engine
templates (SURVEY.md §2.8 lists it among the MLlib algorithms the template
zoo leans on). TPU-first shape:

- the assignment step is the matmul identity ``|x - c|^2 = |x|^2 - 2 x.c +
  |c|^2`` -- one ``[N, D] @ [D, K]`` product on the MXU, no pairwise loop;
- the update step is a one-hot matmul ``onehot(assign)^T @ x`` -- also MXU;
- with a mesh, rows shard over the ``data`` axis and GSPMD inserts the
  psums for the ``[K, D]`` sums / ``[K]`` counts (the Spark-shuffle
  aggregation of MLlib's per-partition accumulators, as collectives);
- k-means++ seeding runs host-side on numpy (O(N*K) once, sequential by
  nature), matching MLlib's ``k-means||`` role without the distributed
  variant's extra passes.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from predictionio_tpu.parallel.mesh import cached_by_mesh


@cached_by_mesh(maxsize=32)
def _build_step(mesh, k: int):
    row = NamedSharding(mesh, PartitionSpec("data"))
    rep = NamedSharding(mesh, PartitionSpec())

    def step(x, w, centers):
        # [N, K] squared distances via the matmul identity; padding rows
        # (w == 0) still argmin somewhere, their contribution is zeroed
        x2 = jnp.sum(x * x, axis=1, keepdims=True)
        c2 = jnp.sum(centers * centers, axis=1)
        d = x2 - 2.0 * (x @ centers.T) + c2[None]
        assign = jnp.argmin(d, axis=1)
        onehot = jax.nn.one_hot(assign, k, dtype=x.dtype) * w[:, None]
        sums = onehot.T @ x                 # [K, D] -- psum over 'data'
        counts = onehot.sum(axis=0)         # [K]
        new_centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), centers
        )
        cost = jnp.sum(jnp.min(d, axis=1) * w)
        return new_centers, assign, cost

    return jax.jit(
        step,
        in_shardings=(row, row, rep),
        out_shardings=(rep, row, rep),
    )


def _kmeanspp_init(x: np.ndarray, k: int, rng: np.random.Generator) -> np.ndarray:
    """Standard k-means++ seeding (host, numpy)."""
    n = x.shape[0]
    centers = np.empty((k, x.shape[1]), dtype=x.dtype)
    centers[0] = x[rng.integers(n)]
    d2 = np.sum((x - centers[0]) ** 2, axis=1)
    for j in range(1, k):
        total = float(d2.sum())
        if total <= 0.0:
            # every remaining point coincides with a chosen center (constant
            # or heavily duplicated data): any pick is equally (un)good --
            # rng.choice with an all-zero p would raise instead
            centers[j] = x[rng.integers(n)]
            continue
        centers[j] = x[rng.choice(n, p=d2 / total)]
        d2 = np.minimum(d2, np.sum((x - centers[j]) ** 2, axis=1))
    return centers


@dataclass
class KMeansModel:
    centers: np.ndarray       # [k, D]
    cost: float               # final within-cluster sum of squares
    iterations_run: int

    def predict(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float32)
        d = (
            np.sum(x * x, axis=1, keepdims=True)
            - 2.0 * (x @ self.centers.T)
            + np.sum(self.centers * self.centers, axis=1)[None]
        )
        return d.argmin(axis=1)


def kmeans_fit(
    x: np.ndarray,
    k: int,
    iterations: int = 20,
    tol: float = 1e-4,
    seed: int = 0,
    mesh=None,
) -> KMeansModel:
    """Fit K-Means with k-means++ init and Lloyd iterations on the mesh.

    Rows pad to a lane-aligned multiple of the mesh's ``data`` axis with
    zero weight, so every shard is equal-sized and padding never moves a
    center. Stops early when the relative cost improvement drops below
    ``tol`` (MLlib's epsilon semantics).
    """
    from predictionio_tpu.parallel.mesh import local_mesh, put_global

    if k <= 0:
        raise ValueError(f"k must be positive, got {k}")
    x = np.asarray(x, dtype=np.float32)
    if x.ndim != 2 or x.shape[0] < k:
        raise ValueError(f"need a [N>=k, D] matrix, got shape {x.shape}")
    mesh = mesh or local_mesh(1, 1)
    shards = mesh.shape.get("data", 1)
    n = x.shape[0]
    padded = -(-n // (8 * shards)) * (8 * shards)
    xp = np.pad(x, ((0, padded - n), (0, 0)))
    w = np.zeros(padded, dtype=np.float32)
    w[:n] = 1.0

    rng = np.random.default_rng(seed)
    centers = jnp.asarray(_kmeanspp_init(x, k, rng))
    row = NamedSharding(mesh, PartitionSpec("data"))
    xd = put_global(xp, row)
    wd = put_global(w, row)
    step = _build_step(mesh, k)

    prev_cost = None
    it = 0
    for it in range(1, iterations + 1):
        centers, _, cost_dev = step(xd, wd, centers)
        # step() scores the INPUT centers (assignment happens before the
        # update), so this cost lags the centers it returns by one update
        cost = float(cost_dev)
        # first iteration has no previous cost to compare against (inf
        # would make the threshold inf and stop the loop immediately)
        if prev_cost is not None and prev_cost - cost <= tol * abs(prev_cost):
            break
        prev_cost = cost
    # one assignment-only pass so the reported cost matches the RETURNED
    # centers, not the pre-update ones
    _, _, final_cost = step(xd, wd, centers)
    return KMeansModel(
        centers=np.asarray(centers), cost=float(final_cost), iterations_run=it
    )
