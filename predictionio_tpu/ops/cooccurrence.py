"""Item-item cooccurrence + LLR scoring kernels.

TPU-native replacement for the similar-product template's cooccurrence logic
and the Universal Recommender's correlated cross-occurrence (CCO) with
log-likelihood-ratio scoring (community template, Mahout CCO -- SURVEY.md
section 2.5 #37, BASELINE.json configs #3/#4).

Design: cooccurrence is a matmul. With the user-history one-hot matrix
``A [users, items]``, the cooccurrence of primary events with event-type-t
events is ``A_primary^T @ A_t`` -- the MXU's favorite shape. Only the
compact padded-CSR ``(indices, mask)`` ever leaves the host; the dense
one-hot chunks are scattered ON DEVICE inside a ``lax.scan`` (an earlier
host-built-chunk version shipped the dense [chunk, items] f32 blocks over
the interconnect -- ~4 GB for 2M events on a remote-tunnel backend, ~40x
the CSR's footprint). The ``[items, items]`` accumulator lives on device;
LLR is then elementwise.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.ragged import PaddedCSR
from predictionio_tpu.parallel.mesh import cached_by_mesh
from predictionio_tpu.utils.jax_compat import pcast_varying, shard_map


def _dense_onehot(indices, mask, num_cols: int):
    """Binarized dense [rows, num_cols] from padded-CSR rows (jittable;
    scatter-add then clamp, sentinel column dropped) -- the ONE definition
    both the host-streamed and mesh paths build their matmuls from."""
    rows = indices.shape[0]
    row_ids = jnp.repeat(jnp.arange(rows), indices.shape[1])
    out = jnp.zeros((rows, num_cols + 1), dtype=jnp.float32)
    out = out.at[row_ids, indices.reshape(-1)].add(mask.reshape(-1))
    return jnp.minimum(out[:, :num_cols], 1.0)


def _normalize(primary: PaddedCSR, other: PaddedCSR | None, mesh):
    """Shared preamble of both entry points: resolve self-cooccurrence,
    validate the shared user universe, default to a 1-device local mesh
    (same on-device path, degenerate psum)."""
    other = other if other is not None else primary
    if primary.num_rows != other.num_rows:
        raise ValueError(
            f"CSRs must share the user universe: {primary.num_rows} vs {other.num_rows}"
        )
    if mesh is None or "data" not in mesh.axis_names:
        from predictionio_tpu.parallel.mesh import local_mesh

        mesh = local_mesh(1, 1)
    return other, mesh


def cooccurrence(
    primary: PaddedCSR,
    other: PaddedCSR | None = None,
    chunk: int = 4096,
    mesh=None,
) -> np.ndarray:
    """``A_primary^T @ A_other`` over shared user rows -> [items_p, items_o].

    ``other=None`` means self-cooccurrence. Both CSRs must be row-indexed by
    the same user universe (same num_rows). User rows shard over the mesh's
    ``data`` axis (a 1-device local mesh when none is given): each device
    scatters its local users' one-hot chunks on device and accumulates
    their contribution (fixed-size chunks keep the dense buffers bounded),
    and one final ``psum`` combines the per-device ``[items_p, items_o]``
    partials over ICI -- the Spark-shuffle aggregation of the reference's
    cooccurrence jobs as a single collective.
    """
    other, mesh = _normalize(primary, other, mesh)
    return _cooccurrence_mesh(primary, other, chunk, mesh)


def _pad_rows_sentinel(csr: PaddedCSR, rows: int) -> tuple[np.ndarray, np.ndarray]:
    """(indices, mask) grown to ``rows`` rows; padding rows carry the
    sentinel column with mask 0, so they contribute nothing."""
    pad = rows - csr.indices.shape[0]
    indices = np.pad(csr.indices, ((0, pad), (0, 0)), constant_values=csr.num_cols)
    mask = np.pad(csr.mask, ((0, pad), (0, 0)))
    return indices, mask


@cached_by_mesh(maxsize=64)
def _build_cooc_fn(
    mesh,
    chunk: int,
    num_p: int,
    num_o: int,
    len_p: int,
    len_o: int,
    top_k: int,
    llr: bool,
    drop_diagonal: bool,
    total: float,
):
    """The jitted sharded cooccurrence program, cached by every static it
    closes over (a fresh closure per call would retrace + recompile each
    of URAlgorithm's per-event-type calls and every re-train). ``top_k ==
    0`` returns the raw replicated accumulator; otherwise the (optionally
    LLR-weighted) per-row top-k indicators, computed ON DEVICE so the
    [items, items] matrix never crosses the host link. The LLR totals are
    runtime ARGUMENTS (replicated), not baked constants, so one compiled
    program serves every event type of the same shape.
    """
    from jax.sharding import PartitionSpec

    def local(idx_p, msk_p, idx_o, msk_o, row_t, col_t):
        n_chunks = idx_p.shape[0] // chunk

        def body(acc, args):
            i_p, m_p, i_o, m_o = args
            return (
                acc
                + _dense_onehot(i_p, m_p, num_p).T
                @ _dense_onehot(i_o, m_o, num_o),
                None,
            )

        def split(a):
            return a.reshape(n_chunks, chunk, a.shape[1])

        # fresh constants are "unvarying" under shard_map's vma tracking;
        # the scan carry must match the (varying) body output type
        acc0 = pcast_varying(
            jnp.zeros((num_p, num_o), dtype=jnp.float32), "data"
        )
        acc, _ = jax.lax.scan(
            body, acc0, (split(idx_p), split(msk_p), split(idx_o), split(msk_o))
        )
        acc = jax.lax.psum(acc, "data")
        if top_k == 0:
            return acc
        m = _llr_math(acc, row_t, col_t, total) if llr else acc
        if drop_diagonal:
            m = jnp.where(jnp.eye(num_p, dtype=bool), -jnp.inf, m)
        vals, idx = jax.lax.top_k(m, top_k)
        return idx.astype(jnp.int32), jnp.where(jnp.isfinite(vals), vals, 0.0)

    row = PartitionSpec("data")
    rep = PartitionSpec()
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(row, row, row, row, rep, rep),
            out_specs=rep if top_k == 0 else (rep, rep),
        )
    )


def _run_cooc(
    primary: PaddedCSR,
    other: PaddedCSR,
    chunk: int,
    mesh,
    *,
    top_k: int = 0,
    llr: bool = False,
    drop_diagonal: bool = False,
    total: float = 0.0,
    row_totals=None,
    col_totals=None,
):
    """Pad, upload (once per distinct CSR), run the cached program, fetch.

    Accepts ``ShardedPaddedCSR`` inputs (parallel.reader): each process
    then contributes only its local user-row slice via
    make_array_from_process_local_data instead of uploading a full host
    copy -- the retention-bounded multi-host path.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from predictionio_tpu.parallel.reader import ShardedPaddedCSR, cooc_global_rows

    data_size = int(mesh.shape["data"])
    sharded = isinstance(primary, ShardedPaddedCSR)
    if sharded != isinstance(other, ShardedPaddedCSR):
        raise ValueError(
            "mixing a sharded-reader CSR with a full host CSR is not "
            "supported: build both sides sharded (or neither)"
        )
    if sharded:
        rows = primary.global_rows
        expect = cooc_global_rows(primary.num_rows, mesh, chunk)
        if rows != expect or other.global_rows != rows:
            raise ValueError(
                f"sharded CSR was built for a different mesh/chunk layout "
                f"(rows {rows}/{other.global_rows}, this call expects "
                f"{expect}); rebuild with build_cooc_csr_sharded(mesh=..., "
                f"chunk={chunk})"
            )
        per_device = rows // data_size
        chunk = max(1, min(chunk, per_device))
    else:
        # base row math on the PHYSICAL (row_multiple-padded) CSR rows, not
        # num_rows: pack_padded_csr rounds rows up, and a target below the
        # physical count would make _pad_rows_sentinel's pad width negative
        phys_rows = max(primary.indices.shape[0], other.indices.shape[0])
        per_device = -(-phys_rows // data_size)
        chunk = max(1, min(chunk, per_device))
        # every device scans the same number of fixed-size chunks: pad the
        # user universe so rows = data * chunks_per_device * chunk
        chunks_per_device = -(-per_device // chunk)
        rows = data_size * chunks_per_device * chunk
    fn = _build_cooc_fn(
        mesh, chunk, primary.num_cols, other.num_cols,
        primary.max_len, other.max_len,
        top_k, llr, drop_diagonal, float(total),
    )
    from predictionio_tpu.parallel.mesh import fetch_global, put_global

    sharding = NamedSharding(mesh, PartitionSpec("data"))
    rep = NamedSharding(mesh, PartitionSpec())
    if sharded:
        put_local = lambda a, L: jax.make_array_from_process_local_data(
            sharding, a, (rows, L)
        )
        g_idx_p = put_local(primary.local.indices, primary.max_len)
        g_msk_p = put_local(primary.local.mask, primary.max_len)
        if other is primary:
            g_idx_o, g_msk_o = g_idx_p, g_msk_p
        else:
            g_idx_o = put_local(other.local.indices, other.max_len)
            g_msk_o = put_local(other.local.mask, other.max_len)
    else:
        put = lambda a: put_global(a, sharding)
        idx_p, msk_p = _pad_rows_sentinel(primary, rows)
        g_idx_p, g_msk_p = put(idx_p), put(msk_p)
        if other is primary:  # self-cooccurrence: one upload serves both
            g_idx_o, g_msk_o = g_idx_p, g_msk_p
        else:
            idx_o, msk_o = _pad_rows_sentinel(other, rows)
            g_idx_o, g_msk_o = put(idx_o), put(msk_o)
    dummy = np.zeros(1, np.float32)
    row_t = jax.device_put(
        np.asarray(row_totals if row_totals is not None else dummy, np.float32),
        rep,
    )
    col_t = jax.device_put(
        np.asarray(col_totals if col_totals is not None else dummy, np.float32),
        rep,
    )
    out = fn(g_idx_p, g_msk_p, g_idx_o, g_msk_o, row_t, col_t)
    return jax.tree_util.tree_map(fetch_global, out)


def _cooccurrence_mesh(primary: PaddedCSR, other: PaddedCSR, chunk: int, mesh):
    return _run_cooc(primary, other, chunk, mesh)


def distinct_user_counts(csr: PaddedCSR) -> np.ndarray:
    """Per-item distinct-user count in O(nnz) on the host -- the diagonal of
    the (binarized) self-cooccurrence, without the [items, items] matmul."""
    rows = np.repeat(np.arange(csr.indices.shape[0]), csr.max_len)
    cols = csr.indices.reshape(-1)
    valid = (csr.mask.reshape(-1) > 0) & (cols < csr.num_cols)
    pairs = np.unique(
        rows[valid].astype(np.int64) * csr.num_cols + cols[valid].astype(np.int64)
    )
    return np.bincount(
        (pairs % csr.num_cols).astype(np.int64), minlength=csr.num_cols
    ).astype(np.float32)


def _xlogx(x):
    return jnp.where(x > 0, x * jnp.log(x), 0.0)


def _llr_math(k11, row_totals, col_totals, total):
    """G^2 log-likelihood-ratio over the 2x2 contingency per (i, j) pair."""
    k12 = jnp.maximum(row_totals[:, None] - k11, 0.0)
    k21 = jnp.maximum(col_totals[None, :] - k11, 0.0)
    k22 = jnp.maximum(total - k11 - k12 - k21, 0.0)
    h_k = _xlogx(k11) + _xlogx(k12) + _xlogx(k21) + _xlogx(k22)
    h_rows = _xlogx(k11 + k12) + _xlogx(k21 + k22)
    h_cols = _xlogx(k11 + k21) + _xlogx(k12 + k22)
    h_total = _xlogx(k11 + k12 + k21 + k22)
    llr = 2.0 * (h_k + h_total - h_rows - h_cols)
    return jnp.where(k11 > 0, jnp.maximum(llr, 0.0), 0.0)


_llr_kernel = jax.jit(_llr_math)


def llr_scores(
    cooc: np.ndarray,
    row_totals: np.ndarray,
    col_totals: np.ndarray,
    total: float,
) -> np.ndarray:
    """LLR significance of each cooccurrence count (same shape as cooc)."""
    return np.asarray(
        _llr_kernel(
            jnp.asarray(cooc, dtype=jnp.float32),
            jnp.asarray(row_totals, dtype=jnp.float32),
            jnp.asarray(col_totals, dtype=jnp.float32),
            float(total),
        )
    )


def cooccurrence_indicators(
    primary: PaddedCSR,
    other: PaddedCSR | None = None,
    *,
    top_k: int,
    llr_row_totals: np.ndarray | None = None,
    llr_col_totals: np.ndarray | None = None,
    total: float | None = None,
    drop_diagonal: bool | None = None,
    chunk: int = 4096,
    mesh=None,
):
    """Fused cooc -> (optional LLR) -> per-row top-k, entirely on device.

    Returns ``(indices [items_p, k], values [items_p, k])`` like
    :func:`top_k_sparsify`. Providing ``llr_row_totals``/``llr_col_totals``
    (+ ``total``) applies the G^2 weighting before ranking. The unfused
    chain fetches the [items_p, items_o] matrix to the host TWICE (once
    after cooccurrence, once into top_k_sparsify) -- ~800 MB at 10k items,
    seconds of pure transfer on a remote-tunnel backend -- where the fused
    form downloads only the [items_p, k] indicator arrays.

    Ties may rank in a different order than the host ``argpartition`` path;
    the selected VALUES are identical.
    """
    self_cooc = other is None or other is primary
    other, mesh = _normalize(primary, other, mesh)
    if (llr_row_totals is None) != (llr_col_totals is None):
        raise ValueError("provide both llr totals or neither")
    if llr_row_totals is not None and total is None:
        raise ValueError("LLR weighting needs the grand total")
    if drop_diagonal is None:
        drop_diagonal = self_cooc
    if drop_diagonal and primary.num_cols != other.num_cols:
        raise ValueError("drop_diagonal requires a square matrix")
    idx, vals = _run_cooc(
        primary,
        other,
        chunk,
        mesh,
        top_k=min(top_k, other.num_cols),
        llr=llr_row_totals is not None,
        drop_diagonal=drop_diagonal,
        total=float(total or 0.0),
        row_totals=llr_row_totals,
        col_totals=llr_col_totals,
    )
    return np.asarray(idx), np.asarray(vals)


def top_k_sparsify(matrix: np.ndarray, k: int, drop_diagonal: bool = True):
    """Keep the top-k entries per ROW -> (indices [n, k], values [n, k]).

    The serving-side 'indicator' form (reference UR keeps top-N correlators
    per item in Elasticsearch)."""
    m = matrix.copy()
    if drop_diagonal and m.shape[0] == m.shape[1]:
        np.fill_diagonal(m, -np.inf)
    k = min(k, m.shape[1])
    idx = np.argpartition(-m, k - 1, axis=1)[:, :k]
    vals = np.take_along_axis(m, idx, axis=1)
    order = np.argsort(-vals, axis=1)
    idx = np.take_along_axis(idx, order, axis=1)
    vals = np.take_along_axis(vals, order, axis=1)
    vals = np.where(np.isfinite(vals), vals, 0.0)
    return idx.astype(np.int32), vals.astype(np.float32)
