"""Item-item cooccurrence + LLR scoring kernels.

TPU-native replacement for the similar-product template's cooccurrence logic
and the Universal Recommender's correlated cross-occurrence (CCO) with
log-likelihood-ratio scoring (community template, Mahout CCO -- SURVEY.md
section 2.5 #37, BASELINE.json configs #3/#4).

Design: cooccurrence is a matmul. With the user-history one-hot matrix
``A [users, items]``, the cooccurrence of primary events with event-type-t
events is ``A_primary^T @ A_t`` -- the MXU's favorite shape. Users stream
through in chunks (host builds each dense chunk from the padded CSR); the
``[items, items]`` accumulator lives on device. LLR is then elementwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.ragged import PaddedCSR


def _dense_onehot(indices, mask, num_cols: int):
    """Binarized dense [rows, num_cols] from padded-CSR rows (jittable;
    scatter-add then clamp, sentinel column dropped) -- the ONE definition
    both the host-streamed and mesh paths build their matmuls from."""
    rows = indices.shape[0]
    row_ids = jnp.repeat(jnp.arange(rows), indices.shape[1])
    out = jnp.zeros((rows, num_cols + 1), dtype=jnp.float32)
    out = out.at[row_ids, indices.reshape(-1)].add(mask.reshape(-1))
    return jnp.minimum(out[:, :num_cols], 1.0)


@functools.partial(jax.jit, static_argnames=("num_cols",), donate_argnums=(3,))
def _accumulate_chunk(indices, mask, other_onehot, acc, *, num_cols):
    """acc += onehot(indices)^T @ other_onehot for one user chunk."""
    return acc + _dense_onehot(indices, mask, num_cols).T @ other_onehot


def _onehot_chunk(csr: PaddedCSR, start: int, end: int) -> np.ndarray:
    chunk = end - start
    out = np.zeros((chunk, csr.num_cols), dtype=np.float32)
    idx = csr.indices[start:end]
    msk = csr.mask[start:end] > 0
    rows = np.repeat(np.arange(chunk), idx.shape[1])
    valid = msk.reshape(-1) & (idx.reshape(-1) < csr.num_cols)
    out[rows[valid], idx.reshape(-1)[valid]] = 1.0
    return out


def cooccurrence(
    primary: PaddedCSR,
    other: PaddedCSR | None = None,
    chunk: int = 4096,
    mesh=None,
) -> np.ndarray:
    """``A_primary^T @ A_other`` over shared user rows -> [items_p, items_o].

    ``other=None`` means self-cooccurrence. Both CSRs must be row-indexed by
    the same user universe (same num_rows). With ``mesh``, user rows shard
    over the ``data`` axis: each device accumulates its local users'
    contribution (scanning fixed-size chunks so the dense one-hot buffers
    stay bounded) and one final ``psum`` combines the per-device
    ``[items_p, items_o]`` partials over ICI -- the Spark-shuffle
    aggregation of the reference's cooccurrence jobs as a single collective.
    """
    other = other if other is not None else primary
    if primary.num_rows != other.num_rows:
        raise ValueError(
            f"CSRs must share the user universe: {primary.num_rows} vs {other.num_rows}"
        )
    if mesh is not None and "data" not in mesh.axis_names:
        mesh = None  # custom-axis mesh: run the host-streamed path
    if mesh is not None and mesh.shape["data"] > 1:
        return _cooccurrence_mesh(primary, other, chunk, mesh)
    n_users = primary.num_rows
    acc = jnp.zeros((primary.num_cols, other.num_cols), dtype=jnp.float32)
    for start in range(0, n_users, chunk):
        end = min(start + chunk, n_users)
        acc = _accumulate_chunk(
            jnp.asarray(primary.indices[start:end]),
            jnp.asarray(primary.mask[start:end]),
            jnp.asarray(_onehot_chunk(other, start, end)),
            acc,
            num_cols=primary.num_cols,
        )
    return np.asarray(acc)


def _pad_rows_sentinel(csr: PaddedCSR, rows: int) -> tuple[np.ndarray, np.ndarray]:
    """(indices, mask) grown to ``rows`` rows; padding rows carry the
    sentinel column with mask 0, so they contribute nothing."""
    pad = rows - csr.indices.shape[0]
    indices = np.pad(csr.indices, ((0, pad), (0, 0)), constant_values=csr.num_cols)
    mask = np.pad(csr.mask, ((0, pad), (0, 0)))
    return indices, mask


def _cooccurrence_mesh(
    primary: PaddedCSR, other: PaddedCSR, chunk: int, mesh
) -> np.ndarray:
    from jax.sharding import NamedSharding, PartitionSpec

    data_size = int(mesh.shape["data"])
    # base row math on the PHYSICAL (row_multiple-padded) CSR rows, not
    # num_rows: pack_padded_csr rounds rows up, and a target below the
    # physical count would make _pad_rows_sentinel's pad width negative
    phys_rows = max(primary.indices.shape[0], other.indices.shape[0])
    per_device = -(-phys_rows // data_size)
    chunk = max(1, min(chunk, per_device))
    # every device scans the same number of fixed-size chunks: pad the user
    # universe so rows = data * chunks_per_device * chunk
    chunks_per_device = -(-per_device // chunk)
    rows = data_size * chunks_per_device * chunk
    idx_p, msk_p = _pad_rows_sentinel(primary, rows)
    if other is primary:  # self-cooccurrence: don't build/ship a second copy
        idx_o, msk_o = idx_p, msk_p
    else:
        idx_o, msk_o = _pad_rows_sentinel(other, rows)
    num_p, num_o = primary.num_cols, other.num_cols

    def local(idx_p, msk_p, idx_o, msk_o):
        local_rows = idx_p.shape[0]
        n_chunks = local_rows // chunk

        def body(acc, args):
            i_p, m_p, i_o, m_o = args
            return (
                acc
                + _dense_onehot(i_p, m_p, num_p).T
                @ _dense_onehot(i_o, m_o, num_o),
                None,
            )

        def split(a):
            return a.reshape(n_chunks, chunk, a.shape[1])

        # fresh constants are "unvarying" under shard_map's vma tracking;
        # the scan carry must match the (varying) body output type
        acc0 = jax.lax.pcast(
            jnp.zeros((num_p, num_o), dtype=jnp.float32), "data", to="varying"
        )
        acc, _ = jax.lax.scan(
            body, acc0, (split(idx_p), split(msk_p), split(idx_o), split(msk_o))
        )
        return jax.lax.psum(acc, "data")

    row = PartitionSpec("data")
    rep = PartitionSpec()
    fn = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(row, row, row, row),
            out_specs=rep,
        )
    )
    from predictionio_tpu.parallel.mesh import fetch_global, put_global

    sharding = NamedSharding(mesh, row)
    put = lambda a: put_global(a, sharding)
    return fetch_global(fn(put(idx_p), put(msk_p), put(idx_o), put(msk_o)))


def distinct_user_counts(csr: PaddedCSR) -> np.ndarray:
    """Per-item distinct-user count in O(nnz) on the host -- the diagonal of
    the (binarized) self-cooccurrence, without the [items, items] matmul."""
    rows = np.repeat(np.arange(csr.indices.shape[0]), csr.max_len)
    cols = csr.indices.reshape(-1)
    valid = (csr.mask.reshape(-1) > 0) & (cols < csr.num_cols)
    pairs = np.unique(
        rows[valid].astype(np.int64) * csr.num_cols + cols[valid].astype(np.int64)
    )
    return np.bincount(
        (pairs % csr.num_cols).astype(np.int64), minlength=csr.num_cols
    ).astype(np.float32)


def _xlogx(x):
    return jnp.where(x > 0, x * jnp.log(x), 0.0)


@jax.jit
def _llr_kernel(k11, row_totals, col_totals, total):
    """G^2 log-likelihood-ratio over the 2x2 contingency per (i, j) pair."""
    k12 = jnp.maximum(row_totals[:, None] - k11, 0.0)
    k21 = jnp.maximum(col_totals[None, :] - k11, 0.0)
    k22 = jnp.maximum(total - k11 - k12 - k21, 0.0)
    h_k = _xlogx(k11) + _xlogx(k12) + _xlogx(k21) + _xlogx(k22)
    h_rows = _xlogx(k11 + k12) + _xlogx(k21 + k22)
    h_cols = _xlogx(k11 + k21) + _xlogx(k12 + k22)
    h_total = _xlogx(k11 + k12 + k21 + k22)
    llr = 2.0 * (h_k + h_total - h_rows - h_cols)
    return jnp.where(k11 > 0, jnp.maximum(llr, 0.0), 0.0)


def llr_scores(
    cooc: np.ndarray,
    row_totals: np.ndarray,
    col_totals: np.ndarray,
    total: float,
) -> np.ndarray:
    """LLR significance of each cooccurrence count (same shape as cooc)."""
    return np.asarray(
        _llr_kernel(
            jnp.asarray(cooc, dtype=jnp.float32),
            jnp.asarray(row_totals, dtype=jnp.float32),
            jnp.asarray(col_totals, dtype=jnp.float32),
            float(total),
        )
    )


def top_k_sparsify(matrix: np.ndarray, k: int, drop_diagonal: bool = True):
    """Keep the top-k entries per ROW -> (indices [n, k], values [n, k]).

    The serving-side 'indicator' form (reference UR keeps top-N correlators
    per item in Elasticsearch)."""
    m = matrix.copy()
    if drop_diagonal and m.shape[0] == m.shape[1]:
        np.fill_diagonal(m, -np.inf)
    k = min(k, m.shape[1])
    idx = np.argpartition(-m, k - 1, axis=1)[:, :k]
    vals = np.take_along_axis(m, idx, axis=1)
    order = np.argsort(-vals, axis=1)
    idx = np.take_along_axis(idx, order, axis=1)
    vals = np.take_along_axis(vals, order, axis=1)
    vals = np.where(np.isfinite(vals), vals, 0.0)
    return idx.astype(np.int32), vals.astype(np.float32)
