"""Feature vectorization: text hashing + categorical one-hot.

Parity roles: reference ``e2/.../engine/BinaryVectorizer.scala`` (categorical
properties -> binary vectors) and the classification templates' ad-hoc
tokenization (SURVEY.md section 2.5 #36). Feature hashing keeps the feature
space dense and static-shape -- the TPU-friendly choice for text.
"""

from __future__ import annotations

import re
import zlib
from dataclasses import dataclass, field

import numpy as np

_TOKEN_RE = re.compile(r"[A-Za-z0-9']+")


def tokenize(text: str) -> list[str]:
    return [t.lower() for t in _TOKEN_RE.findall(text)]


def hash_token(token: str, dim: int) -> int:
    # crc32: fast, stable across processes (unlike Python's salted hash)
    return zlib.crc32(token.encode("utf-8")) % dim


def hashing_vectorize(texts: list[str], dim: int = 4096) -> np.ndarray:
    """Bag-of-words feature hashing -> dense [n, dim] float32 counts."""
    out = np.zeros((len(texts), dim), dtype=np.float32)
    for i, text in enumerate(texts):
        for token in tokenize(text):
            out[i, hash_token(token, dim)] += 1.0
    return out


@dataclass
class BinaryVectorizer:
    """Categorical (field, value) pairs -> fixed binary columns.

    Fit on training dicts; unseen categories at transform time are ignored
    (reference BinaryVectorizer contract).
    """

    index: dict[tuple[str, str], int] = field(default_factory=dict)

    @classmethod
    def fit(cls, records: list[dict], fields: list[str]) -> "BinaryVectorizer":
        index: dict[tuple[str, str], int] = {}
        for record in records:
            for f in fields:
                if f in record:
                    key = (f, str(record[f]))
                    index.setdefault(key, len(index))
        return cls(index=index)

    @property
    def dim(self) -> int:
        return len(self.index)

    @property
    def _fields(self) -> list[str]:
        return sorted({f for f, _ in self.index})

    def transform(self, records: list[dict]) -> np.ndarray:
        out = np.zeros((len(records), max(self.dim, 1)), dtype=np.float32)
        fields = self._fields
        for i, record in enumerate(records):
            for f in fields:
                if f in record:
                    j = self.index.get((f, str(record[f])))
                    if j is not None:
                        out[i, j] = 1.0
        return out


@dataclass
class NumericVectorizer:
    """Numeric property columns -> dense matrix (missing -> 0)."""

    fields: list[str]

    def transform(self, records: list[dict]) -> np.ndarray:
        out = np.zeros((len(records), len(self.fields)), dtype=np.float32)
        for i, record in enumerate(records):
            for j, f in enumerate(self.fields):
                v = record.get(f)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[i, j] = float(v)
        return out
