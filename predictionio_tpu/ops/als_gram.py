"""Fused Pallas gather->Gram half-step kernels for ALS.

The ALS half-step tail (``parallel/als.py``) is gather- and bandwidth-
bound: the XLA path materializes the gathered opposite-side factors as a
``[rows, L, K]`` HBM intermediate (one write + two einsum read passes)
before reducing it to a ``[K, K]`` Gram and ``[K]`` rhs per row -- the
ragged-data bottleneck the ALX paper (arxiv 2112.02194, PAPERS.md) names
as THE TPU engineering problem for matrix factorization. This kernel
streams padded-CSR row blocks through VMEM and performs the gather with
double-buffered row DMAs from the HBM-resident factor table, accumulating
each row's Gram/rhs in f32 on-chip; the ``[rows, L, K]`` intermediate
never exists in HBM, so the half-step's HBM traffic drops from
``~3 * rows * L * K * itemsize`` (write + 2 reads) to ONE random-gather
read pass of ``rows * L * K * itemsize``.

Contract (shared with the XLA path -- ``parallel.als`` padding invariant):

- ``indices[r, l]`` selects a row of ``factors``; padding slots (and, in
  model-sharded mode, out-of-shard hits) point at a trailing ZERO row, so
  every padding contribution dies through the gathered zeros -- no mask
  stream crosses HBM.
- ``factors`` is ``[S + 1, K]`` (zero row appended), f32 or bf16; Gram and
  rhs accumulate f32 regardless (the ALX mixed-precision recipe).
- explicit mode:  gram[r] = sum_l y y^T,          rhs[r] = sum_l v * y
- implicit mode:  gram[r] = sum_l (alpha v) y y^T, rhs[r] = sum_l (1 + alpha v) y
  (the YtY global term, the ridge, and the solve stay OUTSIDE the kernel:
  they are [K, K]-small and shared with the XLA path bit-for-bit).

Layout/VMEM budget (mirrors the hard-won notes in ``ops/flash_attention``):

- Blocks keep their last two dims equal to the array dims (K is far below
  a lane, so (BR, K, K) / (BR, K) output blocks are exact-dim blocks; the
  [BR, C, K] gather scratch pads K up to a lane internally).
- The index block rides SMEM -- DMA source addressing is scalar work; a
  [BR, L] i32 block is BR*L*4 bytes (8 KB at BR=8, L=256).
- VMEM per program ~= BR*L*4 (values) + 2*BR*C*K*itemsize (double-buffered
  gather scratch) + BR*(K*K + K)*4 (accumulator blocks): ~0.3 MB at the
  bench shape (BR=8, L=256, C=128, K=16, bf16 table) -- far under the
  ~16 MB/core budget, leaving the auto-pipeliner room to double-buffer
  the idx/val streams across grid steps.
- The gather itself is one row-DMA per (row, l) slot: the DMA engine keeps
  BR*C descriptors in flight per chunk while the MXU folds the PREVIOUS
  chunk (classic two-slot double buffering over the L dimension). Each
  descriptor moves only K*itemsize bytes, so the gather runs at the
  random-row bandwidth the layout admits -- the win over XLA is not a
  faster gather but the intermediate that never hits HBM.
- On CPU meshes the kernels run in interpret mode (the
  ``ops/flash_attention`` precedent), so tier-1 CPU tests exercise this
  exact kernel code.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from predictionio_tpu.utils.jax_compat import (
    pallas as pl,
    pallas_tpu as pltpu,
    shape_struct,
)

#: rows per grid step (a CAP: the largest power of two <= this that divides
#: the block's rows is used, so a 24-row block split over a 2-device data
#: axis -- 12 rows per device -- runs at BR=4 instead of failing). 8 keeps
#: the [BR, C, K] gather scratch small on the aligned common case.
BLOCK_ROWS = 8

#: gather chunk (columns of the L dimension folded per double-buffer slot);
#: the largest of these dividing L is used, so L only needs 8-alignment.
_CHUNKS = (256, 128, 64, 32, 16, 8)


def _pick_chunk(pad_len: int) -> int:
    for cand in _CHUNKS:
        if cand <= pad_len and pad_len % cand == 0:
            return cand
    raise ValueError(
        f"padded length {pad_len} is not a multiple of 8 (pack_padded_csr "
        "guarantees len_multiple=8)"
    )


def _gram_rhs_kernel(
    idx_ref,    # SMEM [BR, L] i32
    val_ref,    # VMEM [BR, L] f32
    alpha_ref,  # SMEM [1, 1]  f32 (ignored in explicit mode)
    table_ref,  # ANY  [S + 1, K] factor dtype (stays in HBM)
    gram_ref,   # VMEM [BR, K, K] f32 out
    rhs_ref,    # VMEM [BR, K] f32 out
    gathered,   # VMEM scratch [2, BR, C, K] factor dtype
    sem,        # DMA semaphores [2] (one per buffer slot)
    *,
    implicit: bool,
    chunk: int,
):
    br, pad_len = idx_ref.shape
    n_chunks = pad_len // chunk
    k = table_ref.shape[1]

    def dma(slot: int, ci: int, p):
        r, cl = p // chunk, p % chunk
        return pltpu.make_async_copy(
            table_ref.at[idx_ref[r, ci * chunk + cl]],
            gathered.at[slot, r, cl],
            sem.at[slot],
        )

    def issue(ci: int) -> None:
        slot = ci % 2

        def start(p, carry):
            dma(slot, ci, p).start()
            return carry

        jax.lax.fori_loop(0, br * chunk, start, None)

    def drain(ci: int) -> None:
        slot = ci % 2

        def wait(p, carry):
            dma(slot, ci, p).wait()
            return carry

        jax.lax.fori_loop(0, br * chunk, wait, None)

    issue(0)
    gram_acc = jnp.zeros((br, k, k), jnp.float32)
    rhs_acc = jnp.zeros((br, k), jnp.float32)
    # n_chunks is static: the chunk loop unrolls, keeping the double-buffer
    # slot index STATIC (Mosaic cannot dynamically index the sublane-major
    # scratch on the compute side; the DMA .at[] indices may stay dynamic)
    for ci in range(n_chunks):
        if ci + 1 < n_chunks:
            issue(ci + 1)  # next chunk's DMAs fly while this one folds
        drain(ci)
        g = gathered[ci % 2].astype(jnp.float32)              # [BR, C, K]
        v = val_ref[:, ci * chunk : (ci + 1) * chunk]         # [BR, C]
        if implicit:
            w = alpha_ref[0, 0] * v
            gram_w, rhs_w = w, 1.0 + w
        else:
            gram_w, rhs_w = None, v
        lhs = g if gram_w is None else g * gram_w[..., None]
        gram_acc = gram_acc + jax.lax.dot_general(
            lhs, g,
            dimension_numbers=(((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        rhs_acc = rhs_acc + jnp.sum(g * rhs_w[..., None], axis=1)
    gram_ref[...] = gram_acc
    rhs_ref[...] = rhs_acc


def gram_rhs(
    indices,
    values,
    factors,
    alpha=0.0,
    *,
    implicit: bool = False,
    interpret: bool = False,
    block_rows: int = BLOCK_ROWS,
):
    """Fused gather->Gram/rhs over one padded-CSR block.

    ``indices`` i32 [R, L] (padding -> the trailing zero factor row),
    ``values`` f32 [R, L], ``factors`` [S + 1, K] f32/bf16 (zero row
    appended). Returns ``(gram [R, K, K] f32, rhs [R, K] f32)``; the
    caller adds ridge/YtY and solves (``ops.linalg.batched_spd_solve``).
    ``alpha`` may be a traced scalar (implicit mode's confidence scale).
    """
    r, pad_len = indices.shape
    k = factors.shape[1]
    br = min(block_rows, r)
    while br > 1 and r % br:
        br //= 2  # e.g. 12 rows/device under a 2-way data split -> BR=4
    chunk = _pick_chunk(pad_len)
    alpha_arr = jnp.asarray(alpha, jnp.float32).reshape(1, 1)
    kernel = functools.partial(
        _gram_rhs_kernel, implicit=implicit, chunk=chunk
    )
    return pl.pallas_call(
        kernel,
        grid=(r // br,),
        in_specs=[
            pl.BlockSpec((br, pad_len), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((br, pad_len), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((br, k, k), lambda i: (i, 0, 0)),
            pl.BlockSpec((br, k), lambda i: (i, 0)),
        ],
        out_shape=[
            shape_struct((r, k, k), jnp.float32, indices),
            shape_struct((r, k), jnp.float32, indices),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, br, chunk, k), factors.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(jnp.asarray(indices, jnp.int32), values, alpha_arr, factors)


def half_step_bytes(
    rows: int, pad_len: int, rank: int, itemsize: int, fused: bool
) -> float:
    """HBM bytes one half-step tail moves over a [rows, pad_len] block.

    The bytes-moved model behind the ``als_half_step_gbps`` bench metric
    (the half-step is bandwidth-bound, so GB/s -- not the misleading MFU
    number -- is the efficiency axis):

    shared streams: indices (i32) + values (f32) read once; Gram + rhs
    (f32) written once. The factor-table source reads are counted as the
    gather's random-read pass (rows*L*K*itemsize in expectation); the
    table's cold first touch is shared by both paths and not modeled
    per block.

    - fused: the gather's random read is the ONLY [rows, L, K]-sized pass;
      the result accumulates in VMEM.
    - unfused (XLA): the same random read, PLUS the gathered [rows, L, K]
      intermediate written to HBM once and read back by the Gram and rhs
      einsums (2 passes) -> 4 gather-sized passes in total.
    """
    streams = rows * pad_len * (4 + 4)            # indices + values
    outs = rows * (rank * rank + rank) * 4        # gram + rhs, f32
    gather_pass = rows * pad_len * rank * itemsize
    passes = 1 if fused else 4
    return float(streams + outs + passes * gather_pass)
