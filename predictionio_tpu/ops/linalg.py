"""Batched linear algebra for the MXU/VPU."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.lax.linalg import cholesky
from jax.scipy.linalg import cho_solve

#: ranks above this fall back to lax's cholesky -- the unrolled graph grows
#: O(K^2) in traced ops and the batch-major advantage fades for bigger tiles
_UNROLL_MAX_K = 32


def batched_spd_solve(
    gram: jnp.ndarray,
    rhs: jnp.ndarray,
    jitter: float = 1e-6,
    unroll: bool | None = None,
):
    """Solve ``gram[b] @ x[b] = rhs[b]`` for a batch of SPD systems.

    Two solve paths, chosen per platform. On TPU, the small K x K
    normal-equation systems ALS produces (K = rank, typically 8-64) are
    hand-unrolled over K with every step an elementwise op across the
    batch, so the batch dim rides the VPU lanes (measured ~5x faster than
    ``lax.linalg.cholesky`` + ``cho_solve`` at 138k x 16 x 16 on v5e). On
    CPU the same unrolled graph is ~8x SLOWER than LAPACK's batched
    Cholesky (round-2 driver evidence: 0.42 -> 0.05 it/s at ML-20M scale),
    so the lax path is the default there. ``unroll=None`` decides from
    ``jax.default_backend()``; callers that compile for an explicit mesh
    (e.g. ``parallel.als``) should pass the mesh platform instead, since
    the default backend need not match the target devices.

    A small jitter guards rows whose Gram is singular (entities with no
    interactions); their solution is ~0 because their rhs is 0.
    """
    k = gram.shape[-1]
    eye = jnp.eye(k, dtype=gram.dtype)
    gram = gram + jitter * eye
    if unroll is None:
        # any non-cpu backend counts as TPU-like (the axon tunnel backend
        # reports platform "axon" for real TPU chips)
        unroll = jax.default_backend() != "cpu"
    if not unroll or k > _UNROLL_MAX_K or gram.ndim != 3:
        chol = cholesky(gram)
        return cho_solve((chol, True), rhs[..., None])[..., 0]
    return _unrolled_chol_solve(gram, rhs)


def _unrolled_chol_solve(gram: jnp.ndarray, rhs: jnp.ndarray) -> jnp.ndarray:
    """Batch-major Cholesky + triangular solves, fully unrolled over K.

    Layout rationale: a [R, K, K] batch with tiny K is lane-hostile on TPU
    (K pads to 128); every operation here is instead a [R]- or [R, K]-wide
    elementwise op, so the batch dim R rides the vector lanes.
    """
    k = gram.shape[-1]
    arange = jnp.arange(k)

    # Cholesky, left-looking by column: cols[j] is L[:, :, j] as [R, K]
    cols: list[jnp.ndarray] = []
    for j in range(k):
        s = gram[:, :, j]
        for p in range(j):
            s = s - cols[p] * cols[p][:, j : j + 1]
        d = jnp.sqrt(jnp.maximum(s[:, j], 1e-12))
        cols.append((s / d[:, None]) * (arange >= j)[None, :])
    diag = [cols[i][:, i] for i in range(k)]

    # forward solve L y = b
    ys: list[jnp.ndarray] = []
    for i in range(k):
        s = rhs[:, i]
        for p in range(i):
            s = s - cols[p][:, i] * ys[p]
        ys.append(s / diag[i])

    # back solve L^T x = y  (L^T[i, p] = L[p, i] = cols[i][:, p])
    xs: list[jnp.ndarray | None] = [None] * k
    for i in reversed(range(k)):
        s = ys[i]
        for p in range(i + 1, k):
            s = s - cols[i][:, p] * xs[p]
        xs[i] = s / diag[i]
    return jnp.stack(xs, axis=1)
