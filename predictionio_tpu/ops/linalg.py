"""Batched linear algebra for the MXU."""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import cho_solve
from jax.lax.linalg import cholesky


def batched_spd_solve(gram: jnp.ndarray, rhs: jnp.ndarray, jitter: float = 1e-6):
    """Solve ``gram[b] @ x[b] = rhs[b]`` for a batch of SPD systems.

    Cholesky-based: roughly 2x cheaper than LU on the K x K normal-equation
    systems ALS produces, and numerically safe given the ridge term. A small
    jitter guards rows whose Gram is singular (entities with no
    interactions); their solution is ~0 because their rhs is 0.
    """
    k = gram.shape[-1]
    eye = jnp.eye(k, dtype=gram.dtype)
    chol = cholesky(gram + jitter * eye)
    return cho_solve((chol, True), rhs[..., None])[..., 0]
