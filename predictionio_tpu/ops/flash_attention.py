"""Pallas flash attention: tiled online-softmax attention for TPU.

``plain_attention`` (parallel/ring_attention.py) materializes the full
[B, H, T, T] score matrix -- fine for short histories, O(T^2) HBM for long
ones. This kernel computes the same attention with scores living only in
VMEM tiles, carrying the flash-attention running (max, sum, acc) statistics
across key blocks, plus the matching custom-VJP backward (recomputation
form: probabilities are rebuilt per tile from the saved logsumexp, never
stored).

Role in the framework: the intra-shard / single-device attention for the
sequence template (``models/sequence``). Across mesh shards the same online
softmax runs at the ring level (``parallel.ring_attention``); within a
shard, this kernel keeps the memory footprint O(T * D) so per-chip
sequences can grow until HBM, not VMEM-score-matrix, is the limit.

Shapes follow plain_attention: q, k, v [B, T, H, D]; optional key-validity
``mask`` [B, T]; causal masking over absolute positions. On CPU test
backends the kernels run in interpret mode (tests pin fwd+grad against
plain_attention).

Real-hardware layout constraints (learned the hard way -- interpret mode
checks none of this):

- Blocks must keep their last two dims (8, 128)-divisible or equal to the
  array dims. The public [B, T, H, D] layout blocks as (1, BQ, 1, D) with
  a second-minor 1 != H, so tensors transpose to [B, H, T, D] at the
  pallas boundary and blocks become (1, 1, BQ, D).
- Row operands (mask, lse, delta) carry a singleton middle axis --
  [B, 1, T] / [B*H, 1, T] -- so their (1, T)-shaped blocks match the
  array's own last-two dims.
- Mosaic cannot do dynamic SUBLANE (row) indexing inside a kernel
  ("dynamic load with unaligned indices"): all row selection lives in the
  BlockSpec index maps (per-program DMA), and in-kernel dynamic slices are
  lane/sublane slices at 128-multiple offsets only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from predictionio_tpu.utils.jax_compat import pallas as pl, shape_struct

_NEG = -1e30  # matches plain_attention's finite masked-score constant

BLOCK_Q = 128
BLOCK_K = 128


def _pos(n: int, offset):
    # 2D iota (1D iota fails on TPU), squeezed after
    return offset + jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)[:, 0]


def _fwd_kernel(
    q_ref,      # [1, 1, BQ, D]
    k_ref,      # [1, 1, T, D]
    v_ref,      # [1, 1, T, D]
    mask_ref,   # [1, 1, T]
    out_ref,    # [1, 1, BQ, D]
    lse_ref,    # [1, 1, BQ]
    *, causal: bool, sm_scale: float, block_k: int,
):
    qi = pl.program_id(2)
    bq, d = q_ref.shape[2], q_ref.shape[3]
    t = k_ref.shape[2]
    q = q_ref[0, 0, :, :].astype(jnp.float32)
    q_pos = _pos(bq, qi * bq)

    def body(kb, carry):
        acc, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        msk = mask_ref[0, 0, pl.ds(kb * block_k, block_k)]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                        # [BQ, BK]
        k_pos = _pos(block_k, kb * block_k)
        valid = msk[None, :]
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None]) * valid             # [BQ, BK]
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, t // block_k, body, (acc0, m0, l0))

    out_ref[0, 0, :, :] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(out_ref.dtype)
    lse_ref[0, 0, :] = m + jnp.log(jnp.maximum(l, 1e-20))


def _dq_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
    dq_ref,
    *, causal: bool, sm_scale: float, block_k: int,
):
    """dQ for one query block: dq = sum_kb (P o (dP - delta)) K * scale."""
    qi = pl.program_id(2)
    bq, d = q_ref.shape[2], q_ref.shape[3]
    t = k_ref.shape[2]
    q = q_ref[0, 0, :, :].astype(jnp.float32)
    do = do_ref[0, 0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :]
    delta = delta_ref[0, 0, :]
    q_pos = _pos(bq, qi * bq)

    def body(kb, dq):
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        msk = mask_ref[0, 0, pl.ds(kb * block_k, block_k)]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        k_pos = _pos(block_k, kb * block_k)
        valid = msk[None, :]
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(0, t // block_k, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0, :, :] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref,
    *, causal: bool, sm_scale: float, block_q: int,
):
    """dK/dV for one key block: loop over query blocks."""
    ki = pl.program_id(2)
    bk, d = k_ref.shape[2], k_ref.shape[3]
    t = q_ref.shape[2]
    k_blk = k_ref[0, 0, :, :].astype(jnp.float32)
    v_blk = v_ref[0, 0, :, :].astype(jnp.float32)
    msk = mask_ref[0, 0, pl.ds(ki * bk, bk)]
    k_pos = _pos(bk, ki * bk)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        q_pos = _pos(block_q, qb * block_q)
        valid = msk[None, :]
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)   # [BQ, BK]
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * sm_scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, t // block_q, body, (dk0, dv0))
    dk_ref[0, 0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)


def _pad_t(x, t_padded):
    pad = t_padded - x.shape[1]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[1] = (0, pad)
    return jnp.pad(x, widths)


def _specs(b_dim, t, h_dim, d, bq):
    """(index-mapped) block specs shared by the three kernels.

    Device tensors are [B, H, T, D]; row operands are [B, 1, T] (mask) and
    [B*H, 1, T] (lse/delta), with all row selection in the index maps.
    """
    q_spec = pl.BlockSpec((1, 1, bq, d), lambda b, h, i: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, t, d), lambda b, h, i: (b, h, 0, 0))
    mask_spec = pl.BlockSpec((1, 1, t), lambda b, h, i: (b, 0, 0))
    #: one query block of this (b, h)'s lse/delta row
    row_blk_spec = pl.BlockSpec((1, 1, bq), lambda b, h, i: (b * h_dim + h, 0, i))
    #: the full lse/delta row (dkv loops over all query blocks)
    row_full_spec = pl.BlockSpec((1, 1, t), lambda b, h, i: (b * h_dim + h, 0, 0))
    return q_spec, kv_spec, mask_spec, row_blk_spec, row_full_spec


def _to_bhtd(x):
    return jnp.transpose(x, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, mask, causal=True, sm_scale=None, interpret=False):
    """Flash attention. q,k,v [B, T, H, D] -> [B, T, H, D].

    ``mask``: [B, T] key-validity mask, or None for all-valid. Note rows
    whose every key is masked come back ~0 (the flash/ring convention),
    where ``plain_attention`` would return a uniform average -- such rows
    are padding and must be loss-masked by the caller either way.
    """
    out, _ = _flash_fwd(q, k, v, mask, causal, sm_scale, interpret)
    return out


def _flash_forward(q, k, v, mask, causal, sm_scale, interpret):
    b, t, h, d = q.shape
    scale = sm_scale if sm_scale is not None else d**-0.5
    bq = bk = BLOCK_Q
    t_padded = -(-t // bq) * bq
    if mask is None:
        mask = jnp.ones((b, t), bool)
    qp, kp, vp = (_pad_t(x, t_padded) for x in (q, k, v))
    maskp = _pad_t(mask.astype(bool), t_padded)[:, None, :]  # pad -> invalid

    nq = t_padded // bq
    q_spec, kv_spec, mask_spec, row_blk_spec, _ = _specs(b, t_padded, h, d, bq)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, causal=causal, sm_scale=scale, block_k=bk
        ),
        grid=(b, h, nq),
        in_specs=[q_spec, kv_spec, kv_spec, mask_spec],
        out_specs=[q_spec, row_blk_spec],
        out_shape=[
            _struct((b, h, t_padded, d), q.dtype, q),
            _struct((b * h, 1, t_padded), jnp.float32, q),
        ],
        interpret=interpret,
    )(_to_bhtd(qp), _to_bhtd(kp), _to_bhtd(vp), maskp)
    return _to_bhtd(out)[:, :t], lse


def _struct(shape, dtype, like):
    """ShapeDtypeStruct that inherits `like`'s varying-mesh-axes (vma) so
    the kernel composes under shard_map(check_vma=True); plain (non-sharded)
    callers -- and pre-vma jax (utils.jax_compat) -- get the ordinary
    struct."""
    return shape_struct(shape, dtype, like)


def _flash_fwd(q, k, v, mask, causal, sm_scale, interpret):
    out, lse = _flash_forward(q, k, v, mask, causal, sm_scale, interpret)
    return out, (q, k, v, mask, out, lse)


def _flash_bwd(causal, sm_scale, interpret, res, g):
    q, k, v, mask, out, lse = res
    b, t, h, d = q.shape
    scale = sm_scale if sm_scale is not None else d**-0.5
    bq = bk = BLOCK_Q
    t_padded = -(-t // bq) * bq
    if mask is None:
        mask = jnp.ones((b, t), bool)
        mask_grad = None
    else:
        import numpy as np

        mask_grad = np.zeros(mask.shape, jax.dtypes.float0)

    # delta[b,h,i] = rowsum(dO o O): the softmax-jacobian correction term
    delta = jnp.einsum("bthd,bthd->bht", g.astype(jnp.float32),
                       out.astype(jnp.float32)).reshape(b * h, 1, t)

    qp, kp, vp, gp = (_pad_t(x, t_padded) for x in (q, k, v, g))
    maskp = _pad_t(mask.astype(bool), t_padded)[:, None, :]
    lsep = lse  # already t_padded long: it never left the padded domain
    deltap = jnp.pad(delta, ((0, 0), (0, 0), (0, t_padded - t)))

    nq = t_padded // bq
    nk = t_padded // bk
    q_spec, kv_spec, mask_spec, row_blk_spec, row_full_spec = _specs(
        b, t_padded, h, d, bq
    )
    full_q = pl.BlockSpec((1, 1, t_padded, d), lambda b_, h_, i: (b_, h_, 0, 0))

    qt, kt, vt, gt = (_to_bhtd(x) for x in (qp, kp, vp, gp))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, sm_scale=scale, block_k=bk),
        grid=(b, h, nq),
        in_specs=[
            q_spec, kv_spec, kv_spec, mask_spec, q_spec,
            row_blk_spec, row_blk_spec,
        ],
        out_specs=q_spec,
        out_shape=_struct((b, h, t_padded, d), q.dtype, q),
        interpret=interpret,
    )(qt, kt, vt, maskp, gt, lsep, deltap)

    k_spec = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, sm_scale=scale, block_q=bq),
        grid=(b, h, nk),
        in_specs=[
            full_q, k_spec, k_spec, mask_spec, full_q,
            row_full_spec, row_full_spec,
        ],
        out_specs=[k_spec, k_spec],
        out_shape=[
            _struct((b, h, t_padded, d), k.dtype, k),
            _struct((b, h, t_padded, d), v.dtype, v),
        ],
        interpret=interpret,
    )(qt, kt, vt, maskp, gt, lsep, deltap)

    return (
        _to_bhtd(dq)[:, :t],
        _to_bhtd(dk)[:, :t],
        _to_bhtd(dv)[:, :t],
        mask_grad,
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)
