"""Symmetric per-block int8 quantization for device-resident factor tables.

The serving-scale bottleneck is HBM bytes per scanned item
(``ops/mips.py``): a rank-16 f32 item-factor table costs 64 B/item, so a
10M-item catalog reads 640 MB per full scan. Packing rows int8 with one
f32 scale per contiguous block of rows cuts that 4x (8x from bf16), which
is the ALX recipe (arxiv 2112.02194) applied to the SERVING table the way
``factorDtype: bfloat16`` applied it to training gathers.

Quantization is symmetric (zero-point = 0): factor tables are zero-mean
by construction (ridge-regularized ALS solves), so an asymmetric
zero-point would spend a stream on correcting a bias that is ~0, and
symmetry keeps the kernel's dequantize a single multiply. The scale is
per BLOCK of rows, not per row: the MIPS kernel reads one scalar per
[block_items, K] tile (SMEM), and the error bound stays local to the
block instead of following the global absmax.

Error contract (property-tested in ``tests/test_mips.py``):

- element round-trip: ``|x - scale * q| <= scale / 2`` within each block
  (127 clips only the exact absmax element, which rounds to itself);
- dot-product: for a query ``y``, ``|y . x - y . deq(x)| <=
  (scale / 2) * ||y||_1`` per item row -- the bound ``score_error_bound``
  reports and the shortlist oversampling margin is sized against.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

#: rows per quantization block (and per MIPS kernel tile). 512 int8 rows
#: at rank 16 is an 8 KB tile -- far under VMEM, big enough that the
#: per-block f32 scale is amortized to 0.06 bits/element of overhead.
BLOCK_ITEMS = 512


@dataclass(frozen=True)
class PackedFactors:
    """A factor table packed for the MIPS scan.

    ``q`` is ``[padded_items, K]`` int8 with ``padded_items`` a
    ``block_items`` multiple (padding rows are zero -- they dequantize to
    zero scores and the search tail drops their indices);
    ``scales`` is ``[num_blocks, 1]`` f32 (2D: SMEM scalars ride (1, 1)
    blocks). Rows ``i`` of the original table live at ``q[i]`` unchanged
    -- candidate indices out of the kernel are already catalog indices.
    """

    q: np.ndarray
    scales: np.ndarray
    num_items: int
    block_items: int

    @property
    def num_blocks(self) -> int:
        return self.q.shape[0] // self.block_items

    @property
    def packed_bytes(self) -> int:
        return self.q.nbytes + self.scales.nbytes


def pack_int8_blockwise(
    factors: np.ndarray, block_items: int = BLOCK_ITEMS
) -> PackedFactors:
    """Quantize ``[num_items, K]`` f32/f64 factors to symmetric per-block
    int8. Blocks are contiguous row ranges; the last block zero-pads."""
    factors = np.asarray(factors, np.float32)
    if factors.ndim != 2:
        raise ValueError(f"factors must be [items, K], got {factors.shape}")
    if block_items < 8 or block_items % 8:
        raise ValueError(
            f"block_items must be a positive multiple of 8, got {block_items}"
        )
    num_items, k = factors.shape
    padded = -(-max(num_items, 1) // block_items) * block_items
    x = np.zeros((padded, k), np.float32)
    x[:num_items] = factors
    blocks = x.reshape(-1, block_items, k)
    absmax = np.abs(blocks).max(axis=(1, 2))
    # all-zero blocks (padding tails, unseen cold rows) keep scale 1.0:
    # 0 / 1.0 quantizes to 0 and dequantizes to 0 exactly
    scales = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(
        np.rint(blocks / scales[:, None, None]), -127, 127
    ).astype(np.int8)
    return PackedFactors(
        q=q.reshape(padded, k),
        scales=scales.reshape(-1, 1),
        num_items=num_items,
        block_items=block_items,
    )


def unpack_blockwise(packed: PackedFactors) -> np.ndarray:
    """Dequantize back to ``[num_items, K]`` f32 (padding rows dropped)."""
    blocks = packed.q.reshape(-1, packed.block_items, packed.q.shape[1])
    x = blocks.astype(np.float32) * packed.scales[:, :, None]
    return x.reshape(-1, packed.q.shape[1])[: packed.num_items]


def quantization_error_bound(packed: PackedFactors) -> np.ndarray:
    """Per-block max-abs element error, ``scales / 2`` -- the round-trip
    contract ``tests/test_mips.py`` pins."""
    return packed.scales[:, 0] / 2.0


def score_error_bound(packed: PackedFactors, query: np.ndarray) -> np.ndarray:
    """Per-block bound on ``|exact - quantized|`` dot-product scores for
    one query row: ``(scale / 2) * ||query||_1``. The shortlist margin
    (``RetrievalConfig.shortlist`` over ``num``) buys recall against
    exactly this reordering window."""
    l1 = float(np.abs(np.asarray(query, np.float32)).sum())
    return quantization_error_bound(packed) * l1
