"""Sharded serving fabric: hash-partitioned scorer shards + supervisor.

``pio deploy --scorer-shards N`` runs this instead of the single-scorer
multi-process tier. The topology:

- **N scorer shards** (``serving/shard.py``): each a full
  ``QueryService`` restricted to its hash partition of the user factor
  table (item-side and replicated state whole), consuming one request
  ring per frontend worker and exposing its control surface on a
  loopback port.
- **M frontend workers** (``serving/frontend.py``): the unchanged
  ``SO_REUSEPORT`` accept/parse loops, now with ``N+1`` rings each --
  one per shard plus a CONTROL ring. A query routes by
  ``shardmap.shard_of(user_id) % N`` to its owning shard's ring; every
  control route rides the control ring to this supervisor.
- **The supervisor** (this module, running in the deploy process):
  creates every ring file and wakeup ONCE (they outlive respawns on
  both sides), spawns and supervises both tiers, consumes the control
  rings through an ATTACHED
  :class:`~predictionio_tpu.serving.procserver.ScorerBridge`, and fans
  control operations out over the shards' loopback ports.

**The per-shard swap-epoch protocol.** ``POST /models/swap`` resolves
the target version ONCE (the first shard's answer pins an unversioned
swap), then fans out serially under one lock. Version skew across shards
is therefore bounded by a single fan-out -- one swap window -- and each
response's ``x-pio-model-version`` header remains exact per shard
because every shard stamps its own epoch. The last fully-resolved target
becomes the fabric's COMMITTED version: a SIGKILLed shard is respawned
pinned to it (``--model-version``), so a rejoining shard can never skew
ahead of (or behind) its siblings by more than that same window.

Failure isolation: a dead shard takes down only its hash partition --
surviving shards keep answering their users byte-identically, their
rings and processes untouched. A dead frontend is respawned onto the
SAME ring files with a bumped ``--rid-base`` generation, so in-flight
completions addressed to the dead generation are dropped by rid, never
misdelivered.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

from predictionio_tpu.serving import shmring
from predictionio_tpu.serving.procserver import FrontendConfig, ScorerBridge
from predictionio_tpu.utils.http import Request, Response, instrumented_router

logger = logging.getLogger("pio.fabric")

#: rid generations are (generation << _RID_GEN_SHIFT): 2**33 ids per
#: frontend generation before aliasing, far past any drain window
_RID_GEN_SHIFT = 33


class _Shard:
    def __init__(self, index: int, proc: subprocess.Popen, portfile: str):
        self.index = index
        self.proc = proc
        self.portfile = portfile
        self.port: int | None = None
        self.dead = False


class _Frontend:
    def __init__(self, index: int, generation: int, proc: subprocess.Popen):
        self.index = index
        self.generation = generation
        self.proc = proc
        self.dead = False


class ShardFabric:
    """Deploy-side owner of the sharded serving tier. Same
    ``start()/stop()/port`` surface as ``MultiprocServiceHandle``."""

    #: consecutive failed respawns of one slot before giving up on it
    _MAX_RESPAWN_FAILURES = 6

    def __init__(
        self,
        variant,
        host: str = "0.0.0.0",
        port: int = 8000,
        num_shards: int = 2,
        frontend: FrontendConfig | None = None,
        server_name: str = "pio-queryserver",
        model_version: int | None = None,
        instance_id: str | None = None,
        batch_window_ms: float | None = None,
        max_batch_size: int | None = None,
    ):
        if num_shards < 2:
            raise ValueError("the sharded fabric needs --scorer-shards >= 2")
        self.variant = variant
        self._host = host
        self._requested_port = port
        self.num_shards = num_shards
        self.config = frontend or FrontendConfig()
        if self.config.workers < 1:
            raise ValueError("frontend workers must be >= 1")
        self._server_name = server_name
        self._requested_model_version = model_version
        self._requested_instance_id = instance_id
        self._batch_window_ms = batch_window_ms
        self._max_batch_size = max_batch_size

        self.port: int | None = None
        self._reserve: socket.socket | None = None
        self._dir: str | None = None
        self._shard_req: list[shmring.Wakeup] = []
        self._ctl_req: shmring.Wakeup | None = None
        self._fe_cmp: list[shmring.Wakeup] = []
        self._fe_stop: list[shmring.Wakeup] = []
        #: frontend index -> this process's mapping of its control ring
        self._ctl_rings: list[shmring.RingFile] = []
        self._shards: list[_Shard] = []
        self._frontends: list[_Frontend] = []
        self._bridge: ScorerBridge | None = None
        self.metrics = None
        #: guards shard ports/versions, committed version, respawn
        #: counters, and both process lists against the supervisor
        self._lock = threading.Lock()
        #: serializes swap fan-outs end-to-end -- THE skew bound: two
        #: concurrent swaps cannot interleave shards
        self._swap_lock = threading.Lock()
        self._committed: int | None = None
        self._shard_versions: dict[int, int | None] = {}
        self._respawns = 0
        self._fe_respawns = 0
        self._stopping = False
        self._stop_lock = threading.Lock()
        self._stop_requested = threading.Event()
        self._supervisor: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ShardFabric":
        if not hasattr(socket, "SO_REUSEPORT"):
            raise RuntimeError(
                "the sharded fabric needs SO_REUSEPORT (Linux/BSD)"
            )
        try:
            self._reserve = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._reserve.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            self._reserve.bind((self._host, self._requested_port))
            self.port = self._reserve.getsockname()[1]
            self._dir = tempfile.mkdtemp(prefix="pio-fabric-")
            self._pin_startup_version()
            n, m = self.num_shards, self.config.workers
            for k in range(n):
                self._shard_req.append(
                    shmring.Wakeup.create(self._dir, f"shard-req-{k}")
                )
            self._ctl_req = shmring.Wakeup.create(self._dir, "ctl-req")
            for j in range(m):
                self._fe_cmp.append(
                    shmring.Wakeup.create(self._dir, f"cmp-{j}")
                )
                self._fe_stop.append(
                    shmring.Wakeup.create(self._dir, f"stop-{j}")
                )
            # every ring file is created ONCE here and reused across
            # respawns on either side: a surviving process's mmap must
            # keep pointing at the live inode (RingFile.create's
            # truncate-and-replace would orphan it)
            for j in range(m):
                for k in range(n):
                    ring = shmring.RingFile.create(
                        self._ring_path(j, k), self.config.ring_slots,
                        self.config.slot_bytes, generation=1,
                    )
                    ring.close()
                self._ctl_rings.append(
                    shmring.RingFile.create(
                        self._ctl_path(j), self.config.ring_slots,
                        self.config.slot_bytes, generation=1,
                    )
                )
            for k in range(n):
                self._shards.append(self._launch_shard(k))
            self._await_shards(self._shards)
            for j in range(m):
                self._frontends.append(self._launch_frontend(j, generation=1))
            self._await_frontends(self._frontends)
            self._start_control_bridge()
        except BaseException:
            self._teardown(kill=True)
            raise
        self._supervisor = threading.Thread(
            target=self._supervise, name="pio-fabric-supervisor", daemon=True
        )
        self._supervisor.start()
        return self

    def _pin_startup_version(self) -> None:
        """Resolve the startup epoch ONCE in the fabric so every shard
        starts on the SAME version even if a publish lands mid-spawn --
        the swap protocol's skew bound, applied to boot. A plain
        instance deploy (empty registry, no pin) stays unpinned."""
        pin = self._requested_model_version
        if pin is None:
            try:
                from predictionio_tpu.online.registry import ModelRegistry

                latest = ModelRegistry.for_variant(self.variant).latest()
                if latest is not None:
                    pin = latest.version
            except Exception:
                logger.warning(
                    "could not resolve a startup registry version;"
                    " shards resolve independently", exc_info=True,
                )
        with self._lock:
            self._committed = pin
            self._shard_versions = {
                k: pin for k in range(self.num_shards)
            }
        self._startup_version = pin

    def _ring_path(self, frontend: int, shard: int) -> str:
        return os.path.join(self._dir, f"fe{frontend}-shard{shard}.ring")

    def _ctl_path(self, frontend: int) -> str:
        return os.path.join(self._dir, f"fe{frontend}-ctl.ring")

    def _child_env(self) -> dict:
        env = dict(os.environ)
        # children must resolve this package AND the engine's modules the
        # way the deploy process does (tests put engines on sys.path)
        env["PYTHONPATH"] = os.pathsep.join(
            dict.fromkeys(p for p in sys.path if p)
        )
        return env

    def _launch_shard(self, index: int) -> _Shard:
        portfile = os.path.join(self._dir, f"shard-{index}.port")
        try:
            os.unlink(portfile)
        except OSError:
            pass
        cmd = [
            sys.executable, "-m", "predictionio_tpu.serving.shard",
            "--variant", self.variant.path,
            "--shard", str(index),
            "--num-shards", str(self.num_shards),
            "--wake-req", self._shard_req[index].spec(),
            "--portfile", portfile,
            "--dispatch", self.config.dispatch,
            "--max-inflight", str(self.config.max_inflight),
            "--control-threads", str(self.config.control_threads),
            "--server-name", self._server_name,
        ]
        for j in range(self.config.workers):
            cmd += ["--ring", self._ring_path(j, index)]
        for j in range(self.config.workers):
            cmd += ["--wake-cmp", self._fe_cmp[j].spec()]
        with self._lock:
            pin = self._committed
        if pin is not None:
            cmd += ["--model-version", str(pin)]
        elif self._requested_instance_id:
            cmd += ["--instance-id", self._requested_instance_id]
        if self._batch_window_ms is not None:
            cmd += ["--batch-window-ms", str(self._batch_window_ms)]
        if self._max_batch_size is not None:
            cmd += ["--max-batch-size", str(self._max_batch_size)]
        pass_fds = tuple(
            fd for w in [self._shard_req[index], *self._fe_cmp]
            if (fd := w.pass_fd) is not None
        )
        log = open(os.path.join(self._dir, f"shard-{index}.log"), "ab")
        try:
            proc = subprocess.Popen(
                cmd, pass_fds=pass_fds, env=self._child_env(),
                stdout=log, stderr=subprocess.STDOUT,
            )
        finally:
            log.close()
        logger.info(
            "scorer shard %d/%d spawned (pid %d, pinned version %s)",
            index, self.num_shards, proc.pid, pin,
        )
        return _Shard(index, proc, portfile)

    def _launch_frontend(self, index: int, generation: int) -> _Frontend:
        cmd = [
            sys.executable, "-m", "predictionio_tpu.serving.frontend",
            "--host", self._host,
            "--port", str(self.port),
            "--worker", str(index),
            "--wake-cmp", self._fe_cmp[index].spec(),
            "--wake-stop", self._fe_stop[index].spec(),
            "--server-name", self._server_name,
            "--stats-flush-s", str(self.config.stats_flush_s),
            "--rid-base", str(generation << _RID_GEN_SHIFT),
        ]
        for k in range(self.num_shards):
            cmd += [
                "--ring", self._ring_path(index, k),
                "--wake-req", self._shard_req[k].spec(),
            ]
        cmd += ["--ring", self._ctl_path(index),
                "--wake-req", self._ctl_req.spec()]
        pass_fds = tuple(
            fd for w in [
                *self._shard_req, self._ctl_req,
                self._fe_cmp[index], self._fe_stop[index],
            ]
            if (fd := w.pass_fd) is not None
        )
        log = open(os.path.join(self._dir, f"frontend-{index}.log"), "ab")
        try:
            proc = subprocess.Popen(
                cmd, pass_fds=pass_fds, env=self._child_env(),
                stdout=log, stderr=subprocess.STDOUT,
            )
        finally:
            log.close()
        logger.info(
            "frontend worker %d spawned (pid %d, generation %d)",
            index, proc.pid, generation,
        )
        return _Frontend(index, generation, proc)

    def _log_tail(self, name: str, limit: int = 500) -> str:
        try:
            with open(os.path.join(self._dir, f"{name}.log"), "rb") as f:
                return f.read()[-limit:].decode("utf-8", "replace")
        except OSError:
            return ""

    def _await_shards(self, shards: list[_Shard]) -> None:
        deadline = time.monotonic() + self.config.spawn_timeout_s
        pending = list(shards)
        while pending:
            still = []
            for s in pending:
                if os.path.exists(s.portfile):
                    with open(s.portfile) as f:
                        s.port = int(f.read().strip())
                    continue
                if s.proc.poll() is not None:
                    raise RuntimeError(
                        f"scorer shard {s.index} exited"
                        f" rc={s.proc.returncode} before READY (log:"
                        f" {self._log_tail(f'shard-{s.index}')!r})"
                    )
                still.append(s)
            pending = still
            if pending and time.monotonic() > deadline:
                raise RuntimeError(
                    f"scorer shard(s) {[s.index for s in pending]} not"
                    f" READY within {self.config.spawn_timeout_s}s"
                )
            if pending:
                time.sleep(0.02)

    def _await_frontends(self, frontends: list[_Frontend]) -> None:
        deadline = time.monotonic() + self.config.spawn_timeout_s
        pending = list(frontends)
        while pending:
            pending = [
                fe for fe in pending
                if self._ctl_rings[fe.index].state == shmring.STATE_INIT
            ]
            if not pending:
                return
            for fe in pending:
                if fe.proc.poll() is not None:
                    raise RuntimeError(
                        f"frontend worker {fe.index} exited"
                        f" rc={fe.proc.returncode} before READY (log:"
                        f" {self._log_tail(f'frontend-{fe.index}')!r})"
                    )
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"frontend worker(s) {[fe.index for fe in pending]}"
                    f" not READY within {self.config.spawn_timeout_s}s"
                )
            time.sleep(0.02)

    def _start_control_bridge(self) -> None:
        router, self.metrics = instrumented_router(
            before_scrape=self._mirror, tracing=False,
            extra_snapshots=self._frontend_snapshots,
        )
        router.add("GET", "/", self.handle_info)
        router.add("POST", "/models/swap", self.handle_model_swap)
        router.add("POST", "/models/lag", self.handle_model_lag)
        router.add("GET", "/models.json", self.handle_models)
        router.add("GET", "/reload", self.handle_reload)
        router.add("POST", "/stop", self.handle_stop)
        # control traffic only: a small sync dispatcher pool; the
        # frontends never route queries here
        ctl_config = FrontendConfig(
            workers=self.config.workers, dispatch="sync",
            max_inflight=max(4, self.config.control_threads * 2),
        )
        self._bridge = ScorerBridge(
            router, "", 0, ctl_config,
            server_name=self._server_name,
            attach=[
                (self._ctl_rings[j], self._ctl_req, self._fe_cmp[j])
                for j in range(self.config.workers)
            ],
        )
        self._bridge.start()

    def stop(self) -> None:
        with self._stop_lock:
            self._stop_stopped()

    def _stop_stopped(self) -> None:
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
        if self._supervisor is not None:
            self._supervisor.join(timeout=5.0)
        # frontends drain FIRST (they wait for in-flight shard answers),
        # then the shards get SIGTERM with nothing left in flight
        for wake in self._fe_stop:
            wake.signal()
        from predictionio_tpu.serving.frontend import FORWARD_TIMEOUT_S

        # snapshot under the lock: the supervisor swaps list slots on
        # respawn, and it only just observed _stopping (or is mid-loop)
        with self._lock:
            frontends = list(self._frontends)
            shards = list(self._shards)
        deadline = time.monotonic() + FORWARD_TIMEOUT_S + 5.0
        for fe in frontends:
            timeout = max(deadline - time.monotonic(), 0.1)
            try:
                fe.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                logger.warning(
                    "frontend worker %d did not drain; killing", fe.index
                )
                fe.proc.kill()
                try:
                    fe.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
        for s in shards:
            if s.proc.poll() is None:
                s.proc.terminate()
        for s in shards:
            try:
                s.proc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                logger.warning(
                    "scorer shard %d did not drain; killing", s.index
                )
                s.proc.kill()
                try:
                    s.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
        self._teardown()

    def _teardown(self, kill: bool = False) -> None:
        with self._lock:
            self._stopping = True
            procs = [*self._shards, *self._frontends]
        if kill:
            for p in procs:
                if p.proc.poll() is None:
                    p.proc.kill()
            for p in procs:
                try:
                    p.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
        if self._bridge is not None:
            self._bridge.stop()  # closes ctl rings, ctl_req, fe_cmp wakes
            self._bridge = None
        for wake in [*self._shard_req, *self._fe_stop]:
            wake.close()
        if self._reserve is not None:
            self._reserve.close()
            self._reserve = None
        if self._dir is not None:
            shutil.rmtree(self._dir, ignore_errors=True)

    def wait(self) -> None:
        """Block until ``POST /stop`` arrives (the ``pio undeploy``
        contract)."""
        try:
            self._stop_requested.wait()
        except KeyboardInterrupt:
            pass

    # -- shard HTTP fan-out --------------------------------------------------
    def _shard_port(self, index: int) -> int | None:
        with self._lock:
            s = self._shards[index]
            return None if s.dead else s.port

    def _shard_call(
        self, index: int, method: str, path: str,
        body: dict | None = None, timeout: float = 10.0,
    ) -> tuple[int, dict]:
        port = self._shard_port(index)
        if port is None:
            return 503, {"message": f"shard {index} is down"}
        data = None if body is None else json.dumps(body).encode("utf-8")
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as exc:
            try:
                return exc.code, json.loads(exc.read() or b"{}")
            except ValueError:
                return exc.code, {}
        except Exception as exc:
            return 503, {"message": f"shard {index} unreachable: {exc}"}

    # -- control handlers ----------------------------------------------------
    def handle_model_swap(self, request: Request) -> Response:
        """The PER-SHARD swap-epoch protocol: resolve the target version
        once, fan out serially under the swap lock. Skew across shards
        is bounded by this one fan-out (the swap window); the COMMITTED
        version -- what respawned shards pin to -- moves only here."""
        try:
            body = request.json() or {}
        except json.JSONDecodeError:
            return Response(400, {"message": "malformed JSON body"})
        version = body.get("version")
        if version is not None:
            try:
                version = int(version)
            except (TypeError, ValueError):
                return Response(400, {"message": f"bad version {version!r}"})
        lag = body.get("foldinLagSeconds")
        with self._swap_lock:
            target = version
            results = []
            failures = 0
            for k in range(self.num_shards):
                payload: dict = {}
                if target is not None:
                    payload["version"] = target
                if isinstance(lag, (int, float)):
                    payload["foldinLagSeconds"] = lag
                status, resp = self._shard_call(
                    k, "POST", "/models/swap", payload
                )
                if status == 200:
                    swapped = resp.get("modelVersion")
                    if target is None and swapped is not None:
                        # an unversioned swap resolves "latest" at the
                        # FIRST shard; the rest of the fan-out (and any
                        # respawn) pins that answer, so a publish racing
                        # the fan-out cannot split the fabric
                        target = int(swapped)
                    results.append(
                        {"shard": k, "status": "swapped",
                         "modelVersion": swapped}
                    )
                    with self._lock:
                        self._shard_versions[k] = swapped
                else:
                    failures += 1
                    results.append(
                        {"shard": k, "status": "error", "code": status,
                         "message": resp.get("message")}
                    )
            if target is not None and failures < self.num_shards:
                with self._lock:
                    self._committed = target
        if failures == self.num_shards:
            return Response(
                502, {"message": "swap failed on every shard",
                      "shards": results}
            )
        return Response(200, {
            "status": "swapped" if failures == 0 else "partial",
            "modelVersion": target,
            "shards": results,
        })

    def handle_model_lag(self, request: Request) -> Response:
        try:
            body = request.json() or {}
        except json.JSONDecodeError:
            return Response(400, {"message": "malformed JSON body"})
        lag = body.get("foldinLagSeconds")
        if not isinstance(lag, (int, float)):
            return Response(400, {"message": "foldinLagSeconds required"})
        for k in range(self.num_shards):
            self._shard_call(k, "POST", "/models/lag", body, timeout=5.0)
        return Response(200, {"status": "ok"})

    def handle_models(self, request: Request) -> Response:
        versions: list = []
        for k in range(self.num_shards):
            status, resp = self._shard_call(k, "GET", "/models.json")
            if status == 200:
                versions = resp.get("versions", [])
                break
        with self._lock:
            committed = self._committed
            per_shard = [
                {"shard": k, "currentVersion": self._shard_versions.get(k)}
                for k in range(self.num_shards)
            ]
        return Response(200, {
            "currentVersion": committed,
            "versions": versions,
            "shards": per_shard,
        })

    def handle_info(self, request: Request) -> Response:
        shards = []
        engine_instance = None
        for k in range(self.num_shards):
            status, resp = self._shard_call(k, "GET", "/", timeout=3.0)
            if status == 200:
                if engine_instance is None:
                    engine_instance = resp.get("engineInstance")
                shards.append({
                    "shard": k,
                    "status": "alive",
                    "modelVersion": resp.get("modelVersion"),
                    "queryCount": (resp.get("serverStats") or {}).get(
                        "queryCount"
                    ),
                })
            else:
                shards.append({"shard": k, "status": "down"})
        with self._lock:
            committed = self._committed
            respawns = self._respawns
            fe_respawns = self._fe_respawns
        body = {
            "status": "alive",
            "fabric": {
                "shards": self.num_shards,
                "frontendWorkers": self.config.workers,
                "committedVersion": committed,
                "shardRespawns": respawns,
                "frontendRespawns": fe_respawns,
            },
            "frontend": {
                **self.config.describe(),
                "shards": self.num_shards,
            },
            "shards": shards,
        }
        if engine_instance is not None:
            body["engineInstance"] = engine_instance
        return Response(200, body)

    def handle_reload(self, request: Request) -> Response:
        results = []
        for k in range(self.num_shards):
            status, resp = self._shard_call(k, "GET", "/reload", timeout=60.0)
            results.append({"shard": k, "code": status, **resp})
        # /reload re-resolves the latest INSTANCE: the registry epoch is
        # gone, so respawns must not pin a stale committed version
        with self._lock:
            self._committed = None
            self._shard_versions = {
                k: None for k in range(self.num_shards)
            }
        return Response(200, {"status": "reloaded", "shards": results})

    def handle_stop(self, request: Request) -> Response:
        self._stop_requested.set()
        return Response(200, {"status": "stopping"})

    # -- metrics -------------------------------------------------------------
    def _mirror(self, registry) -> None:
        with self._lock:
            versions = dict(self._shard_versions)
            respawns = self._respawns
            fe_respawns = self._fe_respawns
        registry.set_gauge(
            "pio_scorer_shard_count", float(self.num_shards),
            help="Scorer shards in the serving fabric",
        )
        registry.set_gauge(
            "pio_frontend_workers", float(self.config.workers),
            help="Configured frontend worker processes",
        )
        registry.set_counter(
            "pio_shard_respawns_total", float(respawns),
            help="Scorer shards respawned after unexpected exit",
        )
        registry.set_counter(
            "pio_frontend_respawns_total", float(fe_respawns),
            help="Frontend workers respawned after unexpected exit",
        )
        for k, v in versions.items():
            if v is not None:
                registry.set_gauge(
                    "pio_model_version", float(v), {"shard": str(k)},
                    help="Registry model version serving, per shard",
                )

    def _frontend_snapshots(self) -> list[dict]:
        out = []
        for ring in self._ctl_rings:
            try:
                snap = ring.read_stats()
            except (ValueError, OSError):
                continue
            if snap:
                out.append(snap)
        return out

    # -- supervision ---------------------------------------------------------
    def _supervise(self) -> None:
        #: slot key -> (consecutive failures, next attempt monotonic)
        backoff: dict[str, tuple[int, float]] = {}
        while True:
            time.sleep(0.2)
            with self._lock:
                if self._stopping:
                    return
                shards = list(self._shards)
                frontends = list(self._frontends)
            for s in shards:
                if s.proc.poll() is None or s.dead:
                    continue
                logger.warning(
                    "scorer shard %d died (rc=%s); respawning",
                    s.index, s.proc.returncode,
                )
                with self._lock:
                    s.dead = True
                backoff.setdefault(f"s{s.index}", (0, time.monotonic()))
            for fe in frontends:
                if fe.proc.poll() is None or fe.dead:
                    continue
                logger.warning(
                    "frontend worker %d died (rc=%s); respawning",
                    fe.index, fe.proc.returncode,
                )
                fe.dead = True
                backoff.setdefault(f"f{fe.index}", (0, time.monotonic()))
            for key in sorted(backoff):
                failures, next_try = backoff[key]
                if time.monotonic() < next_try:
                    continue
                ok = (
                    self._respawn_shard(int(key[1:]))
                    if key[0] == "s"
                    else self._respawn_frontend(int(key[1:]))
                )
                if ok:
                    del backoff[key]
                    continue
                failures += 1
                if failures >= self._MAX_RESPAWN_FAILURES:
                    logger.error(
                        "giving up on %s after %d failed respawns;"
                        " the fabric keeps serving on the remaining"
                        " processes", key, failures,
                    )
                    del backoff[key]
                else:
                    backoff[key] = (
                        failures,
                        time.monotonic() + min(0.5 * 2 ** failures, 30.0),
                    )

    def _respawn_shard(self, index: int) -> bool:
        """Respawn one shard pinned to the COMMITTED version: the rejoin
        rule that keeps a returning shard inside the same swap window as
        its siblings (its ring files are reused untouched)."""
        replacement = self._launch_shard(index)
        try:
            self._await_shards([replacement])
        except RuntimeError:
            logger.exception("respawned scorer shard %d failed", index)
            replacement.proc.kill()
            return False
        with self._lock:
            if self._stopping:
                replacement.proc.kill()
                return True
            self._shards[index] = replacement
            self._respawns += 1
            committed = self._committed
            self._shard_versions[index] = committed
        logger.info(
            "scorer shard %d rejoined at committed version %s",
            index, committed,
        )
        return True

    def _respawn_frontend(self, index: int) -> bool:
        with self._lock:
            old = self._frontends[index]
        # the frontend will set READY on attach; INIT first so the await
        # below watches a real transition, not the dead worker's carcass
        self._ctl_rings[index].set_state(shmring.STATE_INIT)
        replacement = self._launch_frontend(index, old.generation + 1)
        try:
            self._await_frontends([replacement])
        except RuntimeError:
            logger.exception("respawned frontend worker %d failed", index)
            replacement.proc.kill()
            return False
        with self._lock:
            if self._stopping:
                replacement.proc.kill()
                return True
            self._frontends[index] = replacement
            self._fe_respawns += 1
        return True
