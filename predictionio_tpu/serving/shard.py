"""Scorer shard process of the sharded serving fabric.

Runs as its own process (``python -m predictionio_tpu.serving.shard``),
spawned and supervised by
:class:`~predictionio_tpu.serving.fabric.ShardFabric`. One shard is a
full single-process :class:`~predictionio_tpu.workflow.create_server.
QueryService` -- models, micro-batcher, router, hot-swap protocol --
restricted to its hash partition of the user factor table
(``QueryService(shard=K, num_shards=N)``); item-side and replicated
state stay whole, so any query for an owned user answers byte-for-byte
what the unsharded server would.

Two faces:

- **Ring face** (the query path): an ATTACHED
  :class:`~predictionio_tpu.serving.procserver.ScorerBridge` consumes
  one request ring per frontend worker (the fabric created the ring
  files; the frontends route each query here by
  ``shardmap.shard_of(user)``), feeding the micro-batcher through the
  same async fast path the unsharded scorer uses.
- **Control face**: a loopback-only HTTP listener on an ephemeral port
  (written to ``--portfile``) exposing the full control surface --
  ``/models/swap``, ``/models.json``, ``/metrics``, ``/reload`` -- which
  is how the fabric fans a swap epoch out per shard and scrapes
  per-shard gauges.

``SIGTERM`` is the graceful drain signal (the fabric stops the frontends
first, so nothing is in flight by the time it arrives); ``--model-version``
pins the startup epoch, which is how a respawned shard rejoins at the
fabric's last COMMITTED version instead of whatever is newest.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import threading

logger = logging.getLogger("pio.shard")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--variant", required=True, help="engine.json path")
    ap.add_argument("--shard", type=int, required=True)
    ap.add_argument("--num-shards", type=int, required=True)
    ap.add_argument(
        "--ring", required=True, action="append",
        help="ring file path, one per frontend worker (fabric-created)",
    )
    ap.add_argument(
        "--wake-req", required=True,
        help="this shard's request wakeup spec (shared by all frontends)",
    )
    ap.add_argument(
        "--wake-cmp", required=True, action="append",
        help="completion wakeup spec, one per --ring in the same order",
    )
    ap.add_argument("--portfile", required=True)
    ap.add_argument("--model-version", type=int, default=None)
    ap.add_argument("--instance-id", default=None)
    ap.add_argument("--dispatch", default="async", choices=("async", "sync"))
    ap.add_argument("--max-inflight", type=int, default=16)
    ap.add_argument("--control-threads", type=int, default=2)
    ap.add_argument("--server-name", default="pio-queryserver")
    ap.add_argument("--batch-window-ms", type=float, default=None)
    ap.add_argument("--max-batch-size", type=int, default=None)
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format=f"shard-{args.shard} %(levelname)s %(name)s: %(message)s",
    )
    if len(args.wake_cmp) != len(args.ring):
        raise SystemExit("--wake-cmp count must match --ring count")

    from predictionio_tpu.serving import shmring
    from predictionio_tpu.serving.procserver import (
        FrontendConfig,
        ScorerBridge,
    )
    from predictionio_tpu.workflow.create_server import create_query_server
    from predictionio_tpu.workflow.json_extractor import load_engine_variant
    from predictionio_tpu.workflow.microbatch import BatchConfig

    variant = load_engine_variant(args.variant)
    batching = None
    if args.batch_window_ms is not None or args.max_batch_size is not None:
        kw = {}
        if args.batch_window_ms is not None:
            kw["window_ms"] = args.batch_window_ms
        if args.max_batch_size is not None:
            kw["max_batch_size"] = args.max_batch_size
        batching = BatchConfig(**kw)
    # the control face binds loopback only: the fabric is the sole client
    thread, service = create_query_server(
        variant, host="127.0.0.1", port=0,
        shard=args.shard, num_shards=args.num_shards,
        model_version=args.model_version,
        instance_id=args.instance_id,
        batching=batching,
    )
    thread.start()

    rings = [shmring.RingFile.attach(path) for path in args.ring]
    wake_req = shmring.Wakeup.from_spec(args.wake_req)
    attach = [
        (ring, wake_req, shmring.Wakeup.from_spec(spec))
        for ring, spec in zip(rings, args.wake_cmp)
    ]
    config = FrontendConfig(
        workers=len(rings),
        max_inflight=args.max_inflight,
        dispatch=args.dispatch,
        control_threads=args.control_threads,
    )
    async_query = None
    if config.dispatch == "async" and service._batcher is not None:
        async_query = service.submit_query_async
    bridge = ScorerBridge(
        service.router, "", 0, config,
        server_name=args.server_name,
        async_query=async_query,
        attach=attach,
    )
    service.scorer_stats = bridge.wakeup_stats
    bridge.start()

    # portfile LAST: its appearance is the fabric's READY signal, and by
    # now both faces answer (tmp+rename so a reader never sees a torn
    # write)
    tmp = f"{args.portfile}.tmp"
    with open(tmp, "w") as f:
        f.write(str(thread.port))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, args.portfile)
    logger.info(
        "shard %d/%d serving (control port %d, %d frontend ring(s),"
        " model version %s)",
        args.shard, args.num_shards, thread.port, len(rings),
        service.model_version,
    )

    stop = threading.Event()

    def _on_term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    while not stop.is_set() and not service._stop_event.is_set():
        stop.wait(0.5)
    logger.info("shard %d draining", args.shard)
    # frontends are already stopped/draining when SIGTERM arrives, so the
    # batcher flush answers everything still parked before the rings close
    service.close()
    bridge.stop()
    thread.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
