"""Multi-process serving tier: SO_REUSEPORT HTTP frontends + shm rings.

The query-serving ceiling on a small box is the GIL-serialized python of
the HTTP stack itself (~2.5 ms/request -> ~400 qps at 32 clients on the
2-core box), not the models. This package splits serving into N frontend
WORKER PROCESSES -- each binds its own ``SO_REUSEPORT`` listener and runs
an accept/parse/validate loop -- feeding one device-owning SCORER process
(the existing :class:`~predictionio_tpu.workflow.create_server.QueryService`
with its ``MicroBatcher`` unchanged) through per-worker shared-memory
message rings. "Add a core" becomes "add a frontend worker".

This ``__init__`` must stay import-light: the frontend worker entry point
(``python -m predictionio_tpu.serving.frontend``) runs in a fresh
interpreter per worker and must come up in well under a second -- no jax,
no storage, no engine imports (``predictionio_tpu.workflow`` pulls in all
three).
"""
