"""Fixed-slot shared-memory message rings + eventfd/FIFO wakeups.

The IPC substrate of the multi-process serving tier: each frontend worker
shares ONE mmap'd ring file with the scorer process, holding two
single-producer/single-consumer message rings (requests: worker -> scorer;
completions: scorer -> worker), a seqlock-guarded stats region the worker
publishes its metrics snapshot through, and a small header (generation,
worker state) the supervisor uses to track respawns.

Design points:

- **Fixed slots, monotonic counters.** Each ring is ``slots`` slots of
  ``slot_bytes``; ``head``/``tail`` are free-running u64 sequence numbers
  (slot index = seq % slots), so full/empty tests are plain subtraction
  and a torn counter can never alias a wrapped ring. The producer writes
  the slot payload FIRST and publishes by storing ``head`` after -- on
  x86-64 (TSO: stores are not reordered with earlier stores, loads not
  reordered with earlier loads) that is release/acquire for free. Each
  side's in-process callers serialize with their own ``threading.Lock``;
  the cross-process contract is strictly SPSC.
- **Oversize spill.** A message that does not fit a slot (large query
  body, big response page) spills to a one-off file next to the ring and
  the slot carries only the file name -- the ring never blocks on or
  fragments for a rare large payload. The consumer unlinks the spill.
- **Futex-style wakeups.** Blocking "ring has work" waits ride an
  ``eventfd`` (inherited across the spawn via ``pass_fds``; one fd, both
  directions of ownership work because eventfd is just a kernel counter)
  with a named-FIFO fallback for platforms without ``os.eventfd``. Waits
  always carry a timeout: a lost wakeup degrades to one poll interval,
  never a hang.

Durability is explicitly NOT a goal (unlike ``data/wal.py``): rings hold
in-flight RPCs whose clients are waiting on open sockets; a crash loses
exactly the in-flight window and nothing else.
"""

from __future__ import annotations

import json
import mmap
import os
import select
import struct

MAGIC = 0x5049_4F52  # "PIOR"
VERSION = 1

#: header field offsets (u32 unless noted)
_OFF_MAGIC = 0
_OFF_VERSION = 4
_OFF_GENERATION = 8     # u64
_OFF_STATE = 16
_OFF_REQ_HEAD = 24      # u64; producer = worker
_OFF_REQ_TAIL = 32      # u64; consumer = scorer
_OFF_CMP_HEAD = 40      # u64; producer = scorer
_OFF_CMP_TAIL = 48      # u64; consumer = worker
_OFF_STATS_SEQ = 56     # u64; seqlock (odd = write in progress)
_OFF_STATS_LEN = 64

HEADER_BYTES = 4096
STATS_BYTES = 65536

#: worker lifecycle states (header ``state`` field)
STATE_INIT = 0
STATE_READY = 1
STATE_DRAINING = 2
STATE_DONE = 3

#: per-slot header: u32 meta_len, u32 body_len, u32 flags
_SLOT_HEADER = struct.Struct("<III")
_FLAG_SPILLED = 1


class RingFull(Exception):
    """Raised by ``push`` when the consumer is ``slots`` messages behind;
    callers map this to backpressure (the frontend's 429)."""


class Wakeup:
    """Cross-process wake signal: eventfd when available, named FIFO else.

    ``create()`` in the parent; the child reconstructs from ``spec()``
    (``fd:N`` specs require the fd in the child's ``pass_fds``). Both
    processes may ``signal()`` and ``wait()`` the same object -- it is a
    counter, not a channel.
    """

    def __init__(self, fd: int | None = None, fifo_path: str | None = None):
        self._fd = fd
        self._fifo_path = fifo_path
        self._fifo_rfd: int | None = None
        self._fifo_wfd: int | None = None
        #: cumulative wake accounting, per PROCESS-LOCAL object (the fd is
        #: shared across the spawn; these counters are not): ``signals`` =
        #: signal() calls issued from this side, ``wakes`` = drain() calls
        #: (each one a real "this side was woken / serviced the fd"
        #: event). The scorer aggregates them into the wakeup-budget
        #: gauges (``pio_scorer_wakeups_per_request``).
        self.signals = 0
        self.wakes = 0

    @classmethod
    def create(cls, fifo_dir: str, name: str) -> "Wakeup":
        if hasattr(os, "eventfd"):
            fd = os.eventfd(0, os.EFD_NONBLOCK)
            os.set_inheritable(fd, True)
            return cls(fd=fd)
        path = os.path.join(fifo_dir, f"{name}.fifo")
        os.mkfifo(path)
        return cls(fifo_path=path)

    def spec(self) -> str:
        if self._fd is not None:
            return f"fd:{self._fd}"
        return f"fifo:{self._fifo_path}"

    @classmethod
    def from_spec(cls, spec: str) -> "Wakeup":
        kind, _, rest = spec.partition(":")
        if kind == "fd":
            return cls(fd=int(rest))
        if kind == "fifo":
            return cls(fifo_path=rest)
        raise ValueError(f"bad wakeup spec {spec!r}")

    @property
    def pass_fd(self) -> int | None:
        """The fd a spawner must include in ``pass_fds`` (eventfd only)."""
        return self._fd

    def _read_fd(self) -> int:
        if self._fd is not None:
            return self._fd
        if self._fifo_rfd is None:
            self._fifo_rfd = os.open(
                self._fifo_path, os.O_RDONLY | os.O_NONBLOCK
            )
        return self._fifo_rfd

    def signal(self) -> None:
        self.signals += 1
        try:
            if self._fd is not None:
                os.write(self._fd, struct.pack("<Q", 1))
                return
            if self._fifo_wfd is None:
                # O_NONBLOCK open fails with ENXIO until a reader exists;
                # the reader's timeout covers the pre-open window
                self._fifo_wfd = os.open(
                    self._fifo_path, os.O_WRONLY | os.O_NONBLOCK
                )
            os.write(self._fifo_wfd, b"\x01")
        except (BlockingIOError, FileNotFoundError, OSError):
            # a saturated counter/pipe still wakes the reader; a missing
            # reader will poll on its own timeout
            pass

    def wait(self, timeout: float) -> bool:
        """Block up to ``timeout`` for a signal; drains the counter."""
        try:
            fd = self._read_fd()
            ready, _, _ = select.select([fd], [], [], timeout)
            if not ready:
                return False
            self.drain()
            return True
        except OSError:
            return False

    def drain(self) -> None:
        self.wakes += 1
        try:
            fd = self._read_fd()
            while True:
                if not os.read(fd, 4096):
                    return
        except (BlockingIOError, OSError):
            return

    def fileno(self) -> int:
        return self._read_fd()

    def close(self) -> None:
        for fd in (self._fd, self._fifo_rfd, self._fifo_wfd):
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._fd = self._fifo_rfd = self._fifo_wfd = None


class MessageRing:
    """One direction of the ring: SPSC, fixed slots, JSON meta + raw body."""

    def __init__(
        self,
        mm: mmap.mmap,
        head_off: int,
        tail_off: int,
        data_off: int,
        slots: int,
        slot_bytes: int,
        spill_dir: str,
        name: str,
    ):
        self._mm = mm
        self._head_off = head_off
        self._tail_off = tail_off
        self._data_off = data_off
        self._slots = slots
        self._slot_bytes = slot_bytes
        self._spill_dir = spill_dir
        self._name = name

    def _get(self, off: int) -> int:
        return struct.unpack_from("<Q", self._mm, off)[0]

    def _set(self, off: int, value: int) -> None:
        struct.pack_into("<Q", self._mm, off, value)

    def pending(self) -> int:
        return self._get(self._head_off) - self._get(self._tail_off)

    def push(self, meta: dict, body: bytes = b"") -> None:
        """Publish one message; raises :class:`RingFull` when the consumer
        is a full ring behind (the backpressure signal)."""
        head = self._get(self._head_off)
        if head - self._get(self._tail_off) >= self._slots:
            raise RingFull(self._name)
        data = json.dumps(meta, separators=(",", ":")).encode("utf-8")
        flags = 0
        if _SLOT_HEADER.size + len(data) + len(body) > self._slot_bytes:
            # oversize: the whole message moves to a one-off spill file,
            # the slot carries only its name (unique per sequence number)
            fname = f"{self._name}-{head}.spill"
            with open(os.path.join(self._spill_dir, fname), "wb") as f:
                f.write(struct.pack("<I", len(data)))
                f.write(data)
                f.write(body)
            data = json.dumps({"_spill": fname}).encode("utf-8")
            body = b""
            flags = _FLAG_SPILLED
        off = self._data_off + (head % self._slots) * self._slot_bytes
        _SLOT_HEADER.pack_into(self._mm, off, len(data), len(body), flags)
        off += _SLOT_HEADER.size
        self._mm[off:off + len(data)] = data
        off += len(data)
        self._mm[off:off + len(body)] = body
        # publish AFTER the payload: the store ordering is the fence
        self._set(self._head_off, head + 1)

    def pop(self) -> tuple[dict, bytes] | None:
        tail = self._get(self._tail_off)
        if tail >= self._get(self._head_off):
            return None
        off = self._data_off + (tail % self._slots) * self._slot_bytes
        meta_len, body_len, flags = _SLOT_HEADER.unpack_from(self._mm, off)
        off += _SLOT_HEADER.size
        meta = json.loads(bytes(self._mm[off:off + meta_len]))
        body = bytes(self._mm[off + meta_len:off + meta_len + body_len])
        self._set(self._tail_off, tail + 1)
        if flags & _FLAG_SPILLED:
            path = os.path.join(self._spill_dir, meta["_spill"])
            with open(path, "rb") as f:
                blob = f.read()
            os.unlink(path)
            (meta_len,) = struct.unpack_from("<I", blob, 0)
            meta = json.loads(blob[4:4 + meta_len])
            body = blob[4 + meta_len:]
        return meta, body


class RingFile:
    """The per-worker shared file: header + stats + request/completion
    rings. ``create`` (re)initializes -- truncating any carcass from a
    killed worker -- and ``attach`` maps an existing file read-write."""

    def __init__(self, path: str, mm: mmap.mmap, fileobj):
        self.path = path
        self._mm = mm
        self._file = fileobj
        slots = struct.unpack_from("<I", mm, HEADER_BYTES - 8)[0]
        slot_bytes = struct.unpack_from("<I", mm, HEADER_BYTES - 4)[0]
        spill_dir = os.path.dirname(os.path.abspath(path))
        name = os.path.splitext(os.path.basename(path))[0]
        req_off = HEADER_BYTES + STATS_BYTES
        cmp_off = req_off + slots * slot_bytes
        self.requests = MessageRing(
            mm, _OFF_REQ_HEAD, _OFF_REQ_TAIL, req_off,
            slots, slot_bytes, spill_dir, f"{name}-req",
        )
        self.completions = MessageRing(
            mm, _OFF_CMP_HEAD, _OFF_CMP_TAIL, cmp_off,
            slots, slot_bytes, spill_dir, f"{name}-cmp",
        )
        self.slots = slots
        self.slot_bytes = slot_bytes

    @classmethod
    def create(
        cls, path: str, slots: int, slot_bytes: int, generation: int
    ) -> "RingFile":
        size = HEADER_BYTES + STATS_BYTES + 2 * slots * slot_bytes
        # O_TRUNC via "wb": a respawn over a dead worker's file starts
        # from zeroed counters; the old process's mapping (if any) now
        # points at the orphaned inode and cannot corrupt this one
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.truncate(size)
        os.replace(tmp, path)
        f = open(path, "r+b")
        try:
            mm = mmap.mmap(f.fileno(), size)
            struct.pack_into("<I", mm, _OFF_MAGIC, MAGIC)
            struct.pack_into("<I", mm, _OFF_VERSION, VERSION)
            struct.pack_into("<Q", mm, _OFF_GENERATION, generation)
            struct.pack_into("<I", mm, _OFF_STATE, STATE_INIT)
            struct.pack_into("<I", mm, HEADER_BYTES - 8, slots)
            struct.pack_into("<I", mm, HEADER_BYTES - 4, slot_bytes)
            return cls(path, mm, f)
        except BaseException:
            # a failed map/header init must not strand the descriptor on
            # the supervisor's respawn loop (pio check R001)
            f.close()
            raise

    @classmethod
    def attach(cls, path: str) -> "RingFile":
        f = open(path, "r+b")
        size = os.fstat(f.fileno()).st_size
        mm = mmap.mmap(f.fileno(), size)
        if struct.unpack_from("<I", mm, _OFF_MAGIC)[0] != MAGIC:
            mm.close()
            f.close()
            raise ValueError(f"{path}: not a pio ring file")
        return cls(path, mm, f)

    # -- header fields ------------------------------------------------------
    @property
    def generation(self) -> int:
        return struct.unpack_from("<Q", self._mm, _OFF_GENERATION)[0]

    @property
    def state(self) -> int:
        return struct.unpack_from("<I", self._mm, _OFF_STATE)[0]

    def set_state(self, state: int) -> None:
        struct.pack_into("<I", self._mm, _OFF_STATE, state)

    # -- stats region (worker-published metrics snapshot) -------------------
    def write_stats(self, obj: dict) -> None:
        """Seqlock write: readers retry while ``seq`` is odd or changed
        under them; a SIGKILL mid-write leaves an odd seq that readers
        permanently skip (they fall back to 'no stats')."""
        data = json.dumps(obj, separators=(",", ":")).encode("utf-8")
        if len(data) > STATS_BYTES:
            return  # a pathological label explosion must not crash serving
        seq = struct.unpack_from("<Q", self._mm, _OFF_STATS_SEQ)[0]
        struct.pack_into("<Q", self._mm, _OFF_STATS_SEQ, seq + 1)  # odd
        self._mm[HEADER_BYTES:HEADER_BYTES + len(data)] = data
        struct.pack_into("<I", self._mm, _OFF_STATS_LEN, len(data))
        struct.pack_into("<Q", self._mm, _OFF_STATS_SEQ, seq + 2)  # even

    def read_stats(self) -> dict | None:
        for _ in range(8):
            seq0 = struct.unpack_from("<Q", self._mm, _OFF_STATS_SEQ)[0]
            if seq0 == 0 or seq0 % 2:
                return None
            length = struct.unpack_from("<I", self._mm, _OFF_STATS_LEN)[0]
            data = bytes(self._mm[HEADER_BYTES:HEADER_BYTES + length])
            if struct.unpack_from("<Q", self._mm, _OFF_STATS_SEQ)[0] == seq0:
                try:
                    return json.loads(data)
                except ValueError:
                    return None
        return None

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass
        self._file.close()
