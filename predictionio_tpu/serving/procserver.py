"""Scorer-side bridge of the multi-process serving tier.

The scorer process (the one that owns the device, the models, and the
``MicroBatcher``) runs a :class:`ScorerBridge` instead of an HTTP
listener: it spawns N frontend worker processes (fresh interpreters via
``subprocess`` -- never ``fork()``: this process is full of threads and
locks, the exact hazard ``pio check`` C004 exists for), consumes their
request rings, and answers through two dispatch paths:

- **The async fast path** (``dispatch="async"``, the default with
  batching on): the ring consumer itself parses a ``POST /queries.json``
  frame and submits it straight into the micro-batcher
  (``QueryService.submit_query_async``); a ``Future.add_done_callback``
  running on the batcher's FLUSHER thread serializes the response and
  pushes the completion ring entry. Zero dispatcher threads touch the
  query path, and a request costs TWO cross-thread wakeups (consumer
  eventfd wake + completion eventfd) instead of the sync chain's five
  (consumer wake -> SimpleQueue handoff -> dispatcher -> flusher ->
  future wake -> completion push). Because the pushing thread is the
  flusher, a full completion ring must NEVER park it -- overflow lands
  on a timer-driven retry queue (:class:`_CompletionRetry`) and the
  flusher moves on. ``pio check`` C005 statically gates the
  no-blocking-in-done-callbacks contract this creates.
- **The dispatcher pool** survives for control routes (``/metrics``,
  ``/models/*``, ``/reload``, ``/stop``, the info page -- everything
  that is not a query) and as the whole dispatch model when
  ``dispatch="sync"`` or batching is off: frames go through the
  unchanged :class:`~predictionio_tpu.utils.http.Router` on pool
  threads, exactly the pre-async tier.

Either way responses are produced by the same router/service code, so
bodies stay byte-identical across dispatch modes and vs single-process.

Port discovery without a blackhole window: the bridge binds ONE
``SO_REUSEPORT`` socket on the requested port (port 0 resolves to a real
ephemeral port) and keeps it bound but **never listening** -- a TCP
socket that has not called ``listen()`` is not in the kernel's
``SO_REUSEPORT`` delivery group, so it reserves the port for respawns
without stealing SYNs from the workers.

Supervision: a SIGKILLed worker is respawned with a fresh ring file under
a bumped generation; completions addressed to the dead generation are
dropped (its clients are gone with its sockets), and everything else
keeps serving. Backpressure: the bridge admits at most ``max_inflight``
requests into the scorer (fast path and pool alike); beyond that it
simply stops popping, the rings fill, and the frontends answer 429 --
the ingest pipeline's bounded-queue contract at the serving tier.

The wakeup budget is MEASURED, not asserted: eventfd wakes and thread
handoffs on the query path feed ``pio_scorer_wakeups_per_request`` (and
``pio_scorer_dispatch_threads``), rendered by ``pio top`` -- the gauges
behind the 5-to-2 claim.
"""

from __future__ import annotations

import logging
import os
import queue
import select
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass
from urllib.parse import parse_qs, urlsplit

from predictionio_tpu.serving import shmring
from predictionio_tpu.utils.http import Request

logger = logging.getLogger("pio.procserver")


@dataclass
class FrontendConfig:
    """Process-tier knobs (CLI: ``pio deploy --frontend-workers N``)."""

    workers: int = 2
    #: per-direction ring capacity (messages); the backpressure horizon
    ring_slots: int = 128
    #: per-slot byte budget; bigger messages spill to one-off files
    slot_bytes: int = 32768
    #: concurrent requests admitted into the scorer (the backpressure
    #: horizon and, with batching, the micro-batcher's coalescing
    #: ceiling). Under ``dispatch="sync"`` it is ALSO the dispatcher
    #: thread count -- and must stay small there: measured on the 2-core
    #: box, 64 dispatcher threads collapsed throughput 13x (every batch
    #: completion woke a thread herd that thrashed the GIL and
    #: scheduler). The async fast path has no per-request threads, so
    #: this is pure admission control.
    max_inflight: int = 16
    #: dispatch model: "async" (ring consumer -> micro-batcher future ->
    #: flusher callback; zero dispatcher threads on the query path) or
    #: "sync" (the dispatcher-pool tier, kept for A/B and for
    #: batching-disabled deploys, which always use the pool)
    dispatch: str = "async"
    #: pool threads kept for CONTROL routes under async dispatch
    #: (/metrics, /models/*, /reload, ...); query traffic never uses them
    control_threads: int = 2
    #: ``sched_setaffinity`` pinning: frontend workers get one core each
    #: from the top of the process affinity set, the scorer keeps the
    #: rest (CLI --pin-cpus / PIO_PIN_CPUS=1). No-op with <2 cores or on
    #: platforms without sched_setaffinity.
    pin_cpus: bool = False
    #: how often a worker publishes its metrics snapshot
    stats_flush_s: float = 0.25
    #: how long to wait for a spawned worker to reach READY
    spawn_timeout_s: float = 40.0

    def __post_init__(self) -> None:
        if self.dispatch not in ("async", "sync"):
            raise ValueError(
                f"dispatch must be 'async' or 'sync', got {self.dispatch!r}"
            )

    def describe(self) -> dict:
        return {
            "workers": self.workers,
            "ringSlots": self.ring_slots,
            "slotBytes": self.slot_bytes,
            "maxInflight": self.max_inflight,
            "dispatch": self.dispatch,
            "pinCpus": self.pin_cpus,
        }


class _Worker:
    """One spawned frontend: its ring, process handle, and generation.
    Under the sharded fabric's ATTACHED bridges the ring belongs to a
    frontend some other process supervises, so ``proc`` is None."""

    def __init__(self, index: int, generation: int, ring: shmring.RingFile,
                 proc: subprocess.Popen | None = None):
        self.index = index
        self.generation = generation
        self.ring = ring
        self.proc = proc
        self.dead = False
        #: serializes pool threads producing into the SPSC completion ring
        self.cmp_lock = threading.Lock()


class _CompletionRetry:
    """Timer-driven retry for completions that hit a full completion
    ring. The sync tier parked the dispatcher thread that hit
    ``RingFull`` (bounded at 5 s); on the async fast path the pushing
    thread is the micro-batcher's FLUSHER, and parking it would stall
    every in-flight batch behind one briefly-descheduled worker. So
    full-ring completions are parked here instead and one timer thread
    retries them every couple of milliseconds until the worker drains a
    slot, the worker dies (respawn: its clients are gone), or the
    deadline expires and the response is dropped with a warning --
    exactly the sync tier's bounded-retry contract, minus the parked
    thread. The thread sleeps on a condition variable whenever the queue
    is empty, so the common case (rings never full) costs nothing.

    Each parked entry still owns its admission permit
    (``ScorerBridge._inflight``); the permit is released when the entry
    resolves, so a backed-up worker keeps exerting backpressure."""

    _INTERVAL_S = 0.002
    _DEADLINE_S = 5.0

    def __init__(self, bridge: "ScorerBridge"):
        self._bridge = bridge
        self._cv = threading.Condition()
        #: [worker, rmeta, payload, is_query, deadline]
        self._entries: list = []
        self._stopped = False
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="pio-scorer-cmp-retry", daemon=True
        )
        self._thread.start()

    def depth(self) -> int:
        with self._cv:
            return len(self._entries)

    def add(self, w: _Worker, rmeta: dict, payload: bytes,
            is_query: bool) -> None:
        with self._cv:
            if not self._stopped:
                self._entries.append(
                    (w, rmeta, payload, is_query,
                     time.monotonic() + self._DEADLINE_S)
                )
                self._cv.notify()
                return
        # stopped: the tier is tearing down; drop, release the permit
        self._bridge._inflight.release()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            leftovers = len(self._entries)
            self._entries.clear()
            self._cv.notify()
        for _ in range(leftovers):
            self._bridge._inflight.release()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._entries and not self._stopped:
                    self._cv.wait()
                if self._stopped:
                    return
                entries = self._entries
                self._entries = []
            keep = []
            for entry in entries:
                w, rmeta, payload, is_query, deadline = entry
                pushed = dead = False
                with w.cmp_lock:
                    if w.dead:
                        dead = True
                    else:
                        try:
                            w.ring.completions.push(rmeta, payload)
                            pushed = True
                        except shmring.RingFull:
                            pass
                if dead:
                    self._bridge._inflight.release()
                    continue
                self._bridge._wakes[w.index][1].signal()
                if pushed:
                    if is_query:
                        self._bridge._n_signals += 1
                    self._bridge._inflight.release()
                elif time.monotonic() > deadline:
                    logger.warning(
                        "completion ring full for worker %d for >%.0fs; "
                        "dropping response", w.index, self._DEADLINE_S,
                    )
                    self._bridge._inflight.release()
                else:
                    keep.append(entry)
            if keep:
                with self._cv:
                    if self._stopped:
                        for _ in keep:
                            self._bridge._inflight.release()
                        return
                    self._entries = keep + self._entries
                time.sleep(self._INTERVAL_S)


class ScorerBridge:
    """Spawn/supervise frontends; pump rings through the router (control
    routes / sync mode) or straight into the micro-batcher (the async
    query fast path)."""

    def __init__(
        self,
        router,
        host: str,
        port: int,
        config: FrontendConfig | None = None,
        server_name: str = "pio-queryserver",
        registry=None,
        async_query=None,
        attach: list | None = None,
    ):
        self._router = router
        self._host = host
        self._requested_port = port
        self.config = config or FrontendConfig()
        #: ATTACHED mode (the sharded fabric): ``attach`` is a list of
        #: ``(RingFile, wake_req, wake_cmp)`` triples for rings some
        #: OTHER process created and whose producers it supervises. The
        #: bridge only pumps: no port reservation, no spawning, no
        #: respawn supervision, no cpu pinning -- teardown closes this
        #: process's mappings and stops its threads, nothing else.
        self._attach = attach
        if attach is None and self.config.workers < 1:
            raise ValueError("frontend workers must be >= 1")
        self._server_name = server_name
        self._registry = registry
        self._reserve: socket.socket | None = None
        self.port: int | None = None
        self._dir: str | None = None
        #: index -> (req, cmp, stop) wakeups; created once, reused across
        #: respawns so the consumer's select set never churns
        self._wakes: dict[int, tuple] = {}
        self._workers: list[_Worker] = []
        self._lock = threading.Lock()
        self._inflight = threading.Semaphore(self.config.max_inflight)
        self._draining = False
        self._stopping = False
        #: consumer -> dispatcher hand-off; SimpleQueue's C put/get is the
        #: cheapest in-process wakeup available (no Future allocation)
        self._work: queue.SimpleQueue = queue.SimpleQueue()
        self._dispatchers: list[threading.Thread] = []
        self._consumer: threading.Thread | None = None
        self._supervisor: threading.Thread | None = None
        self._respawns = 0
        #: serializes stop() callers end-to-end (idempotent teardown)
        self._stop_lock = threading.Lock()
        #: the async fast path: ``(request, on_done)`` submitter
        #: (``QueryService.submit_query_async``); None = every frame goes
        #: through the dispatcher pool (the sync tier)
        self._async_query = async_query
        self._retry = _CompletionRetry(self)
        #: worker index -> cpu core, fixed at start() so respawns re-pin
        self._pin_map: dict[int, int] | None = None
        #: the process affinity before --pin-cpus narrowed it; restored
        #: at teardown
        self._orig_affinity: set | None = None
        # -- measured wakeup budget (query path only; plain ints, +=
        # is GIL-atomic enough for telemetry) --------------------------
        #: query frames popped from the rings
        self._n_query = 0
        #: consumer select-wakes consumed by a query frame (the first
        #: frame popped after a wake claims it; the rest of the drain is
        #: the amortization the batching design pays for)
        self._n_wakes_query = 0
        #: query frames handed to the dispatcher pool (sync mode only)
        self._n_handoffs = 0
        #: completion-ring signal()s for query responses
        self._n_signals = 0
        #: worker index -> "a req-eventfd wake is unclaimed" flag
        self._wake_pending: dict[int, bool] = {}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ScorerBridge":
        if self._attach is not None:
            return self._start_attached()
        if not hasattr(socket, "SO_REUSEPORT"):
            raise RuntimeError(
                "multi-process serving needs SO_REUSEPORT (Linux/BSD); "
                "deploy without --frontend-workers on this platform"
            )
        try:
            self._reserve = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._reserve.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            self._reserve.bind((self._host, self._requested_port))
            self.port = self._reserve.getsockname()[1]
            self._dir = tempfile.mkdtemp(prefix="pio-frontend-")
            self._pin_map = self._pin_plan()
            if self._pin_map is not None:
                try:
                    # remember the pre-pin mask: teardown restores it, so
                    # back-to-back pinned bridges in one process (the
                    # bench's A/B arms, the sweep test) each plan from
                    # the FULL affinity set instead of the previous arm's
                    # shrunken one
                    self._orig_affinity = os.sched_getaffinity(0)
                    os.sched_setaffinity(0, self._pin_map["scorer"])
                    logger.info(
                        "pinned scorer to cpus %s",
                        sorted(self._pin_map["scorer"]),
                    )
                except OSError:
                    logger.warning(
                        "cpu pinning failed for scorer", exc_info=True
                    )
            # async fast path: the pool only ever sees control routes, so
            # a couple of threads suffice; sync mode keeps the full
            # max_inflight-wide pool (= the query dispatch concurrency)
            n_dispatch = (
                self.config.max_inflight
                if self._async_query is None
                else max(1, min(self.config.control_threads,
                                self.config.max_inflight))
            )
            for k in range(n_dispatch):
                t = threading.Thread(
                    target=self._dispatch_loop, name=f"pio-scorer-{k}",
                    daemon=True,
                )
                t.start()
                self._dispatchers.append(t)
            self._retry.start()
            for i in range(self.config.workers):
                self._wakes[i] = (
                    shmring.Wakeup.create(self._dir, f"req-{i}"),
                    shmring.Wakeup.create(self._dir, f"cmp-{i}"),
                    shmring.Wakeup.create(self._dir, f"stop-{i}"),
                )
                self._workers.append(self._launch(i, generation=1))
            self._await_ready(self._workers)
        except BaseException:
            # a half-started tier must not outlive this call: workers
            # that already reached READY are listening on the port with
            # no consumer behind them -- clients would hang, and the
            # orphans would hold the port after the parent dies
            self._teardown(kill=True)
            raise
        self._start_consumer()
        self._supervisor = threading.Thread(
            target=self._supervise, name="pio-scorer-supervisor", daemon=True
        )
        self._supervisor.start()
        self._gauge_workers()
        return self

    def _start_consumer(self) -> None:
        # ONE creation site for the consumer role: `_wake_pending` (and
        # the wakeup-budget counters) are confined to this thread, and
        # both the spawned and the attached start paths must share that
        # confinement
        self._consumer = threading.Thread(
            target=self._consume, name="pio-scorer-consumer", daemon=True
        )
        self._consumer.start()

    def _start_attached(self) -> "ScorerBridge":
        """Start over pre-created rings: dispatcher pool + retry timer +
        consumer, nothing that owns processes or sockets. The same
        wake_req object may back several ring indexes (one shard's
        request eventfd is signalled by every frontend); duplicate fds in
        the consumer's select set are harmless, and ``Wakeup.close`` is
        idempotent per object."""
        for i, (ring, wake_req, wake_cmp) in enumerate(self._attach):
            self._wakes[i] = (wake_req, wake_cmp)
            self._workers.append(_Worker(i, ring.generation, ring))
        n_dispatch = (
            self.config.max_inflight
            if self._async_query is None
            else max(1, min(self.config.control_threads,
                            self.config.max_inflight))
        )
        for k in range(n_dispatch):
            t = threading.Thread(
                target=self._dispatch_loop, name=f"pio-scorer-{k}",
                daemon=True,
            )
            t.start()
            self._dispatchers.append(t)
        self._retry.start()
        self._start_consumer()
        return self

    def _stop_attached(self) -> None:
        with self._lock:
            if self._stopping:
                return
            self._draining = True
            self._stopping = True
        if self._consumer is not None:
            self._consumer.join(timeout=5.0)
        for _ in self._dispatchers:
            self._work.put(None)
        for t in self._dispatchers:
            t.join(timeout=10.0)
        self._retry.stop()
        # snapshot under the bridge lock: in spawned mode the supervisor
        # swaps _workers slots on respawn under this lock (attached mode
        # has no supervisor, but the discipline is one lock for the list)
        with self._lock:
            workers = list(self._workers)
        seen: set[int] = set()
        for w in workers:
            with w.cmp_lock:
                w.dead = True
            w.ring.close()
        for wakes in self._wakes.values():
            for wake in wakes:
                if id(wake) not in seen:
                    seen.add(id(wake))
                    wake.close()

    def _pin_plan(self) -> dict | None:
        """The --pin-cpus core assignment: frontends take one core each
        from the TOP of the process affinity set, the scorer keeps the
        rest (its consumer, flusher, and BLAS threads want headroom).
        With fewer spare cores than workers, workers share the spare set
        round-robin (the 2-core box: scorer on core 0, every frontend on
        core 1). Skipped -- loudly -- when pinning cannot help."""
        if not self.config.pin_cpus:
            return None
        if not hasattr(os, "sched_setaffinity"):
            logger.warning("--pin-cpus unsupported on this platform")
            return None
        try:
            cores = sorted(os.sched_getaffinity(0))
        except OSError:
            logger.warning("--pin-cpus skipped: affinity unreadable")
            return None
        if len(cores) < 2:
            logger.warning(
                "--pin-cpus skipped: only %d cpu(s) available", len(cores)
            )
            return None
        n_frontend = min(self.config.workers, len(cores) - 1)
        frontend = cores[len(cores) - n_frontend:]
        return {
            "scorer": set(cores[: len(cores) - n_frontend]),
            "workers": {
                i: frontend[i % n_frontend]
                for i in range(self.config.workers)
            },
        }

    def _launch(self, index: int, generation: int) -> _Worker:
        path = os.path.join(self._dir, f"worker-{index}.ring")
        ring = shmring.RingFile.create(
            path, self.config.ring_slots, self.config.slot_bytes, generation
        )
        wake_req, wake_cmp, wake_stop = self._wakes[index]
        cmd = [
            sys.executable, "-m", "predictionio_tpu.serving.frontend",
            "--ring", path,
            "--host", self._host,
            "--port", str(self.port),
            "--worker", str(index),
            "--wake-req", wake_req.spec(),
            "--wake-cmp", wake_cmp.spec(),
            "--wake-stop", wake_stop.spec(),
            "--server-name", self._server_name,
            "--stats-flush-s", str(self.config.stats_flush_s),
        ]
        if self._pin_map is not None:
            cmd += ["--pin-cpu", str(self._pin_map["workers"][index])]
        env = dict(os.environ)
        # the worker interpreter must find this package without an install
        pkg_parent = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        pass_fds = tuple(
            fd for w in (wake_req, wake_cmp, wake_stop)
            if (fd := w.pass_fd) is not None
        )
        log = open(os.path.join(self._dir, f"worker-{index}.log"), "ab")
        try:
            proc = subprocess.Popen(
                cmd, pass_fds=pass_fds, env=env,
                stdout=log, stderr=subprocess.STDOUT,
            )
        finally:
            log.close()
        logger.info(
            "frontend worker %d spawned (pid %d, generation %d)",
            index, proc.pid, generation,
        )
        return _Worker(index, generation, ring, proc)

    def _await_ready(self, workers: list[_Worker]) -> None:
        deadline = time.monotonic() + self.config.spawn_timeout_s
        pending = list(workers)
        while pending:
            pending = [
                w for w in pending if w.ring.state == shmring.STATE_INIT
            ]
            if not pending:
                return
            for w in pending:
                if w.proc.poll() is not None:
                    raise RuntimeError(
                        f"frontend worker {w.index} exited "
                        f"rc={w.proc.returncode} before READY "
                        f"(log: {self._worker_log_tail(w.index)!r})"
                    )
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"frontend worker(s) "
                    f"{[w.index for w in pending]} not READY within "
                    f"{self.config.spawn_timeout_s}s"
                )
            time.sleep(0.02)

    def _worker_log_tail(self, index: int, limit: int = 500) -> str:
        try:
            with open(os.path.join(self._dir, f"worker-{index}.log"), "rb") as f:
                return f.read()[-limit:].decode("utf-8", "replace")
        except OSError:
            return ""

    def stop(self) -> None:
        """Graceful drain: workers stop accepting and finish in-flight
        requests (the bridge keeps dispatching while they do), then the
        pool drains and everything is torn down. Idempotent; concurrent
        callers serialize and the second is a no-op."""
        with self._stop_lock:
            if self._attach is not None:
                self._stop_attached()
            else:
                self._stop_locked()

    def _stop_locked(self) -> None:
        with self._lock:
            if self._stopping:
                return
            self._draining = True
        for _, _, wake_stop in self._wakes.values():
            wake_stop.signal()
        # a draining worker legitimately waits up to the frontend's
        # forward timeout for an in-flight answer (first-bucket jit
        # compiles are the sized-for case); killing it sooner would drop
        # exactly the requests the drain contract promises to answer
        from predictionio_tpu.serving.frontend import FORWARD_TIMEOUT_S

        deadline = time.monotonic() + FORWARD_TIMEOUT_S + 5.0
        with self._lock:
            # the supervisor may have been mid-respawn when _draining
            # flipped: its install runs under this lock, so snapshot
            # under it too (pio check C006)
            workers = list(self._workers)
        for w in workers:
            timeout = max(deadline - time.monotonic(), 0.1)
            try:
                w.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                logger.warning(
                    "frontend worker %d did not drain; killing", w.index
                )
                w.proc.kill()
                try:
                    w.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
        with self._lock:
            self._stopping = True
        if self._consumer is not None:
            self._consumer.join(timeout=5.0)
        if self._supervisor is not None:
            self._supervisor.join(timeout=5.0)
        self._teardown()

    def _teardown(self, kill: bool = False) -> None:
        """Release every tier resource; with ``kill`` the workers are
        SIGKILLed first (the start()-failed path, where a graceful drain
        has nothing to drain and orphans must not survive)."""
        with self._lock:
            self._draining = True
            self._stopping = True
            # snapshot under the lock: the supervisor may still be
            # installing a respawned worker into the list (pio check
            # C006 -- the write side holds this lock too)
            workers = list(self._workers)
        for w in workers:
            if kill and w.proc.poll() is None:
                w.proc.kill()
        for w in workers:
            try:
                w.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
        # sentinels queue BEHIND any in-flight work: dispatchers finish
        # the stragglers, then exit
        for _ in self._dispatchers:
            self._work.put(None)
        for t in self._dispatchers:
            t.join(timeout=10.0)
        self._retry.stop()
        for w in workers:
            # a straggler async callback (flusher-side) racing this
            # teardown must see dead and drop, not push into a closed
            # mapping -- the same dead-before-close protocol the
            # supervisor uses on respawn
            with w.cmp_lock:
                w.dead = True
            w.ring.close()
        for wakes in self._wakes.values():
            for wake in wakes:
                wake.close()
        if self._reserve is not None:
            self._reserve.close()
            self._reserve = None
        if self._orig_affinity is not None:
            try:
                os.sched_setaffinity(0, self._orig_affinity)
            except OSError:
                pass
            self._orig_affinity = None
        if self._dir is not None:
            shutil.rmtree(self._dir, ignore_errors=True)

    # -- request pump -------------------------------------------------------
    def _consume(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
                workers = list(self._workers)
            progressed = False
            for w in workers:
                if w.dead:
                    continue
                try:
                    while w.ring.requests.pending():
                        # admission control: no permit -> stop popping;
                        # the ring backs up and the frontend answers 429
                        if not self._inflight.acquire(timeout=0.5):
                            with self._lock:
                                if self._stopping:
                                    return
                            break
                        try:
                            msg = w.ring.requests.pop()
                        except BaseException:
                            # the supervisor can close a retired worker's
                            # ring between the acquire and this read; the
                            # permit must ride every exit out of the pop,
                            # or each lost race permanently shrinks
                            # max_inflight (pio check R001)
                            self._inflight.release()
                            raise
                        if msg is None:
                            self._inflight.release()
                            break
                        progressed = True
                        self._route(w, msg)
                except (ValueError, OSError):
                    # the supervisor retired this worker and closed its
                    # ring between our dead-check and the read; the ONLY
                    # popping thread must survive the race, not die on it
                    if not w.dead:
                        logger.exception(
                            "request ring read failed for live worker %d",
                            w.index,
                        )
                    continue
            if progressed:
                continue
            fds = [wakes[0].fileno() for wakes in self._wakes.values()]
            try:
                ready, _, _ = select.select(fds, [], [], 0.25)
            except OSError:
                ready = []
            for index, wakes in self._wakes.items():
                if wakes[0].fileno() in ready:
                    wakes[0].drain()
                    self._wake_pending[index] = True

    @staticmethod
    def _is_query(meta: dict) -> bool:
        return (
            meta.get("m") == "POST"
            and meta.get("t", "").split("?", 1)[0] == "/queries.json"
        )

    def _route(self, w: _Worker, msg: tuple) -> None:
        """Classify one popped frame: ``POST /queries.json`` takes the
        async fast path ON THIS THREAD (when wired); everything else --
        and every frame in sync mode -- goes to the dispatcher pool. The
        frame that claims a pending eventfd wake also books it against
        its path's wakeup budget."""
        meta = msg[0]
        is_query = self._is_query(meta)
        woke = bool(self._wake_pending.get(w.index))
        if woke:
            self._wake_pending[w.index] = False
        if is_query:
            self._n_query += 1
            if woke:
                self._n_wakes_query += 1
            if self._async_query is not None:
                self._submit_query(w, msg)
                return
            self._n_handoffs += 1
        self._work.put((w, msg))

    def _build_request(self, meta: dict, body: bytes) -> Request:
        parsed = urlsplit(meta["t"])
        query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        return Request(
            method=meta["m"],
            path=parsed.path,
            query=query,
            headers=dict(meta.get("h") or {}),
            body=body,
            path_params={},
            frontend_pc=(
                meta["p"], time.perf_counter(), meta.get("w", "?")
            ),
        )

    def _submit_query(self, w: _Worker, msg: tuple) -> None:
        """The async fast path entry: build the Request and hand it to
        ``submit_query_async`` with this frame's completion continuation.
        ``on_done`` fires exactly once -- synchronously for immediate
        errors, from the micro-batcher's flusher otherwise."""
        meta, body = msg
        try:
            request = self._build_request(meta, body)
            self._async_query(
                request,
                lambda response, w=w, meta=meta: self._complete_query(
                    w, meta, response
                ),
            )
        except Exception:
            # submit_query_async answers its own failures; anything
            # reaching here happened BEFORE the hand-off, so the frame
            # still owes its frontend an answer (and its permit back)
            logger.exception("async submit failed for %s", meta.get("t"))
            from predictionio_tpu.utils.http import Response

            self._complete_query(
                w, meta, Response(500, {"message": "internal server error"})
            )

    def _complete_query(self, w: _Worker, meta: dict, response) -> None:
        """Terminal continuation of the async fast path. Usually runs on
        the micro-batcher's flusher thread, so it MUST NOT block: one
        non-blocking ring push; overflow parks on the timer retry queue
        (``pio check`` C005 gates this contract)."""
        try:
            payload = response.payload()
            rmeta = {
                "i": meta["i"],
                "s": response.status,
                "c": response.content_type,
                "h": response.headers,
            }
        except Exception:
            logger.exception("completion serialization failed")
            self._inflight.release()
            return
        self._deliver(w, rmeta, payload, is_query=True)

    def _dispatch_loop(self) -> None:
        while True:
            item = self._work.get()
            if item is None:
                return
            self._handle(*item)

    def _handle(self, w: _Worker, msg: tuple) -> None:
        delivered = False
        try:
            meta, body = msg
            request = self._build_request(meta, body)
            try:
                response = self._router.dispatch(request)
            except Exception:
                # the router has its own backstops; anything escaping is a
                # dispatch-layer bug, answered like make_server would
                logger.exception("dispatch failed for %s", request.path)
                from predictionio_tpu.utils.http import Response

                response = Response(500, {"message": "internal server error"})
            payload = response.payload()
            rmeta = {
                "i": meta["i"],
                "s": response.status,
                "c": response.content_type,
                "h": response.headers,
            }
            delivered = True  # _deliver owns the permit from here on
            self._deliver(w, rmeta, payload, is_query=self._is_query(meta))
        except Exception:
            logger.exception("completion delivery failed")
        finally:
            if not delivered:
                self._inflight.release()

    def _deliver(
        self, w: _Worker, rmeta: dict, payload: bytes, is_query: bool
    ) -> None:
        """Push one completion toward its worker. Never blocks, never
        raises; owns the inflight permit (released on success, drop, or
        handed to the retry queue with the parked entry).

        A briefly-descheduled worker (measured: ~300 ms scheduler stalls
        under load on sandboxed kernels) can leave its completion ring
        momentarily full; DROPPING would turn that stall into a client
        timeout, so the overflow is parked on the timer retry queue with
        the same 5 s bound the sync tier used -- the worker only has to
        run once within it to drain 128 slots."""
        try:
            pushed = False
            with w.cmp_lock:
                if w.dead:
                    # a respawn retired this worker mid-score: its
                    # clients died with its sockets, drop the answer
                    self._inflight.release()
                    return
                try:
                    w.ring.completions.push(rmeta, payload)
                    pushed = True
                except shmring.RingFull:
                    pass
            self._wakes[w.index][1].signal()
            if pushed:
                if is_query:
                    self._n_signals += 1
                self._inflight.release()
            else:
                self._retry.add(w, rmeta, payload, is_query)
        except Exception:
            logger.exception(
                "completion delivery failed for worker %d", w.index
            )
            self._inflight.release()

    def wakeup_stats(self) -> dict:
        """Measured wakeup/handoff counters for the QUERY path -- the
        source of the ``pio_scorer_wakeups_per_request`` and
        ``pio_scorer_dispatch_threads`` gauges (mirrored into /metrics by
        the query service). ``wake_events`` counts consumer eventfd wakes
        CLAIMED by a query frame (the first frame popped after a wake;
        later frames in the same drain ride it for free -- that
        amortization is real, so it is measured, not assumed)."""
        return {
            "query_requests": self._n_query,
            "wake_events": self._n_wakes_query,
            "handoffs": self._n_handoffs,
            "completion_signals": self._n_signals,
            "dispatch_threads": (
                0 if self._async_query is not None else len(self._dispatchers)
            ),
            "retry_depth": self._retry.depth(),
            "eventfd_signals": sum(
                wakes[1].signals for wakes in self._wakes.values()
            ),
            "eventfd_wakes": sum(
                wakes[0].wakes for wakes in self._wakes.values()
            ),
        }

    # -- supervision --------------------------------------------------------
    #: consecutive failed respawns of one worker index before giving up
    #: (the index stays down; serving continues on surviving workers)
    _MAX_RESPAWN_FAILURES = 6

    def _supervise(self) -> None:
        #: index -> (consecutive failures, next attempt monotonic time)
        backoff: dict[int, tuple[int, float]] = {}
        while True:
            time.sleep(0.2)
            with self._lock:
                if self._stopping or self._draining:
                    return
                workers = list(self._workers)
            for w in workers:
                if w.proc.poll() is None or w.dead:
                    continue
                logger.warning(
                    "frontend worker %d died (rc=%s); respawning",
                    w.index, w.proc.returncode,
                )
                w.dead = True
                backoff.setdefault(w.index, (0, time.monotonic()))
            for index in sorted(backoff):
                failures, next_try = backoff[index]
                if time.monotonic() < next_try:
                    continue
                with self._lock:
                    old = self._workers[index]
                replacement = self._launch(index, old.generation + 1)
                try:
                    self._await_ready([replacement])
                except RuntimeError:
                    # a replacement that never reached READY must NOT be
                    # installed (the next sweep would respawn it at 5/s
                    # forever); back off exponentially, then give up loud
                    logger.exception(
                        "respawned frontend worker %d failed to start "
                        "(attempt %d)", index, failures + 1,
                    )
                    replacement.proc.kill()
                    replacement.ring.close()
                    failures += 1
                    if failures >= self._MAX_RESPAWN_FAILURES:
                        logger.error(
                            "giving up on frontend worker %d after %d "
                            "failed respawns; serving continues on the "
                            "remaining workers", index, failures,
                        )
                        del backoff[index]
                    else:
                        backoff[index] = (
                            failures,
                            time.monotonic() + min(0.5 * 2 ** failures, 30.0),
                        )
                    continue
                with self._lock:
                    if self._draining or self._stopping:
                        replacement.proc.kill()
                        return
                    self._workers[index] = replacement
                    self._respawns += 1
                del backoff[index]
                with old.cmp_lock:
                    # dead=True is already visible: in-flight completions
                    # skip the push, so nobody holds the mapping we close
                    old.ring.close()
                self._gauge_workers()

    def _gauge_workers(self) -> None:
        if self._registry is None:
            return
        self._registry.set_gauge(
            "pio_frontend_workers", float(self.config.workers),
            help="Configured frontend worker processes",
        )
        self._registry.set_counter(
            "pio_frontend_respawns_total", float(self._respawns),
            help="Frontend workers respawned after unexpected exit",
        )

    # -- metrics aggregation ------------------------------------------------
    def metric_snapshots(self) -> list[dict]:
        """Every live worker's published registry snapshot (the
        ``extra_snapshots`` hook of ``instrumented_router``)."""
        with self._lock:
            workers = list(self._workers)
        out = []
        for w in workers:
            if w.dead:
                continue
            try:
                snap = w.ring.read_stats()
            except (ValueError, OSError):
                continue  # retired ring closed mid-scrape
            if snap:
                out.append(snap)
        return out
