"""Scorer-side bridge of the multi-process serving tier.

The scorer process (the one that owns the device, the models, and the
``MicroBatcher``) runs a :class:`ScorerBridge` instead of an HTTP
listener: it spawns N frontend worker processes (fresh interpreters via
``subprocess`` -- never ``fork()``: this process is full of threads and
locks, the exact hazard ``pio check`` C004 exists for), consumes their
request rings, dispatches each message through the unchanged
:class:`~predictionio_tpu.utils.http.Router` on a thread pool (concurrent
dispatch is what lets the micro-batcher keep coalescing), and writes
responses back to each worker's completion ring.

Port discovery without a blackhole window: the bridge binds ONE
``SO_REUSEPORT`` socket on the requested port (port 0 resolves to a real
ephemeral port) and keeps it bound but **never listening** -- a TCP
socket that has not called ``listen()`` is not in the kernel's
``SO_REUSEPORT`` delivery group, so it reserves the port for respawns
without stealing SYNs from the workers.

Supervision: a SIGKILLed worker is respawned with a fresh ring file under
a bumped generation; completions addressed to the dead generation are
dropped (its clients are gone with its sockets), and everything else
keeps serving. Backpressure: the bridge admits at most ``max_inflight``
requests into the dispatch pool; beyond that it simply stops popping, the
rings fill, and the frontends answer 429 -- the ingest pipeline's bounded
-queue contract at the serving tier.
"""

from __future__ import annotations

import logging
import os
import queue
import select
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass
from urllib.parse import parse_qs, urlsplit

from predictionio_tpu.serving import shmring
from predictionio_tpu.utils.http import Request

logger = logging.getLogger("pio.procserver")


@dataclass
class FrontendConfig:
    """Process-tier knobs (CLI: ``pio deploy --frontend-workers N``)."""

    workers: int = 2
    #: per-direction ring capacity (messages); the backpressure horizon
    ring_slots: int = 128
    #: per-slot byte budget; bigger messages spill to one-off files
    slot_bytes: int = 32768
    #: concurrent dispatches admitted into the scorer (= dispatcher
    #: threads; also the coalescing ceiling the micro-batcher sees).
    #: Deliberately small: a wide pool looks tempting, but measured on
    #: the 2-core box 64 dispatcher threads collapsed throughput 13x --
    #: every batch completion woke a thread herd that thrashed the GIL
    #: and scheduler -- while 8-16 threads kept the scorer at full rate
    max_inflight: int = 16
    #: how often a worker publishes its metrics snapshot
    stats_flush_s: float = 0.25
    #: how long to wait for a spawned worker to reach READY
    spawn_timeout_s: float = 40.0

    def describe(self) -> dict:
        return {
            "workers": self.workers,
            "ringSlots": self.ring_slots,
            "slotBytes": self.slot_bytes,
            "maxInflight": self.max_inflight,
        }


class _Worker:
    """One spawned frontend: its ring, process handle, and generation."""

    def __init__(self, index: int, generation: int, ring: shmring.RingFile,
                 proc: subprocess.Popen):
        self.index = index
        self.generation = generation
        self.ring = ring
        self.proc = proc
        self.dead = False
        #: serializes pool threads producing into the SPSC completion ring
        self.cmp_lock = threading.Lock()


class ScorerBridge:
    """Spawn/supervise frontends; pump rings through the router."""

    def __init__(
        self,
        router,
        host: str,
        port: int,
        config: FrontendConfig | None = None,
        server_name: str = "pio-queryserver",
        registry=None,
    ):
        self._router = router
        self._host = host
        self._requested_port = port
        self.config = config or FrontendConfig()
        if self.config.workers < 1:
            raise ValueError("frontend workers must be >= 1")
        self._server_name = server_name
        self._registry = registry
        self._reserve: socket.socket | None = None
        self.port: int | None = None
        self._dir: str | None = None
        #: index -> (req, cmp, stop) wakeups; created once, reused across
        #: respawns so the consumer's select set never churns
        self._wakes: dict[int, tuple] = {}
        self._workers: list[_Worker] = []
        self._lock = threading.Lock()
        self._inflight = threading.Semaphore(self.config.max_inflight)
        self._draining = False
        self._stopping = False
        #: consumer -> dispatcher hand-off; SimpleQueue's C put/get is the
        #: cheapest in-process wakeup available (no Future allocation)
        self._work: queue.SimpleQueue = queue.SimpleQueue()
        self._dispatchers: list[threading.Thread] = []
        self._consumer: threading.Thread | None = None
        self._supervisor: threading.Thread | None = None
        self._respawns = 0
        #: serializes stop() callers end-to-end (idempotent teardown)
        self._stop_lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ScorerBridge":
        if not hasattr(socket, "SO_REUSEPORT"):
            raise RuntimeError(
                "multi-process serving needs SO_REUSEPORT (Linux/BSD); "
                "deploy without --frontend-workers on this platform"
            )
        try:
            self._reserve = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._reserve.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            self._reserve.bind((self._host, self._requested_port))
            self.port = self._reserve.getsockname()[1]
            self._dir = tempfile.mkdtemp(prefix="pio-frontend-")
            for k in range(self.config.max_inflight):
                t = threading.Thread(
                    target=self._dispatch_loop, name=f"pio-scorer-{k}",
                    daemon=True,
                )
                t.start()
                self._dispatchers.append(t)
            for i in range(self.config.workers):
                self._wakes[i] = (
                    shmring.Wakeup.create(self._dir, f"req-{i}"),
                    shmring.Wakeup.create(self._dir, f"cmp-{i}"),
                    shmring.Wakeup.create(self._dir, f"stop-{i}"),
                )
                self._workers.append(self._launch(i, generation=1))
            self._await_ready(self._workers)
        except BaseException:
            # a half-started tier must not outlive this call: workers
            # that already reached READY are listening on the port with
            # no consumer behind them -- clients would hang, and the
            # orphans would hold the port after the parent dies
            self._teardown(kill=True)
            raise
        self._consumer = threading.Thread(
            target=self._consume, name="pio-scorer-consumer", daemon=True
        )
        self._consumer.start()
        self._supervisor = threading.Thread(
            target=self._supervise, name="pio-scorer-supervisor", daemon=True
        )
        self._supervisor.start()
        self._gauge_workers()
        return self

    def _launch(self, index: int, generation: int) -> _Worker:
        path = os.path.join(self._dir, f"worker-{index}.ring")
        ring = shmring.RingFile.create(
            path, self.config.ring_slots, self.config.slot_bytes, generation
        )
        wake_req, wake_cmp, wake_stop = self._wakes[index]
        cmd = [
            sys.executable, "-m", "predictionio_tpu.serving.frontend",
            "--ring", path,
            "--host", self._host,
            "--port", str(self.port),
            "--worker", str(index),
            "--wake-req", wake_req.spec(),
            "--wake-cmp", wake_cmp.spec(),
            "--wake-stop", wake_stop.spec(),
            "--server-name", self._server_name,
            "--stats-flush-s", str(self.config.stats_flush_s),
        ]
        env = dict(os.environ)
        # the worker interpreter must find this package without an install
        pkg_parent = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        pass_fds = tuple(
            fd for w in (wake_req, wake_cmp, wake_stop)
            if (fd := w.pass_fd) is not None
        )
        log = open(os.path.join(self._dir, f"worker-{index}.log"), "ab")
        try:
            proc = subprocess.Popen(
                cmd, pass_fds=pass_fds, env=env,
                stdout=log, stderr=subprocess.STDOUT,
            )
        finally:
            log.close()
        logger.info(
            "frontend worker %d spawned (pid %d, generation %d)",
            index, proc.pid, generation,
        )
        return _Worker(index, generation, ring, proc)

    def _await_ready(self, workers: list[_Worker]) -> None:
        deadline = time.monotonic() + self.config.spawn_timeout_s
        pending = list(workers)
        while pending:
            pending = [
                w for w in pending if w.ring.state == shmring.STATE_INIT
            ]
            if not pending:
                return
            for w in pending:
                if w.proc.poll() is not None:
                    raise RuntimeError(
                        f"frontend worker {w.index} exited "
                        f"rc={w.proc.returncode} before READY "
                        f"(log: {self._worker_log_tail(w.index)!r})"
                    )
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"frontend worker(s) "
                    f"{[w.index for w in pending]} not READY within "
                    f"{self.config.spawn_timeout_s}s"
                )
            time.sleep(0.02)

    def _worker_log_tail(self, index: int, limit: int = 500) -> str:
        try:
            with open(os.path.join(self._dir, f"worker-{index}.log"), "rb") as f:
                return f.read()[-limit:].decode("utf-8", "replace")
        except OSError:
            return ""

    def stop(self) -> None:
        """Graceful drain: workers stop accepting and finish in-flight
        requests (the bridge keeps dispatching while they do), then the
        pool drains and everything is torn down. Idempotent; concurrent
        callers serialize and the second is a no-op."""
        with self._stop_lock:
            self._stop_locked()

    def _stop_locked(self) -> None:
        with self._lock:
            if self._stopping:
                return
            self._draining = True
        for _, _, wake_stop in self._wakes.values():
            wake_stop.signal()
        # a draining worker legitimately waits up to the frontend's
        # forward timeout for an in-flight answer (first-bucket jit
        # compiles are the sized-for case); killing it sooner would drop
        # exactly the requests the drain contract promises to answer
        from predictionio_tpu.serving.frontend import FORWARD_TIMEOUT_S

        deadline = time.monotonic() + FORWARD_TIMEOUT_S + 5.0
        for w in list(self._workers):
            timeout = max(deadline - time.monotonic(), 0.1)
            try:
                w.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                logger.warning(
                    "frontend worker %d did not drain; killing", w.index
                )
                w.proc.kill()
                try:
                    w.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
        with self._lock:
            self._stopping = True
        if self._consumer is not None:
            self._consumer.join(timeout=5.0)
        if self._supervisor is not None:
            self._supervisor.join(timeout=5.0)
        self._teardown()

    def _teardown(self, kill: bool = False) -> None:
        """Release every tier resource; with ``kill`` the workers are
        SIGKILLed first (the start()-failed path, where a graceful drain
        has nothing to drain and orphans must not survive)."""
        with self._lock:
            self._draining = True
            self._stopping = True
        for w in self._workers:
            if kill and w.proc.poll() is None:
                w.proc.kill()
        for w in self._workers:
            try:
                w.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
        # sentinels queue BEHIND any in-flight work: dispatchers finish
        # the stragglers, then exit
        for _ in self._dispatchers:
            self._work.put(None)
        for t in self._dispatchers:
            t.join(timeout=10.0)
        for w in self._workers:
            w.ring.close()
        for wakes in self._wakes.values():
            for wake in wakes:
                wake.close()
        if self._reserve is not None:
            self._reserve.close()
            self._reserve = None
        if self._dir is not None:
            shutil.rmtree(self._dir, ignore_errors=True)

    # -- request pump -------------------------------------------------------
    def _consume(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
                workers = list(self._workers)
            progressed = False
            for w in workers:
                if w.dead:
                    continue
                try:
                    while w.ring.requests.pending():
                        # admission control: no permit -> stop popping;
                        # the ring backs up and the frontend answers 429
                        if not self._inflight.acquire(timeout=0.5):
                            with self._lock:
                                if self._stopping:
                                    return
                            break
                        msg = w.ring.requests.pop()
                        if msg is None:
                            self._inflight.release()
                            break
                        progressed = True
                        self._work.put((w, msg))
                except (ValueError, OSError):
                    # the supervisor retired this worker and closed its
                    # ring between our dead-check and the read; the ONLY
                    # popping thread must survive the race, not die on it
                    if not w.dead:
                        logger.exception(
                            "request ring read failed for live worker %d",
                            w.index,
                        )
                    continue
            if progressed:
                continue
            fds = [wakes[0].fileno() for wakes in self._wakes.values()]
            try:
                ready, _, _ = select.select(fds, [], [], 0.25)
            except OSError:
                ready = []
            for wakes in self._wakes.values():
                if wakes[0].fileno() in ready:
                    wakes[0].drain()

    def _dispatch_loop(self) -> None:
        while True:
            item = self._work.get()
            if item is None:
                return
            self._handle(*item)

    def _handle(self, w: _Worker, msg: tuple) -> None:
        try:
            meta, body = msg
            parsed = urlsplit(meta["t"])
            query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            request = Request(
                method=meta["m"],
                path=parsed.path,
                query=query,
                headers=dict(meta.get("h") or {}),
                body=body,
                path_params={},
                frontend_pc=(
                    meta["p"], time.perf_counter(), meta.get("w", "?")
                ),
            )
            try:
                response = self._router.dispatch(request)
            except Exception:
                # the router has its own backstops; anything escaping is a
                # dispatch-layer bug, answered like make_server would
                logger.exception("dispatch failed for %s", parsed.path)
                from predictionio_tpu.utils.http import Response

                response = Response(500, {"message": "internal server error"})
            payload = response.payload()
            rmeta = {
                "i": meta["i"],
                "s": response.status,
                "c": response.content_type,
                "h": response.headers,
            }
            # a briefly-descheduled worker (measured: ~300 ms scheduler
            # stalls under load on sandboxed kernels) can leave its
            # completion ring momentarily full; DROPPING here turns that
            # stall into a full client timeout, so retry with a bounded
            # deadline instead -- the worker only has to run once within
            # it to drain 128 slots
            deadline = time.monotonic() + 5.0
            while True:
                with w.cmp_lock:
                    if w.dead:
                        # a respawn retired this worker mid-score: its
                        # clients died with its sockets, drop the answer
                        break
                    try:
                        w.ring.completions.push(rmeta, payload)
                        break
                    except shmring.RingFull:
                        pass
                self._wakes[w.index][1].signal()
                if time.monotonic() > deadline:
                    logger.warning(
                        "completion ring full for worker %d for >5s; "
                        "dropping response", w.index,
                    )
                    break
                time.sleep(0.002)
            self._wakes[w.index][1].signal()
        except Exception:
            logger.exception("completion delivery failed")
        finally:
            self._inflight.release()

    # -- supervision --------------------------------------------------------
    #: consecutive failed respawns of one worker index before giving up
    #: (the index stays down; serving continues on surviving workers)
    _MAX_RESPAWN_FAILURES = 6

    def _supervise(self) -> None:
        #: index -> (consecutive failures, next attempt monotonic time)
        backoff: dict[int, tuple[int, float]] = {}
        while True:
            time.sleep(0.2)
            with self._lock:
                if self._stopping or self._draining:
                    return
                workers = list(self._workers)
            for w in workers:
                if w.proc.poll() is None or w.dead:
                    continue
                logger.warning(
                    "frontend worker %d died (rc=%s); respawning",
                    w.index, w.proc.returncode,
                )
                w.dead = True
                backoff.setdefault(w.index, (0, time.monotonic()))
            for index in sorted(backoff):
                failures, next_try = backoff[index]
                if time.monotonic() < next_try:
                    continue
                old = self._workers[index]
                replacement = self._launch(index, old.generation + 1)
                try:
                    self._await_ready([replacement])
                except RuntimeError:
                    # a replacement that never reached READY must NOT be
                    # installed (the next sweep would respawn it at 5/s
                    # forever); back off exponentially, then give up loud
                    logger.exception(
                        "respawned frontend worker %d failed to start "
                        "(attempt %d)", index, failures + 1,
                    )
                    replacement.proc.kill()
                    replacement.ring.close()
                    failures += 1
                    if failures >= self._MAX_RESPAWN_FAILURES:
                        logger.error(
                            "giving up on frontend worker %d after %d "
                            "failed respawns; serving continues on the "
                            "remaining workers", index, failures,
                        )
                        del backoff[index]
                    else:
                        backoff[index] = (
                            failures,
                            time.monotonic() + min(0.5 * 2 ** failures, 30.0),
                        )
                    continue
                with self._lock:
                    if self._draining or self._stopping:
                        replacement.proc.kill()
                        return
                    self._workers[index] = replacement
                    self._respawns += 1
                del backoff[index]
                with old.cmp_lock:
                    # dead=True is already visible: in-flight completions
                    # skip the push, so nobody holds the mapping we close
                    old.ring.close()
                self._gauge_workers()

    def _gauge_workers(self) -> None:
        if self._registry is None:
            return
        self._registry.set_gauge(
            "pio_frontend_workers", float(self.config.workers),
            help="Configured frontend worker processes",
        )
        self._registry.set_counter(
            "pio_frontend_respawns_total", float(self._respawns),
            help="Frontend workers respawned after unexpected exit",
        )

    # -- metrics aggregation ------------------------------------------------
    def metric_snapshots(self) -> list[dict]:
        """Every live worker's published registry snapshot (the
        ``extra_snapshots`` hook of ``instrumented_router``)."""
        with self._lock:
            workers = list(self._workers)
        out = []
        for w in workers:
            if w.dead:
                continue
            try:
                snap = w.ring.read_stats()
            except (ValueError, OSError):
                continue  # retired ring closed mid-scrape
            if snap:
                out.append(snap)
        return out
