"""Shard routing for the sharded serving fabric.

One function decides which scorer shard owns a user, and every tier --
the frontend's ring picker, the shard process's model filter, and the
continuous-learning loop's touched-shard delta routing -- imports it
from here, so the partition can never skew between the process that
routes a query and the process that holds the factors.

Import-light on purpose: the frontend worker (serving/frontend.py) is a
no-jax, no-numpy interpreter, so only stdlib may be imported here.

``zlib.crc32`` rather than ``hash()``: Python string hashing is salted
per interpreter (PYTHONHASHSEED), and the router and the shards are
*different* interpreters -- a salted hash would route user u to shard 1
while shard 2 holds u's factors. CRC32 is stable across processes,
platforms, and releases, which also makes the registry's per-shard
blobs portable between a publisher and any later deploy.
"""

from __future__ import annotations

import json
import zlib

__all__ = ["shard_of", "extract_user"]


def shard_of(user_id: str, num_shards: int) -> int:
    """The shard that owns ``user_id``'s factor rows (0-based)."""
    if num_shards <= 1:
        return 0
    return zlib.crc32(str(user_id).encode("utf-8")) % num_shards


def extract_user(body: bytes) -> str | None:
    """The ``"user"`` field of a query body, or None.

    The frontend calls this before picking a ring; a malformed body or a
    userless query returns None and the caller falls back to any shard
    (item-side state is replicated, so every shard answers userless
    queries identically). Scalars are stringified exactly like the
    scorer's own ``str(query.get("user"))`` lookups, so router and
    model agree on the key.
    """
    try:
        obj = json.loads(body)
    except Exception:
        return None
    if not isinstance(obj, dict):
        return None
    user = obj.get("user")
    if user is None or isinstance(user, (dict, list, bool)):
        return None
    return str(user)
