"""Shard routing for the sharded serving fabric.

One function decides which scorer shard owns a user, and every tier --
the frontend's ring picker, the shard process's model filter, and the
continuous-learning loop's touched-shard delta routing -- imports it
from here, so the partition can never skew between the process that
routes a query and the process that holds the factors.

Import-light on purpose: the frontend worker (serving/frontend.py) is a
no-jax, no-numpy interpreter, so only stdlib may be imported here.

The hash itself lives in ``utils/stablehash`` -- the ingest pipeline's
WAL-partition router buckets entities with the SAME function, so the
partition an event is durably ordered in always matches the shard that
serves the entity. See that module for the crc32-over-``hash()``
rationale (per-interpreter hash salting).
"""

from __future__ import annotations

import json

from predictionio_tpu.utils.stablehash import stable_bucket

__all__ = ["shard_of", "extract_user"]


def shard_of(user_id: str, num_shards: int) -> int:
    """The shard that owns ``user_id``'s factor rows (0-based)."""
    return stable_bucket(user_id, num_shards)


def extract_user(body: bytes) -> str | None:
    """The ``"user"`` field of a query body, or None.

    The frontend calls this before picking a ring; a malformed body or a
    userless query returns None and the caller falls back to any shard
    (item-side state is replicated, so every shard answers userless
    queries identically). Scalars are stringified exactly like the
    scorer's own ``str(query.get("user"))`` lookups, so router and
    model agree on the key.
    """
    try:
        obj = json.loads(body)
    except Exception:
        return None
    if not isinstance(obj, dict):
        return None
    user = obj.get("user")
    if user is None or isinstance(user, (dict, list, bool)):
        return None
    return str(user)
