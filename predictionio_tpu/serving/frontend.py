"""HTTP frontend worker: one ``SO_REUSEPORT`` accept/parse/validate loop.

Runs as its own process (``python -m predictionio_tpu.serving.frontend``),
spawned and supervised by the scorer's
:class:`~predictionio_tpu.serving.procserver.ScorerBridge`. The worker
binds its OWN listening socket with ``SO_REUSEPORT`` on the shared port
(the kernel load-balances new connections across workers) and runs a
SINGLE-THREADED non-blocking event loop: accept, parse (incremental
``utils.http.RequestParser`` -- one buffer per connection, byte-exact
Content-Length, correct keep-alive/close handling), validate, forward
through the shared-memory ring to the scorer, and write completed
responses back -- in per-connection order, so HTTP/1.1 pipelining can
never interleave answers.

One thread is a deliberate choice, not a simplification: a
thread-per-connection frontend pays two extra in-process wakeups per
request (request thread -> completion thread -> request thread), and on a
small box every wakeup is a scheduler hop that under load costs
milliseconds, not microseconds. Here the completion ring's wakeup fd sits
in the SAME epoll as the sockets, so one ``select`` wake services
everything the worker has to do.

The worker is deliberately dumb: no routing, no JSON, no engine, no jax
-- importing this module must stay light so a SIGKILLed worker's
replacement is accepting again in well under a second. Everything that
can change a response body lives in the scorer, which is what keeps
multi-process responses byte-identical to the single-process server.

Backpressure: a full request ring (the scorer is a whole ring behind)
answers ``429`` with ``Retry-After`` -- the same contract the ingest
pipeline's bounded queue presents (``docs/operations.md``).

Per-worker metrics land in a private ``MetricsRegistry`` published
through the ring's seqlock'd stats region (flushed at most every
``stats_flush_s`` under traffic, synchronously when this worker forwards
a ``/metrics`` scrape, and once at drain); the scorer merges every
worker's snapshot into the deployed server's aggregated ``/metrics``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import selectors
import socket
import time
from collections import deque

from predictionio_tpu.serving import shardmap, shmring
from predictionio_tpu.utils.http import (
    HTTPParseError,
    RequestParser,
    build_http_response,
)
from predictionio_tpu.utils.metrics import MetricsRegistry

logger = logging.getLogger("pio.frontend")

#: idle keep-alive connections are reaped after this
KEEPALIVE_TIMEOUT_S = 65.0
#: how long a forwarded request may wait for the scorer before the worker
#: answers 503 on its behalf (covers first-bucket jit compiles, same
#: allowance as the single-process batched path)
FORWARD_TIMEOUT_S = 35.0

#: histogram buckets for the ring round-trip (sub-ms through jit compiles)
_FORWARD_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0, 5.0,
    30.0,
)


def reuseport_listener(host: str, port: int, backlog: int = 128) -> socket.socket:
    """A listening socket in the port's ``SO_REUSEPORT`` group."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        sock.bind((host, port))
        sock.listen(backlog)
        sock.setblocking(False)
    except BaseException:
        # a bind/listen failure (port stolen between reserve and spawn)
        # must not leak the descriptor into the worker's retry loop
        sock.close()
        raise
    return sock


class _Conn:
    """Per-connection state: parser buffer, ordered in-flight requests,
    pending output."""

    __slots__ = (
        "sock", "parser", "out", "order", "ready", "close_after",
        "last_pc", "want_write", "dead", "discard_input",
    )

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.parser = RequestParser()
        self.out = bytearray()
        #: request ids in arrival order -- responses flush in THIS order
        self.order: deque[int] = deque()
        #: request id -> serialized response waiting for its turn
        self.ready: dict[int, bytes] = {}
        self.close_after = False
        self.last_pc = time.perf_counter()
        self.want_write = False
        self.dead = False
        #: set after a parse error: the stream is desynced, so further
        #: bytes are drained and dropped while queued responses flush
        self.discard_input = False


class FrontendWorker:
    """The single-threaded per-process serving loop around one ring (or,
    under the sharded fabric, one ring PER scorer shard plus a control
    ring). With multiple rings the worker routes each ``POST
    /queries.json`` frame by the query's user id --
    ``shardmap.shard_of(user) % num_shards`` picks the owning shard's
    ring -- while every non-query frame (and stats publication) rides the
    LAST ring, which the fabric supervisor consumes. One ring is exactly
    the pre-shard tier: all traffic on ring 0."""

    def __init__(
        self,
        rings: "shmring.RingFile | list[shmring.RingFile]",
        listener: socket.socket,
        wake_reqs: "shmring.Wakeup | list[shmring.Wakeup]",
        wake_cmp: shmring.Wakeup,
        wake_stop: shmring.Wakeup,
        index: int,
        server_name: str = "pio-queryserver",
        stats_flush_s: float = 0.25,
        rid_base: int = 0,
    ):
        self.rings = (
            list(rings) if isinstance(rings, (list, tuple)) else [rings]
        )
        self._wake_reqs = (
            list(wake_reqs)
            if isinstance(wake_reqs, (list, tuple)) else [wake_reqs]
        )
        if len(self._wake_reqs) != len(self.rings):
            raise ValueError(
                f"{len(self.rings)} ring(s) need {len(self.rings)} request"
                f" wakeup(s), got {len(self._wake_reqs)}"
            )
        #: query rings = every ring but the control ring; with one ring
        #: the single ring plays both roles (the unsharded tier)
        self._num_shards = max(1, len(self.rings) - 1)
        self._listener = listener
        self._wake_cmp = wake_cmp
        self._wake_stop = wake_stop
        self.index = index
        self._label = str(index)
        self._server_name = server_name
        self._stats_flush_s = stats_flush_s
        self.registry = MetricsRegistry()
        self._sel = selectors.DefaultSelector()
        #: rid_base keeps request ids DISJOINT across respawn generations:
        #: the fabric reuses ring files over a respawn, so a completion
        #: addressed to the dead generation must never alias a live rid
        self._next_id = rid_base + 1
        #: request id -> (conn, recv_pc, deadline_pc, keep_alive)
        self._pending: dict[int, tuple] = {}
        self._draining = False
        self._stats_last = 0.0
        self._stats_dirty = False

    # -- main loop ----------------------------------------------------------
    def serve(self) -> None:
        self._sel.register(self._listener, selectors.EVENT_READ, "accept")
        self._sel.register(
            self._wake_cmp.fileno(), selectors.EVENT_READ, "completions"
        )
        self._sel.register(
            self._wake_stop.fileno(), selectors.EVENT_READ, "stop"
        )
        for ring in self.rings:
            ring.set_state(shmring.STATE_READY)
        next_sweep = time.perf_counter() + 1.0
        while True:
            for key, _mask in self._sel.select(timeout=0.5):
                data = key.data
                if data == "accept":
                    self._accept()
                elif data == "completions":
                    self._wake_cmp.drain()
                    self._pump_completions()
                elif data == "stop":
                    self._wake_stop.drain()
                    self._begin_drain()
                elif isinstance(data, _Conn):
                    self._service_conn(data)
            # opportunistic: completions that landed while we serviced
            # sockets get written without waiting for the next epoll wake
            self._pump_completions()
            now = time.perf_counter()
            if now >= next_sweep:
                next_sweep = now + 1.0
                self._sweep_timeouts(now)
            self._maybe_flush_stats()
            if self._draining and not self._pending and not any(
                isinstance(k.data, _Conn) and k.data.out
                for k in list(self._sel.get_map().values())
            ):
                break
        self._flush_stats(force=True)
        for ring in self.rings:
            ring.set_state(shmring.STATE_DONE)

    def _begin_drain(self) -> None:
        if self._draining:
            return
        self._draining = True
        for ring in self.rings:
            ring.set_state(shmring.STATE_DRAINING)
        try:
            self._sel.unregister(self._listener)
        except KeyError:
            pass
        self._listener.close()
        # connections with nothing in flight close now; in-flight ones
        # close right after their last response flushes
        for key in list(self._sel.get_map().values()):
            conn = key.data
            if not isinstance(conn, _Conn):
                continue
            conn.close_after = True
            if not conn.order and not conn.out:
                self._close_conn(conn)

    # -- socket events ------------------------------------------------------
    def _accept(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock)
            self._sel.register(sock, selectors.EVENT_READ, conn)
            self._count("pio_frontend_connections_total")

    def _service_conn(self, conn: _Conn) -> None:
        if conn.dead:
            return
        if conn.want_write:
            self._flush_out(conn)
        try:
            data = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            # peer closed its write side; anything still in flight is
            # answered into the void, so just drop the connection unless
            # responses are mid-flush
            if not conn.order and not conn.out:
                self._close_conn(conn)
            else:
                conn.close_after = True
            return
        if conn.discard_input:
            return  # stream already desynced by a parse error; drop
        conn.last_pc = time.perf_counter()
        conn.parser.feed(data)
        while True:
            try:
                parsed = conn.parser.next_request()
            except HTTPParseError as exc:
                self._count(
                    "pio_frontend_http_errors_total",
                    {"kind": str(exc.status)},
                )
                # the buffer is mid-garbage: one error response for the
                # one bad request, then never parse this stream again (a
                # re-parse per arriving segment would enqueue duplicate
                # errors behind any still-pending pipelined answers)
                conn.discard_input = True
                try:
                    conn.sock.shutdown(socket.SHUT_RD)
                except OSError:
                    pass
                self._enqueue_local(
                    conn, exc.status, {"message": exc.message}, close=True
                )
                return
            if parsed is None:
                return
            self._handle_request(conn, parsed)
            if conn.dead or conn.close_after:
                return

    def _handle_request(self, conn: _Conn, parsed) -> None:
        if not parsed.keep_alive or self._draining:
            conn.close_after = True
        if parsed.method == "OPTIONS":
            # CORS preflight: answered at the edge, exactly as the
            # single-process server bypasses its router
            self._enqueue_local(conn, 200)
            return
        recv_pc = time.perf_counter()
        rid = self._alloc_id()
        path = parsed.target.split("?", 1)[0]
        if path == "/metrics":
            # the scrape that is about to aggregate worker snapshots must
            # see THIS worker's counters current up to this very request
            self._flush_stats(force=True)
        ring_idx = self._route_ring(parsed, path, rid)
        meta = {
            "i": rid,
            "m": parsed.method,
            "t": parsed.target,
            "h": parsed.headers,
            "p": recv_pc,
            "w": self._label,
        }
        try:
            self.rings[ring_idx].requests.push(meta, parsed.body)
        except shmring.RingFull:
            self._count("pio_frontend_ring_full_total")
            # backpressure parity with the ingest pipeline's bounded
            # queue: 429 + Retry-After, body shape identical
            conn.order.append(rid)
            self._enqueue_local(
                conn, 429, {"message": "serving queue full, retry later"},
                headers={"Retry-After": "1"}, rid=rid, count_status=True,
            )
            return
        conn.order.append(rid)
        self._pending[rid] = (
            conn, recv_pc, recv_pc + FORWARD_TIMEOUT_S,
            not conn.close_after,
        )
        self._wake_reqs[ring_idx].signal()

    def _route_ring(self, parsed, path: str, rid: int) -> int:
        """Pick the destination ring for one parsed request. Single-ring
        deploys (the pre-shard tier) send everything to ring 0. Under the
        sharded fabric, a query routes to its user's owning shard
        (``shardmap.shard_of``); a query with no extractable user is
        spread ``rid % num_shards`` (every shard answers user-less
        queries identically: the item-side state is replicated);
        everything else -- control routes, scrapes -- rides the LAST
        ring to the fabric supervisor."""
        if len(self.rings) == 1:
            return 0
        if parsed.method == "POST" and path == "/queries.json":
            user = shardmap.extract_user(parsed.body)
            if user is None:
                return rid % self._num_shards
            return shardmap.shard_of(user, self._num_shards)
        return len(self.rings) - 1

    def _enqueue_local(
        self,
        conn: _Conn,
        status: int,
        body: dict | None = None,
        headers: dict | None = None,
        close: bool = False,
        rid: int | None = None,
        count_status: bool = False,
    ) -> None:
        """Answer a request from the frontend itself (CORS preflight,
        ring-full 429, parse errors, scorer-timeout 503): one shared
        path allocates the slot (or reuses an already-ordered ``rid``),
        serializes, and flushes in connection order."""
        if rid is None:
            rid = self._alloc_id()
            conn.order.append(rid)
        if close:
            conn.close_after = True
        conn.ready[rid] = build_http_response(
            status,
            b"" if body is None else json.dumps(body).encode("utf-8"),
            headers=headers,
            server_name=self._server_name,
            keep_alive=not conn.close_after,
        )
        if count_status:
            self._count(
                "pio_frontend_requests_total",
                {"status": f"{status // 100}xx"},
            )
        self._flush_ready(conn)

    def _alloc_id(self) -> int:
        rid = self._next_id
        self._next_id += 1
        return rid

    # -- completion side ----------------------------------------------------
    def _pump_completions(self) -> None:
        for ring in self.rings:
            self._pump_ring(ring)

    def _pump_ring(self, ring: shmring.RingFile) -> None:
        while True:
            msg = ring.completions.pop()
            if msg is None:
                return
            meta, body = msg
            entry = self._pending.pop(meta["i"], None)
            if entry is None:
                continue  # already timed out locally
            conn, recv_pc, _deadline, keep = entry
            status = meta["s"]
            self.registry.observe(
                "pio_frontend_dispatch_seconds",
                time.perf_counter() - recv_pc,
                {"worker": self._label},
                buckets=_FORWARD_BUCKETS,
                help="Ring round-trip: request forwarded until response ready",
            )
            self._count(
                "pio_frontend_requests_total",
                {"status": f"{status // 100}xx"},
            )
            if conn.dead:
                continue
            conn.ready[meta["i"]] = build_http_response(
                status, body,
                content_type=meta.get("c") or "application/json",
                headers=meta.get("h") or {},
                server_name=self._server_name,
                keep_alive=keep and not conn.close_after,
            )
            self._flush_ready(conn)

    def _flush_ready(self, conn: _Conn) -> None:
        """Move completed responses into the output buffer IN ARRIVAL
        ORDER (a pipelined request that finished early waits for its
        predecessors), then write as much as the socket accepts."""
        while conn.order and conn.order[0] in conn.ready:
            conn.out += conn.ready.pop(conn.order.popleft())
        self._flush_out(conn)

    def _flush_out(self, conn: _Conn) -> None:
        if conn.dead:
            return
        while conn.out:
            try:
                sent = conn.sock.send(conn.out)
            except (BlockingIOError, InterruptedError):
                if not conn.want_write:
                    conn.want_write = True
                    self._sel.modify(
                        conn.sock,
                        selectors.EVENT_READ | selectors.EVENT_WRITE,
                        conn,
                    )
                return
            except OSError:
                self._close_conn(conn)
                return
            del conn.out[:sent]
        if conn.want_write:
            conn.want_write = False
            try:
                self._sel.modify(conn.sock, selectors.EVENT_READ, conn)
            except KeyError:
                pass
        if conn.close_after and not conn.order:
            self._close_conn(conn)

    def _close_conn(self, conn: _Conn) -> None:
        if conn.dead:
            return
        conn.dead = True
        # in-flight scorer answers for this connection go nowhere now
        for rid in conn.order:
            self._pending.pop(rid, None)
        conn.order.clear()
        conn.ready.clear()
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- periodic sweeps ----------------------------------------------------
    def _sweep_timeouts(self, now: float) -> None:
        for rid, (conn, _recv, deadline, keep) in list(self._pending.items()):
            if now < deadline:
                continue
            self._pending.pop(rid, None)
            self._count("pio_frontend_scorer_timeouts_total")
            if conn.dead:
                continue
            self._enqueue_local(
                conn, 503, {"message": "scorer timed out"},
                close=True, rid=rid, count_status=True,
            )
        if self._draining:
            return
        for key in list(self._sel.get_map().values()):
            conn = key.data
            if not isinstance(conn, _Conn) or conn.dead:
                continue
            if not conn.order and now - conn.last_pc > KEEPALIVE_TIMEOUT_S:
                self._close_conn(conn)

    # -- metrics publication ------------------------------------------------
    def _count(self, name: str, labels: dict | None = None) -> None:
        all_labels = {"worker": self._label}
        if labels:
            all_labels.update(labels)
        self.registry.inc(name, all_labels, help=_HELP.get(name, ""))
        self._stats_dirty = True

    def _maybe_flush_stats(self) -> None:
        if not self._stats_dirty:
            return
        if time.monotonic() - self._stats_last < self._stats_flush_s:
            return
        self._flush_stats()

    def _flush_stats(self, force: bool = False) -> None:
        if not (self._stats_dirty or force):
            return
        self._stats_dirty = False
        self._stats_last = time.monotonic()
        # the control ring under the fabric (rings[-1] IS ring 0 on a
        # single-ring deploy): whoever supervises reads snapshots there
        self.rings[-1].write_stats(self.registry.snapshot())


_HELP = {
    "pio_frontend_connections_total": "TCP connections accepted by frontend workers",
    "pio_frontend_requests_total": "Requests forwarded through the ring, by status class",
    "pio_frontend_http_errors_total": "Requests answered at the frontend for protocol errors",
    "pio_frontend_ring_full_total": "Requests 429'd because the request ring was full",
    "pio_frontend_scorer_timeouts_total": "Requests 503'd because the scorer never answered",
}


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--ring", required=True, action="append",
        help="ring file path; repeat under the sharded fabric (one per"
        " scorer shard, control ring LAST)",
    )
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--worker", type=int, required=True)
    ap.add_argument(
        "--wake-req", required=True, action="append",
        help="request wakeup spec, one per --ring in the same order",
    )
    ap.add_argument("--wake-cmp", required=True)
    ap.add_argument("--wake-stop", required=True)
    ap.add_argument(
        "--rid-base", type=int, default=0,
        help="request-id offset (the fabric passes generation<<33 so"
        " respawns over reused rings never alias in-flight ids)",
    )
    ap.add_argument("--server-name", default="pio-queryserver")
    ap.add_argument("--stats-flush-s", type=float, default=0.25)
    ap.add_argument(
        "--pin-cpu", type=int, default=-1, metavar="CORE",
        help="sched_setaffinity this worker to one core (-1 = unpinned);"
        " set by the scorer bridge under pio deploy --pin-cpus",
    )
    args = ap.parse_args(argv)

    if args.pin_cpu >= 0 and hasattr(os, "sched_setaffinity"):
        try:
            os.sched_setaffinity(0, {args.pin_cpu})
        except OSError:
            logger.warning(
                "could not pin frontend worker %d to cpu %d",
                args.worker, args.pin_cpu,
            )
    rings = [shmring.RingFile.attach(path) for path in args.ring]
    listener = reuseport_listener(args.host, args.port)
    worker = FrontendWorker(
        rings,
        listener,
        [shmring.Wakeup.from_spec(spec) for spec in args.wake_req],
        shmring.Wakeup.from_spec(args.wake_cmp),
        shmring.Wakeup.from_spec(args.wake_stop),
        index=args.worker,
        server_name=args.server_name,
        stats_flush_s=args.stats_flush_s,
        rid_base=args.rid_base,
    )
    worker.serve()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
