"""JAX backend resolution shared by the train and serve entry points.

Accelerator plugins (e.g. a tunneled TPU) can be registered but broken; a
server or CLI must degrade to the host backend instead of dying. Honors
``PIO_PLATFORM`` (env) as an explicit override.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("pio.platform")


def ensure_backend(platform: str | None = None) -> str:
    """Make sure SOME JAX backend initializes; returns its platform name.

    Resolution order: explicit ``platform`` arg > ``PIO_PLATFORM`` env >
    JAX default, falling back to CPU when the preferred backend fails.
    """
    import jax

    want = platform or os.environ.get("PIO_PLATFORM")
    if want:
        jax.config.update("jax_platforms", want)
    try:
        return jax.devices()[0].platform
    except RuntimeError as exc:
        logger.warning("accelerator backend unavailable (%s); using CPU", exc)
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0].platform
