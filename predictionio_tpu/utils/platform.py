"""JAX backend resolution shared by the train and serve entry points.

Accelerator plugins (e.g. a tunneled TPU) can be registered but broken; a
server or CLI must degrade to the host backend instead of dying. Honors
``PIO_PLATFORM`` (env) as an explicit override.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("pio.platform")


def ensure_backend(platform: str | None = None) -> str:
    """Make sure SOME JAX backend initializes; returns its platform name.

    Resolution order: explicit ``platform`` arg > ``PIO_PLATFORM`` env >
    JAX default. When that fails, retry with the known accelerator list
    ``"tpu,cpu"`` (a configured name may simply not be registered in this
    process), then settle for CPU.
    """
    import jax

    want = platform or os.environ.get("PIO_PLATFORM")
    if want:
        jax.config.update("jax_platforms", want)
    try:
        return jax.devices()[0].platform
    except RuntimeError as exc:
        # the configured platform list can name a plugin that never
        # registered in THIS process (observed: a site hook pins
        # jax_platforms="axon,cpu" while the TPU backend registers under
        # "tpu" -- and whether "axon" registers at all depends on the
        # working directory). Retry the KNOWN accelerator names rather
        # than "" (auto): auto-selection initializes every registered
        # plugin, and a registered-but-wedged tunnel plugin blocks
        # indefinitely on init -- the failure mode this function exists to
        # keep out of the CLI/servers. libtpu's init fails fast when no
        # local TPU is attached, so "tpu,cpu" is a bounded probe.
        logger.warning(
            "configured backend unavailable (%s); retrying tpu,cpu",
            exc,
        )
        try:
            jax.config.update("jax_platforms", "tpu,cpu")
            return jax.devices()[0].platform
        except RuntimeError as exc2:
            logger.warning("accelerator backend unavailable (%s); using CPU", exc2)
            jax.config.update("jax_platforms", "cpu")
            return jax.devices()[0].platform
