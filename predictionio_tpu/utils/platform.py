"""JAX backend resolution shared by the train and serve entry points.

Accelerator plugins (e.g. a tunneled TPU) can be registered but broken; a
server or CLI must degrade to the host backend instead of dying. Honors
``PIO_PLATFORM`` (env) as an explicit override.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("pio.platform")


def ensure_backend(platform: str | None = None, fallback: bool = False) -> str:
    """Make sure SOME JAX backend initializes; returns its platform name.

    Resolution order: explicit ``platform`` arg > ``PIO_PLATFORM`` env >
    JAX default. When the failing name came from the JAX default/site
    config, the degradation ladder always applies (retry ``"tpu,cpu"``,
    then settle for CPU). When the caller explicitly named a platform, an
    unavailable backend RAISES by default -- a typo'd ``PIO_PLATFORM``
    must not silently train/serve elsewhere -- unless ``fallback=True``:
    the long-running service entry points (deploy serving, the training
    workflow) opt in so a persisted ``pio.platform`` pin outlives an
    accelerator outage, with a prominent warning instead of a dead server.
    Callers can also pin a list (``PIO_PLATFORM=tpu,cpu``) to allow
    specific fallbacks without opting into CPU.
    """
    import jax

    want = platform or os.environ.get("PIO_PLATFORM")
    if want:
        prior = jax.config.jax_platforms
        jax.config.update("jax_platforms", want)
        try:
            return jax.devices()[0].platform
        except RuntimeError as exc:
            if not fallback:
                # restore the pre-call selection: a caller that catches
                # this to report a friendly error must not find the
                # process's JAX backend config left pointing at the
                # broken name
                jax.config.update("jax_platforms", prior)
                raise RuntimeError(
                    f"explicitly requested JAX platform {want!r} (via "
                    f"{'platform arg' if platform else 'PIO_PLATFORM'}) is "
                    f"unavailable: {exc}"
                ) from exc
            logger.warning(
                "pinned platform %r unavailable (%s); degrading because "
                "fallback=True", want, exc,
            )
    else:
        try:
            return jax.devices()[0].platform
        except RuntimeError as exc:
            # the configured platform list can name a plugin that never
            # registered in THIS process (observed: a site hook pins
            # jax_platforms="axon,cpu" while the TPU backend registers
            # under "tpu" -- and whether "axon" registers at all depends
            # on the working directory). Fall through to the bounded
            # ladder below.
            logger.warning(
                "configured backend unavailable (%s); retrying tpu,cpu",
                exc,
            )
    # shared degradation ladder. Retry the KNOWN accelerator names rather
    # than "" (auto): auto-selection initializes every registered plugin,
    # and a registered-but-wedged tunnel plugin blocks indefinitely on
    # init -- the failure mode this function exists to keep out of the
    # CLI/servers. libtpu's init fails fast when no local TPU is
    # attached, so "tpu,cpu" is a bounded probe.
    try:
        jax.config.update("jax_platforms", "tpu,cpu")
        return jax.devices()[0].platform
    except RuntimeError as exc2:
        logger.warning("accelerator backend unavailable (%s); using CPU", exc2)
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0].platform
