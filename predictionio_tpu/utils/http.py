"""Minimal threaded HTTP service toolkit over the standard library.

The reference serves REST with akka-http actors (SURVEY.md section 2.2 #15,
#25); here a ``ThreadingHTTPServer`` + route table plays that role -- no
external web framework is required. CORS and JSON envelopes are handled
centrally so every service (event server, query server, dashboard, admin)
shares behavior.
"""

from __future__ import annotations

import json
import re
import threading
import time
import traceback
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

from predictionio_tpu.obs.trace import format_traceparent


@dataclass
class Request:
    method: str
    path: str
    query: dict[str, str]
    headers: dict[str, str]
    body: bytes
    path_params: dict[str, str]
    #: set by the multi-process serving tier: ``(recv_pc, dispatch_pc,
    #: worker)`` -- the frontend worker's perf_counter timestamps (Linux
    #: CLOCK_MONOTONIC is system-wide, so they share the scorer's clock)
    #: bracketing the ring hop; the dispatch root records them as a
    #: ``frontend.ring_wait`` span so traces stitch across the process
    #: boundary
    frontend_pc: tuple | None = None

    def json(self) -> Any:
        if not self.body:
            return None
        return json.loads(self.body.decode("utf-8"))

    def form(self) -> dict[str, str]:
        parsed = parse_qs(self.body.decode("utf-8"), keep_blank_values=True)
        return {k: v[0] for k, v in parsed.items()}


@dataclass
class Response:
    status: int = 200
    body: Any = None
    content_type: str = "application/json; charset=utf-8"
    #: extra response headers (e.g. Retry-After on 429 backpressure)
    headers: dict[str, str] = field(default_factory=dict)

    def payload(self) -> bytes:
        if self.body is None:
            return b""
        if isinstance(self.body, bytes):
            return self.body
        if isinstance(self.body, str):
            return self.body.encode("utf-8")
        return json.dumps(self.body).encode("utf-8")


Handler = Callable[[Request], Response]


class Router:
    """Route table: (method, path regex with <name> captures) -> handler.

    With a ``metrics`` registry attached (``utils.metrics``), every dispatch
    records ``pio_http_requests_total{method,route,status}`` and a
    ``pio_http_request_duration_seconds`` histogram, labeled by the ROUTE
    PATTERN (bounded cardinality), not the raw path.

    With a ``tracer`` attached (``obs.trace``), every dispatch runs under
    a root span named by the route pattern: an inbound W3C ``traceparent``
    header joins the caller's trace, the response carries ``traceparent``
    out, error-status JSON bodies gain a ``traceId`` field, and handler
    exceptions become a 500 WITH the trace id (traceback still printed --
    the ``make_server`` backstop behavior, moved here so the trace id
    exists when the response is built).
    """

    def __init__(self, metrics=None, tracer=None):
        self._routes: list[tuple[str, str, re.Pattern, Handler]] = []
        self.metrics = metrics
        self.tracer = tracer

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        regex = re.sub(r"<([a-zA-Z_]+)>", r"(?P<\1>[^/]+)", pattern)
        self._routes.append(
            (method.upper(), pattern, re.compile(f"^{regex}$"), handler)
        )

    def route(self, method: str, pattern: str):
        def deco(fn: Handler) -> Handler:
            self.add(method, pattern, fn)
            return fn

        return deco

    #: never traced: a scrape loop (Prometheus, `pio top`) would otherwise
    #: flood the ring buffers with its own polling traffic
    UNTRACED_PATHS = ("/metrics", "/traces.json")

    def dispatch(self, request: Request) -> Response:
        tracer = self.tracer
        if (
            tracer is None
            or not tracer.enabled
            or request.path in self.UNTRACED_PATHS
        ):
            return self._dispatch(request, None)
        traceparent = next(
            (
                v
                for k, v in request.headers.items()
                if k.lower() == "traceparent"
            ),
            None,
        )
        with tracer.start_remote(
            f"{request.method} {request.path}", traceparent
        ) as span:
            # a sampled-out root (trace_id None) suppresses all span work
            # for the request; it must also not emit ids it never made
            sampled = span.trace_id is not None
            if sampled and request.frontend_pc is not None:
                recv_pc, dispatch_pc, worker = request.frontend_pc
                tracer.record_span(
                    span.trace_id, "frontend.ring_wait",
                    recv_pc, dispatch_pc,
                    parent_id=span.span_id, attrs={"worker": worker},
                )
            response = self._dispatch(request, span if sampled else None)
            if sampled:
                span.set_attr("status", response.status)
                if response.status >= 500:
                    span.set_status("error")
                response.headers.setdefault(
                    "traceparent",
                    format_traceparent(span.trace_id, span.span_id),
                )
                # error bodies carry the trace id so a client report ("here
                # is the 429 I got") joins directly to the server-side trace
                if response.status >= 400 and isinstance(response.body, dict):
                    response.body.setdefault("traceId", span.trace_id)
        return response

    def _dispatch(self, request: Request, span) -> Response:
        t0 = time.perf_counter()
        route_label = "<unmatched>"
        path_matched = False
        response = None
        for method, pattern, regex, handler in self._routes:
            m = regex.match(request.path)
            if not m:
                continue
            if not path_matched:
                path_matched = True
                route_label = pattern  # known even for a 405 below
            if method != request.method:
                continue
            request.path_params = m.groupdict()
            route_label = pattern
            if span is not None:
                # route pattern, not raw path: bounded op cardinality
                span.set_op(f"{request.method} {pattern}")
            try:
                response = handler(request)
            except json.JSONDecodeError:
                # same mapping the server backstop applies -- handled here
                # so the metric records the 400 the client actually gets
                response = Response(400, {"message": "malformed JSON body"})
            except Exception:
                # same backstop contract as make_server (traceback printed,
                # generic 500), handled here so the active span can stamp
                # its trace id onto the response
                traceback.print_exc()
                response = Response(500, {"message": "internal server error"})
            except BaseException:
                self._record(request, route_label, 500, t0)
                raise
            break
        if response is None:
            response = (
                Response(405, {"message": "method not allowed"})
                if path_matched
                else Response(404, {"message": "not found"})
            )
            if span is not None:
                # no handler ran, so the span still carries the raw client
                # path as its op; rename to the bounded route label or the
                # span->histogram bridge mints one series per scanner probe
                span.set_op(f"{request.method} {route_label}")
        self._record(request, route_label, response.status, t0)
        return response

    def record_route(
        self, request: Request, route: str, status: int, t0: float
    ) -> None:
        """Record the per-route request metrics for a request answered
        OUTSIDE ``dispatch`` -- the async scorer fast path submits
        ``/queries.json`` straight into the micro-batcher and finishes in
        a future callback, but its requests must land in the same
        ``pio_http_requests_total``/duration series with the same bounded
        route label."""
        self._record(request, route, status, t0)

    def _record(self, request: Request, route: str, status: int, t0: float) -> None:
        if self.metrics is None:
            return
        labels = {"method": request.method, "route": route, "status": str(status)}
        self.metrics.inc(
            "pio_http_requests_total", labels, help="HTTP requests served"
        )
        self.metrics.observe(
            "pio_http_request_duration_seconds",
            time.perf_counter() - t0,
            {"route": route},
            help="Request handling latency",
        )


def instrumented_router(
    before_scrape=None,
    tracing: bool | None = None,
    trace_sample: float | None = None,
    extra_snapshots=None,
) -> tuple[Router, "object"]:
    """(router, registry): a Router wired to a fresh MetricsRegistry with
    the ``GET /metrics`` Prometheus exposition route installed -- the one
    definition every service (event, query, dashboard, admin) shares --
    plus a span tracer (``router.tracer``) exposing ``GET /traces.json``
    (recent + slowest + error traces; ``?op=substr&min_ms=N&limit=N``).

    ``before_scrape(registry)`` runs on every /metrics request, letting a
    service mirror externally-tracked state (e.g. the query server's
    served-count) into the registry without maintaining it in two places.

    ``tracing`` defaults to on unless ``PIO_TRACING=0``; pass False for
    an A/B arm or a zero-overhead deployment (the disabled path hands out
    one shared no-op span and allocates nothing). ``trace_sample``
    defaults to ``PIO_TRACE_SAMPLE`` (1-in-8): headerless roots -- and
    ``traceparent`` headers with the W3C sampled flag clear (``-00``) --
    sample at that rate, while a header with the flag set always traces;
    pass 1.0 to trace everything.

    ``extra_snapshots()`` (optional) returns a list of
    ``MetricsRegistry.snapshot()`` dicts from OTHER processes -- the
    multi-process serving tier's frontend workers -- merged into every
    ``/metrics`` scrape so the deployed server exposes ONE aggregated
    view (counters/histograms sum across workers; gauges last-wins).
    """
    from predictionio_tpu.obs.trace import (
        Tracer,
        tracing_enabled_default,
        tracing_sample_default,
    )
    from predictionio_tpu.utils.metrics import (
        CONTENT_TYPE,
        MetricsRegistry,
        build_info_labels,
        global_registry,
        span_bridge,
    )

    registry = MetricsRegistry()
    if tracing is None:
        tracing = tracing_enabled_default()
    if trace_sample is None:
        trace_sample = tracing_sample_default()
    router = Router(
        metrics=registry,
        tracer=Tracer(
            enabled=tracing,
            on_spans=span_bridge(registry),
            sample=trace_sample,
        ),
    )
    # build-info labels can change exactly once per fact (backend resolves,
    # jax gets imported); zero out a superseded series so dashboards see
    # one live build_info row, then freeze once everything is resolved
    build_state = {"labels": None, "frozen": False}

    def refresh_build_info() -> None:
        if build_state["frozen"]:
            return
        labels = build_info_labels()
        prev = build_state["labels"]
        if prev is not None and prev != labels:
            registry.set_gauge("pio_build_info", 0.0, prev)
        registry.set_gauge(
            "pio_build_info", 1.0, labels,
            help="Build/runtime identity (value is always 1)",
        )
        build_state["labels"] = labels
        build_state["frozen"] = not (
            "not-imported" in labels.values()
            or labels.get("backend") == "uninitialized"
        )

    def handle_metrics(request: Request) -> Response:
        refresh_build_info()
        if before_scrape is not None:
            before_scrape(registry)
        snapshots = extra_snapshots() if extra_snapshots is not None else ()
        if snapshots:
            merged = MetricsRegistry()
            merged.merge_snapshot(registry.snapshot())
            for snap in snapshots:
                try:
                    merged.merge_snapshot(snap)
                except Exception:
                    # one worker's torn/garbled snapshot must not take the
                    # whole scrape down; its series are simply absent
                    continue
            body = merged.exposition()
        else:
            body = registry.exposition()
        # process-global series (training-snapshot cache etc.) ride every
        # service's scrape; names are disjoint from per-service ones
        shared = global_registry().exposition().strip()
        if shared:
            body = body.rstrip("\n") + "\n" + shared + "\n"
        return Response(200, body, content_type=CONTENT_TYPE)

    def handle_traces(request: Request) -> Response:
        q = request.query
        try:
            min_ms = float(q["min_ms"]) if "min_ms" in q else None
            limit = int(q.get("limit", 50))
        except ValueError:
            return Response(
                400, {"message": "min_ms must be a number, limit an integer"}
            )
        return Response(
            200, router.tracer.snapshot(op=q.get("op"), min_ms=min_ms, limit=limit)
        )

    router.add("GET", "/metrics", handle_metrics)
    router.add("GET", "/traces.json", handle_traces)
    return router, registry


_CORS_HEADERS = {
    "Access-Control-Allow-Origin": "*",
    "Access-Control-Allow-Methods": "GET, POST, DELETE, OPTIONS",
    "Access-Control-Allow-Headers": "Content-Type, Authorization",
}


# --------------------------------------------------------------------------
# lean HTTP/1.1 connection primitives (the multi-process frontend loop)
# --------------------------------------------------------------------------
#
# ``BaseHTTPRequestHandler`` costs ~1 ms of python per request (a handler
# object per REQUEST, header parsing through the email package, per-header
# send calls). The multi-process frontend workers instead run a
# single-threaded non-blocking loop over these primitives: ONE incremental
# parser buffer per connection, byte-exact Content-Length handling, and a
# single pre-serialized write per response.

MAX_REQUEST_LINE = 8192
MAX_HEADER_BYTES = 65536
MAX_HEADER_COUNT = 100
#: request bodies beyond this 413 at the frontend (queries are KBs; this
#: exists so a hostile stream cannot balloon the ring spill directory)
MAX_BODY_BYTES = 32 * 1024 * 1024

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 413: "Payload Too Large",
    414: "URI Too Long", 429: "Too Many Requests",
    431: "Request Header Fields Too Large",
    500: "Internal Server Error", 501: "Not Implemented",
    503: "Service Unavailable", 505: "HTTP Version Not Supported",
}


class HTTPParseError(Exception):
    """Malformed/unsupported inbound HTTP; carries the status to answer
    with before closing the connection."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


@dataclass
class ParsedRequest:
    """One wire-parsed request (pre-routing; the frontend's unit of work)."""

    method: str
    target: str               # raw request-target (path + query string)
    headers: dict[str, str]
    body: bytes
    keep_alive: bool


def _header(headers: dict[str, str], name: str) -> str | None:
    lname = name.lower()
    for k, v in headers.items():
        if k.lower() == lname:
            return v
    return None


class RequestParser:
    """Incremental HTTP/1.1 request parser for a non-blocking loop.

    ``feed()`` appends received bytes; ``next_request()`` returns one
    complete :class:`ParsedRequest` (pipelined requests come out one per
    call, in order), ``None`` while more bytes are needed, and raises
    :class:`HTTPParseError` on anything malformed -- the caller answers
    with its status and closes. A parsed header block is cached across
    calls, so a body arriving in many segments never re-parses headers.
    """

    __slots__ = ("_buf", "_head")

    def __init__(self):
        self._buf = bytearray()
        self._head: tuple | None = None  # (method, target, headers, length, keep)

    def feed(self, data: bytes) -> None:
        self._buf += data

    def buffered(self) -> int:
        return len(self._buf)

    def next_request(self) -> ParsedRequest | None:
        if self._head is None:
            end = self._buf.find(b"\r\n\r\n")
            if end < 0:
                if len(self._buf) > MAX_HEADER_BYTES:
                    raise HTTPParseError(431, "header block too large")
                return None
            self._head = self._parse_head(bytes(self._buf[:end]))
            del self._buf[:end + 4]
        method, target, headers, length, keep = self._head
        if len(self._buf) < length:
            return None
        body = bytes(self._buf[:length])
        del self._buf[:length]
        self._head = None
        return ParsedRequest(method, target, headers, body, keep)

    @staticmethod
    def _parse_head(block: bytes) -> tuple:
        lines = block.decode("latin-1").split("\r\n")
        parts = lines[0].split()
        if len(parts) != 3:
            raise HTTPParseError(400, "malformed request line")
        method, target, version = parts
        if len(lines[0]) > MAX_REQUEST_LINE:
            raise HTTPParseError(414, "request line too long")
        if version not in ("HTTP/1.1", "HTTP/1.0"):
            raise HTTPParseError(505, f"unsupported version {version}")
        if len(lines) - 1 > MAX_HEADER_COUNT:
            raise HTTPParseError(431, "too many headers")
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            key, sep, value = line.partition(":")
            if not sep or not key.strip():
                raise HTTPParseError(400, "malformed header line")
            headers[key.strip()] = value.strip()
        if _header(headers, "Transfer-Encoding") is not None:
            # same capability envelope as the single-process server (it
            # reads Content-Length only); 501 beats silent mis-framing
            raise HTTPParseError(501, "Transfer-Encoding not supported")
        raw_length = _header(headers, "Content-Length")
        try:
            length = int(raw_length) if raw_length else 0
        except ValueError:
            raise HTTPParseError(400, "bad Content-Length")
        if length < 0:
            raise HTTPParseError(400, "bad Content-Length")
        if length > MAX_BODY_BYTES:
            raise HTTPParseError(413, "request body too large")
        connection = (_header(headers, "Connection") or "").lower()
        if version == "HTTP/1.1":
            keep_alive = connection != "close"
        else:
            keep_alive = connection == "keep-alive"
        return method, target, headers, length, keep_alive


#: Date header cache: one strftime per wall-clock second, not per request
_date_cache: tuple[int, str] = (0, "")


def _http_date() -> str:
    global _date_cache
    now = int(time.time())
    if _date_cache[0] != now:
        _date_cache = (
            now,
            time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(now)),
        )
    return _date_cache[1]


def build_http_response(
    status: int,
    payload: bytes,
    content_type: str = "application/json; charset=utf-8",
    headers: dict[str, str] | None = None,
    server_name: str = "pio",
    keep_alive: bool = True,
) -> bytes:
    """Serialize one response to a single buffer (headers + body), ready
    for one non-blocking send -- one segment + NODELAY, the same
    anti-Nagle contract as ``make_server``'s buffered wfile."""
    out = [
        f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}\r\n"
        f"Server: {server_name}\r\n"
        f"Date: {_http_date()}\r\n"
        f"Content-Type: {content_type}\r\n"
        f"Content-Length: {len(payload)}\r\n"
    ]
    for k, v in _CORS_HEADERS.items():
        out.append(f"{k}: {v}\r\n")
    for k, v in (headers or {}).items():
        out.append(f"{k}: {v}\r\n")
    # explicit in both directions: HTTP/1.0 keep-alive only works if the
    # server SAYS keep-alive (default is close), and the header is
    # harmless redundancy for HTTP/1.1 peers
    out.append(
        "Connection: keep-alive\r\n" if keep_alive
        else "Connection: close\r\n"
    )
    out.append("\r\n")
    return "".join(out).encode("latin-1") + payload


def make_server(
    router: Router,
    host: str,
    port: int,
    server_name: str,
    ssl_cert: str | None = None,
    ssl_key: str | None = None,
) -> ThreadingHTTPServer:
    """Build the threaded server; with ``ssl_cert``/``ssl_key`` it serves
    HTTPS (parity role of the reference query server's ``--key-store`` TLS,
    SURVEY.md section 2.3 #25)."""
    class _RequestHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = server_name
        # one TCP segment per response: buffered wfile (handle_one_request
        # flushes it) + NODELAY. Without these, headers and body go out as
        # separate small segments and Nagle + client delayed-ACK adds ~40ms
        # to EVERY keep-alive request -- the difference between a 1ms and a
        # 44ms p50 on /queries.json
        wbufsize = -1
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # quiet by default; services log themselves
            pass

        def _handle(self):
            parsed = urlparse(self.path)
            query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            request = Request(
                method=self.command,
                path=parsed.path,
                query=query,
                headers={k: v for k, v in self.headers.items()},
                body=body,
                path_params={},
            )
            if self.command == "OPTIONS":
                response = Response(200, "")
            else:
                try:
                    response = router.dispatch(request)
                except json.JSONDecodeError:
                    response = Response(400, {"message": "malformed JSON body"})
                except Exception:
                    traceback.print_exc()
                    response = Response(500, {"message": "internal server error"})
            payload = response.payload()
            self.send_response(response.status)
            self.send_header("Content-Type", response.content_type)
            self.send_header("Content-Length", str(len(payload)))
            for k, v in _CORS_HEADERS.items():
                self.send_header(k, v)
            for k, v in response.headers.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)

        do_GET = do_POST = do_DELETE = do_PUT = do_OPTIONS = _handle

    if ssl_key and not ssl_cert:
        raise ValueError("ssl_key given without ssl_cert; TLS not enabled")

    class _Server(ThreadingHTTPServer):
        # socketserver's default listen backlog is 5: a burst of N>5
        # simultaneous connects (every load balancer health-check +
        # client-pool refill looks like this) overflows it and the kernel
        # drops SYNs, surfacing as 1s/3s/7s retransmit spikes in p99
        request_queue_size = 128

    server = _Server((host, port), _RequestHandler)
    if ssl_cert:
        import ssl

        context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        context.load_cert_chain(certfile=ssl_cert, keyfile=ssl_key or None)
        # handshake on first read, NOT in accept(): with on-connect handshake
        # a stalled client would block the single accept loop and freeze the
        # whole server; deferred, it runs in the per-connection thread
        server.socket = context.wrap_socket(
            server.socket, server_side=True, do_handshake_on_connect=False
        )
    return server


class ServiceThread:
    """Run an HTTP server on a daemon thread (tests / embedded use).

    ``on_stop`` runs after the listener closes -- the hook services use to
    drain background pipelines (e.g. the event server's ingest writer).
    """

    def __init__(self, server: ThreadingHTTPServer, on_stop: Callable[[], None] | None = None):
        self.server = server
        self.on_stop = on_stop
        self._thread = threading.Thread(target=server.serve_forever, daemon=True)

    @property
    def port(self) -> int:
        return self.server.server_address[1]

    def start(self) -> "ServiceThread":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        if self.on_stop is not None:
            self.on_stop()
