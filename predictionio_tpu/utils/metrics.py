"""Dependency-free Prometheus-text metrics.

SURVEY.md section 5.5: the reference had no metrics endpoint (log4j +
`/stats.json` only); the rebuild plan calls for structured logging "+
optional Prometheus". This module is that option without a client-library
dependency: counters and fixed-bucket histograms with the text exposition
format any Prometheus/OpenMetrics scraper ingests.

Services attach a registry to their Router (per-request method/route/status
counts + latency histograms are recorded centrally in ``Router.dispatch``)
and expose ``GET /metrics``.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Mapping

#: latency buckets (seconds): sub-ms serving up to slow storage calls
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: span-duration buckets (seconds): spans start well under the request
#: histograms (queue waits and WAL appends are tens of microseconds)
SPAN_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0,
)


def global_registry() -> "MetricsRegistry":
    """The process-wide registry for instrumentation that does not belong
    to any one service router (e.g. the training-snapshot cache, which
    runs inside ``pio train`` AND inside servers that train in-process).
    ``instrumented_router`` merges it into every ``/metrics`` scrape; the
    names recorded here must not collide with per-service ones."""
    return _GLOBAL_REGISTRY


def _escape(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"')


def _fmt_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class MetricsRegistry:
    """Thread-safe counters + histograms with Prometheus text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> help text
        self._help: dict[str, str] = {}
        # name -> {sorted-label-tuple -> float}
        self._counters: dict[str, dict[tuple, float]] = {}
        # name -> {sorted-label-tuple -> float}; set-to-value semantics
        self._gauges: dict[str, dict[tuple, float]] = {}
        # name -> (buckets, {sorted-label-tuple -> [bucket counts..., sum, count]})
        self._histograms: dict[str, tuple[tuple, dict[tuple, list]]] = {}

    def inc(
        self,
        name: str,
        labels: Mapping[str, str] | None = None,
        amount: float = 1.0,
        help: str = "",
    ) -> None:
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + amount

    def set_counter(
        self,
        name: str,
        value: float,
        labels: Mapping[str, str] | None = None,
        help: str = "",
    ) -> None:
        """Pin a counter to an externally-tracked value (single source of
        truth lives elsewhere; the registry only exposes it)."""
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            self._counters.setdefault(name, {})[key] = float(value)

    def set_gauge(
        self,
        name: str,
        value: float,
        labels: Mapping[str, str] | None = None,
        help: str = "",
    ) -> None:
        """Point-in-time value (queue depth, pool size): exposed with TYPE
        gauge so scrapers don't apply rate() to it."""
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            self._gauges.setdefault(name, {})[key] = float(value)

    def observe(
        self,
        name: str,
        value: float,
        labels: Mapping[str, str] | None = None,
        buckets: tuple = DEFAULT_BUCKETS,
        help: str = "",
    ) -> None:
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            bucket_spec, series = self._histograms.setdefault(
                name, (tuple(buckets), {})
            )
            row = series.setdefault(key, [0] * (len(bucket_spec) + 1) + [0.0, 0])
            # rows hold PER-BUCKET (non-cumulative) counts: one bisect +
            # one increment per observation instead of a walk over every
            # bucket -- observe sits on the span bridge's per-span path.
            # Exposition folds the running sum back into Prometheus'
            # cumulative le semantics.
            row[bisect_left(bucket_spec, value)] += 1
            row[-2] += value                  # sum
            row[-1] += 1                      # count

    def observe_batch(
        self,
        name: str,
        items: "list[tuple[float, tuple]]",
        buckets: tuple = DEFAULT_BUCKETS,
        help: str = "",
    ) -> None:
        """Fold many ``(value, label_key)`` observations under one lock
        acquisition; ``label_key`` is the pre-sorted ``(("k", "v"), ...)``
        series key. The span bridge's path: one call per completed trace
        instead of one lock round-trip per span."""
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            bucket_spec, series = self._histograms.setdefault(
                name, (tuple(buckets), {})
            )
            empty = [0] * (len(bucket_spec) + 1) + [0.0, 0]
            for value, key in items:
                row = series.get(key)
                if row is None:
                    row = series[key] = empty[:]
                row[bisect_left(bucket_spec, value)] += 1
                row[-2] += value              # sum
                row[-1] += 1                  # count

    def snapshot(self) -> dict:
        """JSON-serializable dump of every series -- the cross-process
        aggregation format. The multi-process serving tier's frontend
        workers publish these through their ring's stats region; the
        scorer merges them (``merge_snapshot``) into one ``/metrics``
        view at scrape time. Label keys ride as ``[[k, v], ...]`` pairs
        so the dump survives a JSON round-trip."""
        with self._lock:
            return {
                "help": dict(self._help),
                "counters": [
                    [name, [list(kv) for kv in key], value]
                    for name, series in self._counters.items()
                    for key, value in series.items()
                ],
                "gauges": [
                    [name, [list(kv) for kv in key], value]
                    for name, series in self._gauges.items()
                    for key, value in series.items()
                ],
                "histograms": [
                    [name, list(buckets), [list(kv) for kv in key], list(row)]
                    for name, (buckets, series) in self._histograms.items()
                    for key, row in series.items()
                ],
            }

    def merge_snapshot(self, snap: dict) -> None:
        """Fold a ``snapshot()`` dump into this registry: counters and
        histogram rows ADD (sum across workers), gauges SET (last writer
        wins -- point-in-time values don't sum meaningfully across
        label-identical series; per-worker gauges carry a ``worker``
        label precisely so they never collide). A histogram whose bucket
        spec disagrees with an existing series is rejected loudly --
        silent bucket mixing would corrupt every quantile downstream."""
        with self._lock:
            for name, text in (snap.get("help") or {}).items():
                self._help.setdefault(name, text)
            for name, key, value in snap.get("counters") or ():
                key = tuple(tuple(kv) for kv in key)
                series = self._counters.setdefault(name, {})
                series[key] = series.get(key, 0.0) + float(value)
            for name, key, value in snap.get("gauges") or ():
                key = tuple(tuple(kv) for kv in key)
                self._gauges.setdefault(name, {})[key] = float(value)
            for name, buckets, key, row in snap.get("histograms") or ():
                key = tuple(tuple(kv) for kv in key)
                bucket_spec, series = self._histograms.setdefault(
                    name, (tuple(buckets), {})
                )
                if tuple(buckets) != bucket_spec:
                    raise ValueError(
                        f"histogram {name!r}: bucket spec mismatch in merge"
                    )
                mine = series.setdefault(
                    key, [0] * (len(bucket_spec) + 1) + [0.0, 0]
                )
                for i, v in enumerate(row):
                    mine[i] += v

    def exposition(self) -> str:
        lines: list[str] = []
        with self._lock:
            for name, series in sorted(self._counters.items()):
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                lines.append(f"# TYPE {name} counter")
                for key, value in sorted(series.items()):
                    # .17g, not %g: %g rounds to 6 significant digits, which
                    # freezes large counters between scrapes and breaks rate()
                    lines.append(f"{name}{_fmt_labels(dict(key))} {value:.17g}")
            for name, series in sorted(self._gauges.items()):
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                lines.append(f"# TYPE {name} gauge")
                for key, value in sorted(series.items()):
                    lines.append(f"{name}{_fmt_labels(dict(key))} {value:.17g}")
            for name, (buckets, series) in sorted(self._histograms.items()):
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                lines.append(f"# TYPE {name} histogram")
                for key, row in sorted(series.items()):
                    labels = dict(key)
                    # rows store per-bucket counts; Prometheus buckets are
                    # cumulative, so fold the running sum here (scrape
                    # rate), not in observe (span rate)
                    cumulative = 0
                    for i, le in enumerate(buckets):
                        cumulative += row[i]
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels({**labels, 'le': f'{le:g}'})}"
                            f" {cumulative}"
                        )
                    lines.append(
                        f"{name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})}"
                        f" {cumulative + row[len(buckets)]}"
                    )
                    lines.append(f"{name}_sum{_fmt_labels(labels)} {row[-2]:.17g}")
                    lines.append(f"{name}_count{_fmt_labels(labels)} {row[-1]}")
        return "\n".join(lines) + "\n"


_GLOBAL_REGISTRY = MetricsRegistry()


def span_bridge(registry: MetricsRegistry):
    """Span -> histogram bridge: the batch hook (``obs.trace.Tracer
    (on_spans=...)``) that folds finished spans into
    ``pio_span_duration_seconds{op}``, so the aggregate view of the
    traced stages exists without a second instrumentation layer. Takes a
    LIST (one completed trace, or standalone records) and folds it under
    ONE registry lock acquisition -- per-span locking convoyed the
    serving tier's handler threads. Op cardinality is bounded by
    construction (route patterns + a fixed set of stage names)."""

    def observe(records) -> None:
        registry.observe_batch(
            "pio_span_duration_seconds",
            [(r.duration_s, (("op", r.op),)) for r in records],
            buckets=SPAN_BUCKETS,
            help="Span durations by operation (tracing bridge)",
        )
        for r in records:
            if r.status == "error":
                registry.inc(
                    "pio_span_errors_total",
                    {"op": r.op},
                    help="Spans finished in error status",
                )

    return observe


def build_info_labels() -> dict[str, str]:
    """Labels for the ``pio_build_info`` gauge: package version, jax
    version, EFFECTIVE backend, and the ``IS_LEGACY_JAX`` drift-shim
    state -- the four facts a dashboard or bug report needs to correlate
    behavior with the runtime actually underneath.

    Never initializes jax (a ``/metrics`` scrape must not wedge a
    storage-only service on a dead accelerator tunnel): if jax is not
    imported the backend reports ``not-imported``; if imported but no
    backend has been resolved yet it reports ``uninitialized``.
    """
    import sys

    from predictionio_tpu.version import __version__

    labels = {"version": __version__}
    jaxmod = sys.modules.get("jax")
    if jaxmod is None:
        labels["jax_version"] = "not-imported"
        labels["backend"] = "not-imported"
        labels["legacy_jax"] = "unknown"
        return labels
    labels["jax_version"] = getattr(jaxmod, "__version__", "unknown")
    try:
        from predictionio_tpu.utils.jax_compat import IS_LEGACY_JAX

        labels["legacy_jax"] = "true" if IS_LEGACY_JAX else "false"
    except Exception:
        labels["legacy_jax"] = "unknown"
    backend = None
    try:
        # read the already-resolved backend without triggering resolution
        xla_bridge = jaxmod._src.xla_bridge
        resolved = getattr(xla_bridge, "_default_backend", None)
        backend = getattr(resolved, "platform", None)
    except Exception:
        backend = None
    labels["backend"] = backend or "uninitialized"
    return labels
