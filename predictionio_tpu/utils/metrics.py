"""Dependency-free Prometheus-text metrics.

SURVEY.md section 5.5: the reference had no metrics endpoint (log4j +
`/stats.json` only); the rebuild plan calls for structured logging "+
optional Prometheus". This module is that option without a client-library
dependency: counters and fixed-bucket histograms with the text exposition
format any Prometheus/OpenMetrics scraper ingests.

Services attach a registry to their Router (per-request method/route/status
counts + latency histograms are recorded centrally in ``Router.dispatch``)
and expose ``GET /metrics``.
"""

from __future__ import annotations

import threading
from typing import Mapping

#: latency buckets (seconds): sub-ms serving up to slow storage calls
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def global_registry() -> "MetricsRegistry":
    """The process-wide registry for instrumentation that does not belong
    to any one service router (e.g. the training-snapshot cache, which
    runs inside ``pio train`` AND inside servers that train in-process).
    ``instrumented_router`` merges it into every ``/metrics`` scrape; the
    names recorded here must not collide with per-service ones."""
    return _GLOBAL_REGISTRY


def _escape(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"')


def _fmt_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class MetricsRegistry:
    """Thread-safe counters + histograms with Prometheus text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> help text
        self._help: dict[str, str] = {}
        # name -> {sorted-label-tuple -> float}
        self._counters: dict[str, dict[tuple, float]] = {}
        # name -> {sorted-label-tuple -> float}; set-to-value semantics
        self._gauges: dict[str, dict[tuple, float]] = {}
        # name -> (buckets, {sorted-label-tuple -> [bucket counts..., sum, count]})
        self._histograms: dict[str, tuple[tuple, dict[tuple, list]]] = {}

    def inc(
        self,
        name: str,
        labels: Mapping[str, str] | None = None,
        amount: float = 1.0,
        help: str = "",
    ) -> None:
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + amount

    def set_counter(
        self,
        name: str,
        value: float,
        labels: Mapping[str, str] | None = None,
        help: str = "",
    ) -> None:
        """Pin a counter to an externally-tracked value (single source of
        truth lives elsewhere; the registry only exposes it)."""
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            self._counters.setdefault(name, {})[key] = float(value)

    def set_gauge(
        self,
        name: str,
        value: float,
        labels: Mapping[str, str] | None = None,
        help: str = "",
    ) -> None:
        """Point-in-time value (queue depth, pool size): exposed with TYPE
        gauge so scrapers don't apply rate() to it."""
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            self._gauges.setdefault(name, {})[key] = float(value)

    def observe(
        self,
        name: str,
        value: float,
        labels: Mapping[str, str] | None = None,
        buckets: tuple = DEFAULT_BUCKETS,
        help: str = "",
    ) -> None:
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            bucket_spec, series = self._histograms.setdefault(
                name, (tuple(buckets), {})
            )
            row = series.setdefault(key, [0] * (len(bucket_spec) + 1) + [0.0, 0])
            for i, le in enumerate(bucket_spec):
                if value <= le:
                    row[i] += 1
            row[len(bucket_spec)] += 1        # +Inf bucket
            row[-2] += value                  # sum
            row[-1] += 1                      # count

    def exposition(self) -> str:
        lines: list[str] = []
        with self._lock:
            for name, series in sorted(self._counters.items()):
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                lines.append(f"# TYPE {name} counter")
                for key, value in sorted(series.items()):
                    # .17g, not %g: %g rounds to 6 significant digits, which
                    # freezes large counters between scrapes and breaks rate()
                    lines.append(f"{name}{_fmt_labels(dict(key))} {value:.17g}")
            for name, series in sorted(self._gauges.items()):
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                lines.append(f"# TYPE {name} gauge")
                for key, value in sorted(series.items()):
                    lines.append(f"{name}{_fmt_labels(dict(key))} {value:.17g}")
            for name, (buckets, series) in sorted(self._histograms.items()):
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                lines.append(f"# TYPE {name} histogram")
                for key, row in sorted(series.items()):
                    labels = dict(key)
                    # rows store per-bucket CUMULATIVE counts already
                    # (observe increments every bucket with value <= le)
                    for i, le in enumerate(buckets):
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels({**labels, 'le': f'{le:g}'})}"
                            f" {row[i]}"
                        )
                    lines.append(
                        f"{name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})}"
                        f" {row[len(buckets)]}"
                    )
                    lines.append(f"{name}_sum{_fmt_labels(labels)} {row[-2]:.17g}")
                    lines.append(f"{name}_count{_fmt_labels(labels)} {row[-1]}")
        return "\n".join(lines) + "\n"


_GLOBAL_REGISTRY = MetricsRegistry()
