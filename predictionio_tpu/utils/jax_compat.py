"""Version-drift shims for the jax API surface this repo rides.

The codebase targets the current jax API (``jax.shard_map``, varying-mesh-
axes types, ``jax.lax.pcast``); the installed jax may predate it (0.4.x
exposes ``shard_map`` only under ``jax.experimental`` with the vma checker
named ``check_rep`` and no vma machinery at all). Every call site imports
from HERE instead of feature-testing jax inline, so the drift policy lives
in one module and the day the floor moves past the new API this file
deletes down to three aliases.

Mapping rules:

- ``shard_map``: ``jax.shard_map`` when present, else
  ``jax.experimental.shard_map.shard_map`` with the ``check_vma`` kwarg
  renamed to its old spelling ``check_rep`` (same meaning: False disables
  the output-replication/varying checker, which pallas-in-interpret bodies
  trip on both APIs).
- ``pcast_varying``: ``jax.lax.pcast(..., to="varying")`` when present,
  else identity -- pre-vma jax has no varying/unvarying distinction, so a
  fresh constant already has whatever type the checker expects.
- ``shape_struct``: ``jax.ShapeDtypeStruct`` carrying the vma of a model
  array (so pallas out_shapes compose under ``shard_map(check_vma=True)``)
  when ``jax.typeof`` exists; the plain struct otherwise.
- ``pallas`` / ``pallas_tpu``: the Pallas modules, resolved through module
  ``__getattr__`` so importing this shim stays cheap for callers that only
  need ``IS_LEGACY_JAX`` (Pallas pulls in Mosaic lowering machinery).
- ``broadcast_one_to_all`` / ``process_allgather`` /
  ``create_hybrid_device_mesh``: lazy fronts for the multihost/mesh utils
  that still live under ``jax.experimental`` on every supported jax.

``pio check`` rule J001 enforces that every ``jax.experimental`` /
``jax.shard_map`` / ``pjit`` touch in the package routes through here.
"""

from __future__ import annotations

import jax

#: True on pre-``jax.shard_map`` (0.4.x) installs. Gates the few behaviors
#: the legacy stack MISCOMPILES rather than lacks: donating a tp-sharded
#: optimizer-state pytree pairs donated buffers with wrong-shaped outputs
#: inside XLA ("Expected aliased input ... to have the same size").
IS_LEGACY_JAX = not hasattr(jax, "shard_map")

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
                  **kwargs):
        """``jax.shard_map`` signature on the legacy experimental API."""
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kwargs,
        )


def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis (``jax.lax.axis_size``); the old
    API spells it ``psum(1, name)``, which constant-folds to a python int
    at trace time."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast_varying(x, axis_name):
    """Cast a fresh constant to a "varying" collective type (scan carries
    must match their varying body outputs under the vma checker); identity
    on pre-vma jax, where constants and collectives share one type."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to="varying")
    return x


def __getattr__(name: str):
    """Lazy module attributes (PEP 562): ``from ...jax_compat import
    pallas as pl`` works, but callers that never touch Pallas never pay
    its import."""
    if name == "pallas":
        from jax.experimental import pallas

        globals()[name] = pallas
        return pallas
    if name == "pallas_tpu":
        from jax.experimental.pallas import tpu as pallas_tpu

        globals()[name] = pallas_tpu
        return pallas_tpu
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def broadcast_one_to_all(x):
    """One value (array or pytree) from process 0 to every process."""
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(x)


def process_allgather(x, tiled: bool = False):
    """Gather per-process values onto every host as a numpy array."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=tiled)


def create_hybrid_device_mesh(mesh_shape, dcn_mesh_shape, devices=None, **kwargs):
    """ICI-adjacency-preserving device grid for multi-slice meshes."""
    from jax.experimental import mesh_utils

    return mesh_utils.create_hybrid_device_mesh(
        mesh_shape, dcn_mesh_shape, devices=devices, **kwargs
    )


def shape_struct(shape, dtype, like=None):
    """ShapeDtypeStruct inheriting ``like``'s varying-mesh-axes, when the
    installed jax tracks them; plain (non-sharded) callers and pre-vma jax
    get the ordinary struct."""
    typeof = getattr(jax, "typeof", None)
    vma = getattr(typeof(like), "vma", None) if typeof and like is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)
