"""The ONE stable entity-hash definition shared by serving and ingest.

Both the serving fabric's shard router (``serving/shardmap.shard_of``)
and the ingest pipeline's WAL-partition router (``data/ingest``) bucket
entities with this function. Keeping a single definition means an event
for user u is always durably ordered in the same WAL partition that the
serving tier consults for u's factors -- the two layers can never drift.

``zlib.crc32`` rather than ``hash()``: Python string hashing is salted
per interpreter (PYTHONHASHSEED), and the router, the shard processes,
and the follower are *different* interpreters -- a salted hash would
route entity e to bucket 1 in one process and bucket 2 in another.
CRC32 is stable across processes, platforms, and releases, which also
keeps on-disk partition layouts portable between writes and any later
replay.

Import-light on purpose: the frontend worker (serving/frontend.py) is a
no-jax, no-numpy interpreter, so only stdlib may be imported here.
"""

from __future__ import annotations

import zlib

__all__ = ["stable_bucket"]


def stable_bucket(key: object, buckets: int) -> int:
    """The 0-based bucket that owns ``key`` out of ``buckets`` total.

    Scalars are stringified (``str(key)``) before hashing, matching the
    serving tier's ``str(query.get("user"))`` lookups, so a JSON number
    and its string form land in the same bucket.
    """
    if buckets <= 1:
        return 0
    return zlib.crc32(str(key).encode("utf-8")) % buckets
