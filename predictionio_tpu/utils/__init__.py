"""Cross-cutting utilities: logging config, small HTTP server toolkit."""
