"""Time-travel splits for offline replay evaluation.

The split contract: train on events strictly BEFORE ``t``, hold out
interactions AT-OR-AFTER ``t`` (``times >= t``) -- the boundary event
lands in the holdout, matching the snapshot layer's EXCLUSIVE ``until``
bound (``data/snapshot.Snapshot.until_time``) so a replay split and a
snapshot generation bounded at the same ``t`` cover exactly the same
prefix. Exactness is microsecond-level: the split time parses through
the same ``datetime.fromisoformat(...).timestamp()`` path
``EventDataset`` uses for event times, so an event stamped exactly ``t``
compares equal as float64 epoch seconds, never "close".
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field
from typing import Any

import numpy as np

#: the operator-facing format hint for malformed --split-time values
#: (the ``pio check --rules`` contract: exit 2 with the expectation
#: spelled out, never a traceback)
SPLIT_TIME_FORMAT = (
    "ISO-8601, e.g. 2024-01-31T00:00:00+00:00 (a naive timestamp is"
    " read as UTC)"
)


def parse_split_time(value: str) -> float:
    """``--split-time`` ISO string -> float64 epoch seconds.

    Naive timestamps are read as UTC (event times are stored UTC);
    anything ``datetime.fromisoformat`` rejects raises ``ValueError``
    carrying the expected format.
    """
    try:
        # same 'Z' normalization as event ingestion (data/event.py)
        parsed = _dt.datetime.fromisoformat(str(value).replace("Z", "+00:00"))
    except (ValueError, TypeError):
        raise ValueError(
            f"malformed --split-time {value!r}; expected {SPLIT_TIME_FORMAT}"
        ) from None
    if parsed.tzinfo is None:
        parsed = parsed.replace(tzinfo=_dt.timezone.utc)
    return parsed.timestamp()


def _iso(seconds: float) -> str:
    return _dt.datetime.fromtimestamp(
        seconds, tz=_dt.timezone.utc
    ).isoformat()


@dataclass(frozen=True)
class SplitSpec:
    """How to cut the event timeline: an explicit ISO boundary OR an
    event-count fraction (the boundary becomes the timestamp of the
    first held-out event, so a fraction split is as replayable as an
    explicit one). ``k`` rides along because the datasource hooks build
    top-``k`` queries for the held-out users."""

    split_time: str | None = None
    split_frac: float | None = None
    k: int = 10

    def validate(self) -> None:
        if (self.split_time is None) == (self.split_frac is None):
            raise ValueError(
                "exactly one of --split-time and --split-frac is required"
            )
        if self.split_time is not None:
            parse_split_time(self.split_time)
        if self.split_frac is not None and not 0.0 < self.split_frac < 1.0:
            raise ValueError(
                f"--split-frac must be in (0, 1), got {self.split_frac}"
            )
        if self.k < 1:
            raise ValueError(f"--k must be >= 1, got {self.k}")


@dataclass
class SplitBounds:
    """The resolved, replayable description of one split -- recorded in
    the report so a later run can reproduce it with --split-time."""

    split_time_iso: str
    split_frac: float | None
    train_events: int
    holdout_events: int
    holdout_users: int
    train_until_iso: str | None   # newest training event
    holdout_from_iso: str | None  # oldest held-out event

    def to_json_obj(self) -> dict:
        return dict(self.__dict__)


@dataclass
class SplitCut:
    """One template-agnostic cut of (users, items, times) arrays."""

    train_mask: np.ndarray                 # bool [n]
    holdout: dict[int, np.ndarray]         # user idx -> unique item idxs
    bounds: SplitBounds
    split_seconds: float


@dataclass
class ReplayFold:
    """What a datasource's ``read_replay`` hands the replay runner:
    prefix training data (template-shaped), per-held-out-user
    ``(query, [actual item ids])`` pairs, and the resolved bounds."""

    train_data: Any
    pairs: list = field(default_factory=list)
    bounds: SplitBounds | None = None


def resolve_split_seconds(times: np.ndarray, spec: SplitSpec) -> float:
    """The split boundary as epoch seconds. A fraction resolves to the
    timestamp of the event at the ``frac`` quantile of the TIME-SORTED
    stream (ties at that timestamp all land in the holdout -- the
    ``>= t`` rule keeps the split exact rather than exactly-sized)."""
    spec.validate()
    if spec.split_time is not None:
        return parse_split_time(spec.split_time)
    times = np.asarray(times, np.float64)
    if times.size == 0:
        raise ValueError("no events to split -- check appName and eventNames")
    idx = min(int(spec.split_frac * times.size), times.size - 1)
    return float(np.sort(times)[idx])


def split_interactions(
    users: np.ndarray,
    items: np.ndarray,
    times: np.ndarray,
    spec: SplitSpec,
) -> SplitCut:
    """Cut COO interaction arrays at the spec's boundary.

    Returns the train mask (``times < t``), the held-out interactions
    grouped per user (unique item indices, ascending user order -- the
    deterministic query order every run replays identically), and the
    resolved bounds.
    """
    times = np.asarray(times, np.float64)
    t = resolve_split_seconds(times, spec)
    train_mask = times < t
    hold = ~train_mask
    h_users = np.asarray(users)[hold]
    h_items = np.asarray(items)[hold]
    # sorted-split grouping (the build_seen construction): O(distinct
    # users) interpreter time, not O(events)
    holdout: dict[int, np.ndarray] = {}
    if h_users.size:
        order = np.argsort(h_users, kind="stable")
        su, si = h_users[order], h_items[order]
        uniq, starts = np.unique(su, return_index=True)
        ends = np.append(starts[1:], su.size)
        holdout = {
            int(u): np.unique(si[s:e])
            for u, s, e in zip(uniq.tolist(), starts.tolist(), ends.tolist())
        }
    bounds = SplitBounds(
        split_time_iso=_iso(t),
        split_frac=spec.split_frac,
        train_events=int(train_mask.sum()),
        holdout_events=int(hold.sum()),
        holdout_users=len(holdout),
        train_until_iso=_iso(float(times[train_mask].max()))
        if train_mask.any() else None,
        holdout_from_iso=_iso(float(times[hold].min()))
        if hold.any() else None,
    )
    return SplitCut(
        train_mask=train_mask, holdout=holdout, bounds=bounds,
        split_seconds=t,
    )
