"""Offline replay evaluation -- the DASE "E" pillar for the TPU port.

``pio eval --replay`` cuts the event timeline at ``t`` (train ``< t``,
holdout ``>= t``), trains on the prefix (or rehydrates a pinned registry
generation), scores every held-out user in one batched ``batch_predict``
pass, and reports vectorized ranking metrics plus the standing
scan-vs-mips retrieval guard. See docs/evaluation.md.
"""

from predictionio_tpu.eval.metrics import (
    DEFAULT_METRICS,
    METRIC_CATALOG,
    ranking_metrics,
    relevance_matrix,
    select_metrics,
)
from predictionio_tpu.eval.replay import run_replay_eval
from predictionio_tpu.eval.split import (
    ReplayFold,
    SplitBounds,
    SplitSpec,
    parse_split_time,
    resolve_split_seconds,
    split_interactions,
)

__all__ = [
    "DEFAULT_METRICS",
    "METRIC_CATALOG",
    "ReplayFold",
    "SplitBounds",
    "SplitSpec",
    "parse_split_time",
    "ranking_metrics",
    "relevance_matrix",
    "resolve_split_seconds",
    "run_replay_eval",
    "select_metrics",
    "split_interactions",
]
