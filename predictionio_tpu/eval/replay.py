"""Offline replay evaluation: the `pio eval --replay` core.

Replays a time-bounded event prefix through the DASE hooks: the
datasource's ``read_replay`` cuts the timeline (train ``< t``, holdout
``>= t`` -- ``eval.split``), the algorithm trains on the prefix (or a
pinned registry generation is rehydrated instead), EVERY held-out user
is scored through the template's vectorized ``batch_predict`` in one
pass, and the ranked lists reduce to hit-rate@k / NDCG@k / MRR /
recall@k (``eval.metrics``). Seen-filtering matches live serving
semantics: the fold's training data carries the ``eval_fold`` flag, so
templates downgrade live event-store filtering to the trained-in map
exactly as the k-fold evaluator does (a live read would see the held-out
events themselves and score every actual item -inf).

The report also carries the standing retrieval guard PR 16 queued: the
scan and mips arms re-rank the same split with the same model, reporting
shortlist recall@k and the response byte-identity rate -- the accuracy
trip-wire for every future speed PR.
"""

from __future__ import annotations

import json
import logging
from typing import Any

from predictionio_tpu.eval.metrics import ranking_metrics, select_metrics
from predictionio_tpu.eval.split import ReplayFold, SplitSpec

logger = logging.getLogger("pio.eval")


def _serve_all(engine, engine_params, algorithms, models, pairs):
    """One batched pass: every algorithm's ``batch_predict`` over the
    whole holdout, combined per query by the engine's Serving component
    (the exact live /queries.json combination step)."""
    serving = engine.serving(engine_params)
    indexed = [(qid, q) for qid, (q, _) in enumerate(pairs)]
    per_algo = [
        dict(a.batch_predict(m, indexed)) for a, m in zip(algorithms, models)
    ]
    return [
        serving.serve(q, [pa[qid] for pa in per_algo])
        for qid, (q, _) in enumerate(pairs)
    ]


def _ranked_ids(response: Any, k: int) -> list[str]:
    """A served response -> its ranked item ids (responses lacking
    ``itemScores`` rank nothing, i.e. score as a total miss)."""
    if not isinstance(response, dict):
        return []
    return [s["item"] for s in response.get("itemScores") or []][:k]


def _load_registry_models(engine, variant, ctx, model_version, registry_dir):
    """Rehydrate a pinned registry generation -- the `pio deploy
    --model-version` resolution path, so eval lineage names the exact
    bytes a rollback would serve. Raises ``RegistryError`` verbatim on a
    missing/GC'd/corrupt version."""
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.online.registry import ModelRegistry
    from predictionio_tpu.workflow.core_workflow import (
        engine_params_from_instance,
        resolve_engine_instance,
    )

    registry = ModelRegistry.for_variant(variant, registry_dir=registry_dir)
    entry = registry.get(int(model_version))
    blob = entry.load_blob()  # CRC-verified
    params_obj = entry.engine_params_obj
    engine_params = (
        EngineParams.from_json_obj(params_obj)
        if params_obj
        else engine_params_from_instance(
            resolve_engine_instance(variant, entry.instance_id or None)
        )
    )
    models = engine.prepare_deploy(
        ctx, engine_params, entry.instance_id or "", blob
    )
    lineage = {
        "source": "registry",
        "model_version": entry.version,
        "registry_source": entry.source,
        "instance_id": entry.instance_id or None,
        "registry_dir": registry.dir,
    }
    return engine_params, models, lineage


def _retrieval_guard(engine, engine_params, models, pairs, k) -> dict | None:
    """Scan-vs-mips A/B on the SAME model and split: shortlist recall@k
    (overlap of the mips top-k with the scan top-k) and the response
    byte-identity rate. None when the primary algorithm has no
    retrieval surface (e.g. NCF's jitted MLP scorer)."""
    algo_name, algo_params = engine_params.algorithm_params_list[0]
    algo_cls = engine.algorithm_class_map.get(algo_name)
    if algo_cls is None or not hasattr(algo_cls, "_retrieval"):
        return None
    arms = {}
    for mode in ("scan", "mips"):
        params = dict(algo_params)
        retrieval = dict(params.get("retrieval") or {})
        retrieval["mode"] = mode
        params["retrieval"] = retrieval
        arm_algo = algo_cls(params)
        indexed = [(qid, q) for qid, (q, _) in enumerate(pairs)]
        arms[mode] = dict(arm_algo.batch_predict(models[0], indexed))
        if mode == "mips":
            shortlist = int(arm_algo._retrieval.shortlist)
    overlaps, identical, compared = [], 0, 0
    for qid in range(len(pairs)):
        scan_ids = _ranked_ids(arms["scan"][qid], k)
        mips_ids = _ranked_ids(arms["mips"][qid], k)
        if not scan_ids:
            continue  # nothing to retrieve for this user in either arm
        compared += 1
        overlaps.append(len(set(scan_ids) & set(mips_ids)) / len(scan_ids))
        if json.dumps(arms["scan"][qid], sort_keys=True) == json.dumps(
            arms["mips"][qid], sort_keys=True
        ):
            identical += 1
    return {
        f"shortlist_recall_at_{k}": (
            round(sum(overlaps) / len(overlaps), 6) if overlaps else None
        ),
        "response_identity_rate": (
            round(identical / compared, 6) if compared else None
        ),
        "users_compared": compared,
        "shortlist": shortlist,
    }


def run_replay_eval(
    variant,
    *,
    split_time: str | None = None,
    split_frac: float | None = None,
    k: int = 10,
    metrics=None,
    model_version: int | None = None,
    registry_dir: str | None = None,
    retrieval_guard: bool = True,
    engine=None,
    include_responses: bool = False,
) -> dict:
    """Run one replay evaluation; returns the JSON-able report.

    Without ``model_version`` the algorithm trains on the prefix
    in-process (no instance row, no model blob -- evaluation owns no
    persistence side effects); with it, the pinned registry generation
    is rehydrated and scored against the same holdout, and the report's
    lineage block names the manifest it came from.

    Raises ``ValueError`` (bad spec / unknown metric / empty prefix),
    ``NotImplementedError`` (datasource without ``read_replay``), or
    ``online.registry.RegistryError`` (missing/corrupt pinned version);
    the CLI maps each onto the exit-2 contract.
    """
    from predictionio_tpu.workflow.context import RuntimeContext
    from predictionio_tpu.workflow.json_extractor import build_engine

    names = select_metrics(metrics)
    if split_time is None and split_frac is None:
        split_frac = 0.8
    spec = SplitSpec(split_time=split_time, split_frac=split_frac, k=int(k))
    spec.validate()
    engine = engine or build_engine(variant)
    engine_params = variant.engine_params
    ctx = RuntimeContext(variant.runtime_conf)

    data_source = engine.data_source_class(engine_params.data_source_params)
    fold: ReplayFold = data_source.read_replay(ctx, spec)
    pairs = fold.pairs

    if model_version is not None:
        engine_params, models, lineage = _load_registry_models(
            engine, variant, ctx, model_version, registry_dir
        )
        algorithms = engine._algorithms(engine_params)
    else:
        engine._maybe_sanity_check("replay training data", fold.train_data, False)
        preparator = engine.preparator_class(engine_params.preparator_params)
        prepared = preparator.prepare(ctx, fold.train_data)
        algorithms = engine._algorithms(engine_params)
        models = [a.train(ctx, prepared) for a in algorithms]
        lineage = {"source": "replay-train", "model_version": None,
                   "instance_id": None}

    responses = _serve_all(engine, engine_params, algorithms, models, pairs)
    predicted = [_ranked_ids(r, spec.k) for r in responses]
    actual = [a for _, a in pairs]
    values = ranking_metrics(predicted, actual, spec.k, names)

    guard = None
    if retrieval_guard:
        guard = _retrieval_guard(engine, engine_params, models, pairs, spec.k)

    def _key(name: str) -> str:
        return "mrr" if name == "mrr" else f"{name}_at_{spec.k}"

    report = {
        "engine": variant.variant_id,
        "engine_variant": variant.path,
        "k": spec.k,
        "metrics": {
            _key(n): (round(v, 6) if v is not None else None)
            for n, v in values.items()
        },
        "split": fold.bounds.to_json_obj() if fold.bounds else None,
        "model": lineage,
        "retrieval_guard": guard,
    }
    if include_responses:
        report["responses"] = responses
        report["actual"] = [list(map(str, a)) for a in actual]
        report["queries"] = [q for q, _ in pairs]
    return report
