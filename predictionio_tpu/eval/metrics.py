"""Vectorized ranking metrics over one batched prediction pass.

The scoring pass is the templates' ``batch_predict`` (one device/matmul
pass over every held-out user); this module reduces its ranked lists to
hit-rate@k / NDCG@k / MRR / recall@k in a handful of whole-array numpy
ops -- there is no per-user python scoring loop anywhere in the replay
path. All accumulation is float64, and ``tests/test_eval.py`` pins the
results to a plain per-user oracle at 1e-9.

Ids are opaque strings (predicted lists come straight out of
``itemScores``), encoded on the fly so metrics work identically for
in-process-trained models and pinned registry generations whose item
vocabulary differs from the live store's.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import numpy as np

#: metric name -> definition, the ``pio eval`` catalog (printed on an
#: unknown-metric error, the ``pio check --rules`` exit-2 contract)
METRIC_CATALOG: Mapping[str, str] = {
    "hit_rate": "fraction of held-out users with >=1 held-out item in"
                " their top-k",
    "ndcg": "normalized discounted cumulative gain@k (binary relevance,"
            " log2 position discount, ideal = all holdouts up front)",
    "mrr": "mean reciprocal rank of each user's FIRST held-out hit"
           " (0 when the top-k misses entirely)",
    "recall": "mean fraction of each user's held-out items recovered in"
              " the top-k",
}

DEFAULT_METRICS: tuple[str, ...] = tuple(METRIC_CATALOG)


def select_metrics(names: Iterable[str] | str | None = None) -> tuple[str, ...]:
    """Validate a metric selection against the catalog.

    Accepts a comma-separated string or an iterable; None/empty selects
    everything. Unknown names raise ``ValueError`` carrying the full
    catalog -- the CLI surfaces it verbatim and exits 2.
    """
    if names is None:
        return DEFAULT_METRICS
    if isinstance(names, str):
        names = [n.strip() for n in names.split(",") if n.strip()]
    wanted = [str(n).lower() for n in names]
    if not wanted:
        return DEFAULT_METRICS
    unknown = sorted(set(wanted) - set(METRIC_CATALOG))
    if unknown:
        raise ValueError(
            f"unknown metric(s): {unknown} (known: {sorted(METRIC_CATALOG)})"
        )
    # catalog order, deduplicated -- reports stay stably keyed
    seen = set(wanted)
    return tuple(n for n in METRIC_CATALOG if n in seen)


def _encode(
    predicted: Sequence[Sequence], actual: Sequence[Iterable], k: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """(ranked [U, k] codes with -1 padding, per-user holdout sizes,
    sorted (user, item) pair codes of the holdout sets, code width)."""
    codes: dict = {}
    u_count = len(predicted)
    ranked = np.full((u_count, k), -1, np.int64)
    for u, row in enumerate(predicted):
        for j, item in enumerate(row[:k]):
            c = codes.get(item)
            if c is None:
                c = len(codes)
                codes[item] = c
            ranked[u, j] = c
    n_actual = np.zeros(u_count, np.int64)
    pair_rows, pair_cols = [], []
    for u, row in enumerate(actual):
        uniq = set(row)
        n_actual[u] = len(uniq)
        for item in uniq:
            c = codes.get(item)
            if c is None:
                c = len(codes)
                codes[item] = c
            pair_rows.append(u)
            pair_cols.append(c)
    width = max(len(codes), 1)
    pairs = (
        np.asarray(pair_rows, np.int64) * width
        + np.asarray(pair_cols, np.int64)
        if pair_rows else np.empty(0, np.int64)
    )
    pairs.sort()
    return ranked, n_actual, pairs, width


def relevance_matrix(
    predicted: Sequence[Sequence], actual: Sequence[Iterable], k: int
) -> tuple[np.ndarray, np.ndarray]:
    """(rel [U, k] bool, n_actual [U]): whether each ranked slot is one
    of its user's held-out items -- ONE searchsorted over the whole
    batch, the membership kernel every metric reduces."""
    ranked, n_actual, pairs, width = _encode(predicted, actual, k)
    flat = np.arange(len(predicted), dtype=np.int64)[:, None] * width + ranked
    pos = np.searchsorted(pairs, flat.ravel())
    pos = np.minimum(pos, max(pairs.size - 1, 0))
    hit = (
        pairs[pos] == flat.ravel()
        if pairs.size else np.zeros(flat.size, bool)
    )
    rel = hit.reshape(ranked.shape) & (ranked >= 0)
    return rel, n_actual


def ranking_metrics(
    predicted: Sequence[Sequence],
    actual: Sequence[Iterable],
    k: int,
    metrics: Iterable[str] | str | None = None,
) -> dict[str, float | None]:
    """Selected metrics over one batch of ranked lists.

    ``predicted[u]`` is user ``u``'s ranked item ids (best first, may be
    shorter than ``k``); ``actual[u]`` their held-out ids. An empty batch
    returns every metric as None (the empty-holdout report stays honest
    instead of inventing zeros).
    """
    names = select_metrics(metrics)
    if len(predicted) != len(actual):
        raise ValueError(
            f"predicted ({len(predicted)}) and actual ({len(actual)})"
            " user counts differ"
        )
    if not predicted:
        return {name: None for name in names}
    rel, n_actual = relevance_matrix(predicted, actual, k)
    hits = rel.sum(axis=1)
    out: dict[str, float | None] = {}
    if "hit_rate" in names:
        out["hit_rate"] = float((hits > 0).mean())
    if "ndcg" in names:
        discount = 1.0 / np.log2(np.arange(k, dtype=np.float64) + 2.0)
        dcg = (rel * discount).sum(axis=1)
        ideal_cum = np.concatenate([[0.0], np.cumsum(discount)])
        idcg = ideal_cum[np.minimum(n_actual, k)]
        out["ndcg"] = float(
            np.where(idcg > 0, dcg / np.maximum(idcg, 1e-300), 0.0).mean()
        )
    if "mrr" in names:
        first = np.argmax(rel, axis=1)  # 0 when no hit; masked below
        out["mrr"] = float(
            np.where(hits > 0, 1.0 / (first + 1.0), 0.0).mean()
        )
    if "recall" in names:
        out["recall"] = float(
            np.where(n_actual > 0, hits / np.maximum(n_actual, 1), 0.0).mean()
        )
    return {name: out[name] for name in names}
