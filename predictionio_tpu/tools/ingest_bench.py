"""Ingestion A/B: per-request sync commits vs WAL + group-commit pipeline.

Usage::

    python -m predictionio_tpu.tools.ingest_bench [--clients 32] [--events 50]

Two measured phases against a fresh file-backed sqlite store, plus a
kill-and-replay durability cycle:

- **sync**  -- N client threads, each ``POST``-shaped insert paying one
  storage transaction on the request thread (the pre-pipeline Event Server
  behavior);
- **wal**   -- the same load through :class:`IngestPipeline`: requests park
  on the queue, one WAL fsync + one ``executemany`` transaction per group
  commit;
- **crash** -- a subprocess ingests through the pipeline (fsync=always)
  while logging every acknowledged eventId, is SIGKILLed mid-stream, and
  the parent replays the WAL tail and asserts zero lost / zero duplicated
  acknowledged events (run twice to prove replay idempotence).

``--wal-partitions`` takes either one value (the WAL phase and crash
cycle run at that partition count) or a comma list (``1,2,4,8``), which
switches to a sweep: the same group-commit load is re-driven at each
partition count and the report shows eps per P plus scaling vs P=1. The
partitioned crash cycle additionally audits that every surviving WAL
frame lives in the partition its entity hashes to (zero cross-partition
routing drift) and that each partition's second replay is a no-op.

Load is driven at the ``EventService`` layer (``_insert_one``), not over
HTTP: this box's HTTP envelope saturates around a few hundred req/s and
would mask the storage-commit effect under test (``serving_bench`` owns
the HTTP-envelope A/B). Both phases pay identical validation/serde costs.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

from predictionio_tpu.data import storage as storage_registry
from predictionio_tpu.data.storage.base import AccessKey

APP_ID = 1


def _event_obj(client_id: int, i: int) -> dict:
    return {
        "event": "view",
        "entityType": "user",
        "entityId": f"u{client_id}",
        "targetEntityType": "item",
        "targetEntityId": f"i{(client_id * 7919 + i) % 1000}",
        "properties": {"rating": (i % 5) + 1},
    }


#: sqlite synchronous pragma for the default PIO_SQLITE source
_SYNC_VAR = "PIO_STORAGE_SOURCES_PIO_SQLITE_SYNCHRONOUS"


class _Env:
    """Point the storage registry at a private basedir (optionally pinning
    the sqlite synchronous pragma); restore on exit."""

    def __init__(self, basedir: str, synchronous: str | None = None):
        self.env = {"PIO_FS_BASEDIR": basedir}
        if synchronous is not None:
            self.env[_SYNC_VAR] = synchronous

    def __enter__(self):
        self._saved = {k: os.environ.get(k) for k in (*self.env, _SYNC_VAR)}
        os.environ.pop(_SYNC_VAR, None)
        os.environ.update(self.env)
        storage_registry.reset()
        return self

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        storage_registry.reset()


def _drive(service, clients: int, events_per_client: int) -> dict:
    """Fan ``clients`` threads into ``service._insert_one``; returns eps."""
    record = AccessKey(key="bench", app_id=APP_ID)
    barrier = threading.Barrier(clients + 1)
    failures: list[int] = []

    def worker(cid: int) -> None:
        barrier.wait()
        for i in range(events_per_client):
            status, _ = service._insert_one(_event_obj(cid, i), record, None)
            if status != 201:
                failures.append(status)

    threads = [
        threading.Thread(target=worker, args=(c,), daemon=True)
        for c in range(clients)
    ]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    seconds = time.perf_counter() - t0
    total = clients * events_per_client
    return {
        "seconds": round(seconds, 3),
        "eps": round(total / seconds, 1),
        "failures": len(failures),
    }


def _stored_count() -> int:
    return sum(1 for _ in storage_registry.get_l_events().find(app_id=APP_ID, limit=None))


def run_ab(
    clients: int = 32,
    events_per_client: int = 50,
    group_commit_ms: float = 5.0,
    fsync_policy: str = "always",
    crash_events: int = 200,
    workdir: str | None = None,
    wal_partitions: int = 1,
) -> dict:
    from predictionio_tpu.data.api.eventserver import EventService
    from predictionio_tpu.data.ingest import IngestConfig

    report: dict = {
        "clients": clients,
        "events_per_client": events_per_client,
        "group_commit_ms": group_commit_ms,
        "fsync_policy": fsync_policy,
        "wal_partitions": wal_partitions,
    }
    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="pio_ingest_bench_")

    # -- A: per-request sync commits, durability-matched (every commit
    # fsyncs, like the WAL phase's acks). This is THE baseline: sqlite's
    # default synchronous=NORMAL never fsyncs under a WAL journal, i.e. the
    # pre-pipeline ingest path was not actually durable per request.
    with _Env(os.path.join(workdir, "sync"), synchronous="FULL"):
        storage_registry.get_l_events().init_channel(APP_ID)
        service = EventService()
        report["sync"] = _drive(service, clients, events_per_client)
        report["sync"]["stored"] = _stored_count()

    # -- A': the non-durable sync reference (what the server shipped with)
    with _Env(os.path.join(workdir, "sync_fast")):
        storage_registry.get_l_events().init_channel(APP_ID)
        service = EventService()
        report["sync_nondurable"] = _drive(service, clients, events_per_client)

    # -- B: WAL + group commit ------------------------------------------------
    with _Env(os.path.join(workdir, "wal")):
        storage_registry.get_l_events().init_channel(APP_ID)
        service = EventService(
            ingest_config=IngestConfig(
                mode="wal",
                group_commit_ms=group_commit_ms,
                fsync_policy=fsync_policy,
                wal_partitions=wal_partitions,
            )
        )
        try:
            report["wal"] = _drive(service, clients, events_per_client)
        finally:
            service.shutdown_ingest()
        report["wal"]["stored"] = _stored_count()

    report["speedup"] = (
        round(report["wal"]["eps"] / report["sync"]["eps"], 2)
        if report["sync"]["eps"]
        else None
    )
    report["speedup_vs_nondurable_sync"] = (
        round(report["wal"]["eps"] / report["sync_nondurable"]["eps"], 2)
        if report["sync_nondurable"]["eps"]
        else None
    )

    # -- C: kill-and-replay durability cycle ----------------------------------
    if crash_events:
        report["crash_cycle"] = run_crash_cycle(
            os.path.join(workdir, "crash"),
            min_acked=crash_events,
            partitions=wal_partitions,
        )
    if own_tmp:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return report


def run_sweep(
    partitions: tuple[int, ...] = (1, 2, 4, 8),
    clients: int = 32,
    events_per_client: int = 50,
    group_commit_ms: float = 5.0,
    fsync_policy: str = "always",
    crash_partitions: int | None = None,
    crash_events: int = 200,
    workdir: str | None = None,
) -> dict:
    """Drive the SAME group-commit load at each partition count and report
    eps per P. Only the WAL arm runs (the sync baselines don't change with
    P); ``crash_partitions`` optionally tacks on one kill-and-replay cycle
    at that partition count."""
    from predictionio_tpu.data.api.eventserver import EventService
    from predictionio_tpu.data.ingest import IngestConfig

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="pio_ingest_sweep_")
    report: dict = {
        "clients": clients,
        "events_per_client": events_per_client,
        "group_commit_ms": group_commit_ms,
        "fsync_policy": fsync_policy,
        "partitions": {},
    }
    for p in partitions:
        with _Env(os.path.join(workdir, f"p{p}")):
            storage_registry.get_l_events().init_channel(APP_ID)
            service = EventService(
                ingest_config=IngestConfig(
                    mode="wal",
                    group_commit_ms=group_commit_ms,
                    fsync_policy=fsync_policy,
                    wal_partitions=p,
                )
            )
            try:
                arm = _drive(service, clients, events_per_client)
            finally:
                service.shutdown_ingest()
            arm["stored"] = _stored_count()
            report["partitions"][str(p)] = arm
    base = report["partitions"][str(partitions[0])]["eps"]
    for p in partitions:
        arm = report["partitions"][str(p)]
        arm["scaling_vs_first"] = round(arm["eps"] / base, 2) if base else None
    eps_seq = [report["partitions"][str(p)]["eps"] for p in sorted(partitions)]
    # 10% jitter allowance: two cores + sqlite make exact monotonicity noisy
    report["monotonic"] = all(
        b >= a * 0.9 for a, b in zip(eps_seq, eps_seq[1:])
    )
    if crash_partitions:
        report["crash_cycle"] = run_crash_cycle(
            os.path.join(workdir, "crash"),
            min_acked=crash_events,
            partitions=crash_partitions,
        )
    if own_tmp:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return report


# -- crash cycle --------------------------------------------------------------

def _crash_child(workdir: str, partitions: int = 1) -> None:
    """Ingest forever through the pipeline (fsync=always), logging each
    acknowledged eventId; the parent SIGKILLs us mid-stream."""
    from predictionio_tpu.data.ingest import PartitionedIngestPipeline
    from predictionio_tpu.data.wal import PartitionedWal
    from predictionio_tpu.data.event import Event

    os.environ["PIO_FS_BASEDIR"] = workdir
    storage_registry.reset()
    l_events = storage_registry.get_l_events()
    l_events.init_channel(APP_ID)

    real = l_events

    class _SlowEvents:
        """Widen the acked-but-not-yet-stored window so the SIGKILL
        reliably catches records whose only copy is the WAL."""

        def insert_batch(self, items, on_duplicate="error"):
            time.sleep(0.02)
            return real.insert_batch(items, on_duplicate=on_duplicate)

    wal = PartitionedWal(
        os.path.join(workdir, "wal"),
        partitions=partitions,
        fsync_policy="always",
    )
    pipeline = PartitionedIngestPipeline(
        wal, l_events=lambda: _SlowEvents(), group_commit_ms=2.0
    ).start()
    # spread entities so every partition takes writes (P=1 keeps the
    # original single-entity stream)
    entity_span = 1 if partitions <= 1 else 4 * partitions
    acked = open(os.path.join(workdir, "acked.txt"), "w", buffering=1)
    i = 0
    while True:  # until SIGKILL
        futs = []
        for _ in range(16):
            ev = Event.from_json_obj(_event_obj(i % entity_span, i))
            futs.append(pipeline.submit(ev, APP_ID, None))
            i += 1
        for f in futs:
            acked.write(f.result(timeout=30) + "\n")


def run_crash_cycle(
    workdir: str,
    min_acked: int = 200,
    timeout_s: float = 60.0,
    partitions: int = 1,
) -> dict:
    """SIGKILL a pipeline mid-ingest, replay the WAL, prove exactly-once
    (per partition when ``partitions`` > 1, with a routing audit on the
    surviving frames)."""
    os.makedirs(workdir, exist_ok=True)
    env = dict(os.environ)
    env["PIO_FS_BASEDIR"] = workdir
    proc = subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.tools.ingest_bench",
         "--crash-child", workdir, "--crash-partitions", str(partitions)],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )
    acked_path = os.path.join(workdir, "acked.txt")
    deadline = time.time() + timeout_s
    try:
        while time.time() < deadline:
            try:
                with open(acked_path) as f:
                    if sum(1 for _ in f) >= min_acked:
                        break
            except OSError:
                pass
            if proc.poll() is not None:
                raise RuntimeError(
                    f"crash child exited early rc={proc.returncode}:"
                    f" {(proc.stderr.read() or '')[-800:]}"
                )
            time.sleep(0.02)
        else:
            raise RuntimeError(f"crash child acked < {min_acked} in {timeout_s}s")
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        proc.wait()

    # the acked log's last line can be torn by the kill; count only full lines
    with open(acked_path) as f:
        data = f.read()
    acked_ids = [line for line in data.split("\n")[:-1] if line]

    from predictionio_tpu.data import wal as wal_mod
    from predictionio_tpu.data.ingest import (
        partition_of,
        replay_wal_into_storage,
        wal_parse,
    )
    from predictionio_tpu.data.wal import PartitionedWal

    with _Env(workdir):
        stored_before = _stored_count()
        wal = PartitionedWal(
            os.path.join(workdir, "wal"),
            partitions=partitions,
            fsync_policy="never",
        )
        per_part = [replay_wal_into_storage(p) for p in wal.parts]
        replayed = sum(per_part)
        stored_after = _stored_count()
        # second replay cycle (a second "restart") must change nothing,
        # independently in every partition
        per_part_again = [replay_wal_into_storage(p) for p in wal.parts]
        replayed_again = sum(per_part_again)
        # routing audit: a frame in partition k must hash to k -- any
        # miss means the router and the on-disk layout drifted apart
        misrouted = 0
        for k, part in enumerate(wal.parts):
            for _seqno, payload in wal_mod.iter_log_records(part.directory):
                event, _app, _chan, _trace = wal_parse(payload)
                if partition_of(event, wal.partitions) != k:
                    misrouted += 1
        wal.close()
        stored_ids = [
            e.event_id
            for e in storage_registry.get_l_events().find(app_id=APP_ID, limit=None)
        ]
    stored_set = set(stored_ids)
    lost = [i for i in acked_ids if i not in stored_set]
    return {
        "partitions": partitions,
        "acked": len(acked_ids),
        "stored_before_replay": stored_before,
        "replayed": replayed,
        "replayed_per_partition": per_part,
        "stored_after_replay": stored_after,
        "lost": len(lost),
        "duplicated": len(stored_ids) - len(stored_set),
        "misrouted": misrouted,
        "second_replay_records": replayed_again,
        "second_replay_delta": len(stored_ids) - stored_after,
        "exactly_once": not lost
        and len(stored_ids) == len(stored_set)
        and replayed_again == 0
        and misrouted == 0,
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--clients", type=int, default=32)
    parser.add_argument("--events", type=int, default=50, help="per client")
    parser.add_argument("--group-commit-ms", type=float, default=5.0)
    parser.add_argument("--fsync-policy", default="always",
                        choices=("always", "interval", "never"))
    parser.add_argument("--crash-events", type=int, default=200,
                        help="min acked events before the kill (0 disables)")
    parser.add_argument("--wal-partitions", default="1", metavar="P[,P...]",
                        help="WAL partition count; a comma list (1,2,4,8)"
                        " runs the partition sweep instead of the full A/B")
    parser.add_argument("--crash-child", metavar="DIR", default=None,
                        help=argparse.SUPPRESS)
    parser.add_argument("--crash-partitions", type=int, default=1,
                        help=argparse.SUPPRESS)
    args = parser.parse_args(argv)
    if args.crash_child:
        _crash_child(args.crash_child, partitions=args.crash_partitions)
        return 0
    part_list = [int(p) for p in str(args.wal_partitions).split(",") if p]
    if len(part_list) > 1:
        report = run_sweep(
            partitions=tuple(part_list),
            clients=args.clients,
            events_per_client=args.events,
            group_commit_ms=args.group_commit_ms,
            fsync_policy=args.fsync_policy,
            crash_partitions=max(part_list) if args.crash_events else None,
            crash_events=args.crash_events,
        )
    else:
        report = run_ab(
            clients=args.clients,
            events_per_client=args.events,
            group_commit_ms=args.group_commit_ms,
            fsync_policy=args.fsync_policy,
            crash_events=args.crash_events,
            wal_partitions=part_list[0] if part_list else 1,
        )
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
