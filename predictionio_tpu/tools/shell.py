"""``pio shell``: interactive console with the runtime preloaded.

Parity role of ``bin/pio-shell`` + ``python/pypio`` (SURVEY.md section 2.4
#33, 2.5 #35): where the reference drops into spark-shell/pyspark with pio
on the classpath and pypio exposing ``init/find_events/save_model``, this
opens IPython (or code.interact) with the storage registry, event stores,
and workflow API bound -- and a ``pypio``-shaped helper object.
"""

from __future__ import annotations


class PypioCompat:
    """pypio-shaped convenience API (reference: pypio.pypio, v0.13+)."""

    def init(self):
        from predictionio_tpu.data import storage

        failures = storage.verify_all_data_objects()
        if failures:
            raise RuntimeError(
                "storage verification failed: " + "; ".join(failures)
            )
        return self

    def find_events(self, app_name: str):
        """All events of an app as a pandas DataFrame (DataFrame parity)."""
        import pandas as pd

        from predictionio_tpu.data.store import PEventStore

        return pd.DataFrame([e.to_json_obj() for e in PEventStore.find(app_name)])

    def save_model(self, model_id: str, blob: bytes):
        from predictionio_tpu.data import storage
        from predictionio_tpu.data.storage.base import Model

        storage.get_model_data_models().insert(Model(id=model_id, models=blob))
        return model_id


def run_shell() -> int:
    from predictionio_tpu.data import storage
    from predictionio_tpu.data.store import LEventStore, PEventStore
    from predictionio_tpu.workflow.context import RuntimeContext
    from predictionio_tpu.workflow.json_extractor import load_engine_variant

    namespace = {
        "storage": storage,
        "LEventStore": LEventStore,
        "PEventStore": PEventStore,
        "RuntimeContext": RuntimeContext,
        "load_engine_variant": load_engine_variant,
        "pypio": PypioCompat(),
    }
    banner = (
        "predictionio_tpu shell -- preloaded: storage, LEventStore, PEventStore,\n"
        "RuntimeContext, load_engine_variant, pypio (init/find_events/save_model)"
    )
    print(banner)
    try:
        from IPython import start_ipython

        start_ipython(argv=["--no-banner"], user_ns=namespace)
    except ImportError:
        import code

        code.interact(banner="", local=namespace)
    return 0
