"""``pio shell``: interactive console with the runtime preloaded.

Parity role of ``bin/pio-shell`` + ``python/pypio`` (SURVEY.md section 2.4
#33, 2.5 #35): where the reference drops into spark-shell/pyspark with pio
on the classpath and pypio exposing ``init/find_events/save_model``, this
opens IPython (or code.interact) with the storage registry, event stores,
and workflow API bound -- and a ``pypio``-shaped helper object.
"""

from __future__ import annotations


def run_shell() -> int:
    from predictionio_tpu import pypio
    from predictionio_tpu.data import storage
    from predictionio_tpu.data.store import LEventStore, PEventStore
    from predictionio_tpu.workflow.context import RuntimeContext
    from predictionio_tpu.workflow.json_extractor import load_engine_variant

    namespace = {
        "storage": storage,
        "LEventStore": LEventStore,
        "PEventStore": PEventStore,
        "RuntimeContext": RuntimeContext,
        "load_engine_variant": load_engine_variant,
        "pypio": pypio,
    }
    banner = (
        "predictionio_tpu shell -- preloaded: storage, LEventStore, PEventStore,\n"
        "RuntimeContext, load_engine_variant, pypio (init/find_events/save_model)"
    )
    print(banner)
    try:
        from IPython import start_ipython

        start_ipython(argv=["--no-banner"], user_ns=namespace)
    except ImportError:
        import code

        code.interact(banner="", local=namespace)
    return 0
