"""Shared app lifecycle operations used by both the CLI and the admin REST
server (single copy of the create/delete cascades)."""

from __future__ import annotations

from predictionio_tpu.data import storage
from predictionio_tpu.data.storage.base import AccessKey, App


def create_app(name: str, description: str = "", access_key: str = "") -> tuple[App, str]:
    """Create app + default channel + access key. Raises ValueError if the
    name is taken."""
    apps = storage.get_meta_data_apps()
    if apps.get_by_name(name) is not None:
        raise ValueError(f"app {name!r} already exists")
    app = App(name=name, description=description)
    apps.insert(app)
    storage.get_l_events().init_channel(app.id)
    key = storage.get_meta_data_access_keys().insert(
        AccessKey(key=access_key, app_id=app.id)
    )
    return app, key


def delete_app_cascade(app: App) -> None:
    """Full teardown: channel events + channel meta + default-channel events
    + access keys + the app record."""
    le = storage.get_l_events()
    channels = storage.get_meta_data_channels()
    for ch in channels.get_by_app(app.id):
        le.remove_channel(app.id, ch.id)
        channels.delete(ch.id)
    le.remove_channel(app.id)
    keys = storage.get_meta_data_access_keys()
    for ak in keys.get_by_app_id(app.id):
        keys.delete(ak.key)
    storage.get_meta_data_apps().delete(app.id)


def delete_app_data(
    app: App, channel_name: str | None = None, all_channels: bool = False
) -> None:
    """Wipe event data. Default channel only unless ``channel_name`` (one
    named channel) or ``all_channels`` (default + every named channel).
    Raises LookupError for an unknown channel name."""
    le = storage.get_l_events()
    channels = storage.get_meta_data_channels()
    if channel_name:
        match = [c for c in channels.get_by_app(app.id) if c.name == channel_name]
        if not match:
            raise LookupError(f"channel {channel_name!r} does not exist")
        le.remove_channel(app.id, match[0].id)
        le.init_channel(app.id, match[0].id)
        return
    le.remove_channel(app.id)
    le.init_channel(app.id)
    if all_channels:
        for ch in channels.get_by_app(app.id):
            le.remove_channel(app.id, ch.id)
            le.init_channel(app.id, ch.id)
