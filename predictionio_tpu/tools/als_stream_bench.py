"""A/B + scaling bench for device-resident streamed ALS epochs.

Two entry points:

- :func:`run_ab` -- resident (``build_als_data`` + ``als_fit``) vs
  streamed (``parallel.stream`` block store + ``als_fit_streamed``) at an
  equal sub-20M shape: edges/sec per arm, factor identity/equivalence,
  and the transfer axis -- measured host->device bytes per half-step vs
  the stream model vs the re-ship baseline (both sides' CSR + both factor
  tables per half-step, the structure a non-resident epoch pays). Wired
  into ``bench.py`` as secondary metric #14 ``als_stream``
  (``PIO_BENCH_ALS_FEED=resident|streamed`` pins one arm).

- :func:`run_scale` -- the >=20M-cap lift: a chunked synthetic generator
  (O(chunk) host memory, deterministic per-chunk seeds) feeds the block
  store and one streamed epoch runs at any edge count that fits on DISK,
  not in RAM. Reports edges/sec, peak RSS, and the measured transfer
  ratio. ``python -m predictionio_tpu.tools.als_stream_bench --edges
  100000000`` is the 100M-edge acceptance run; anything at that scale is
  kept OUT of tier-1 (the pytest ``slow`` marker on its test stand-in).

Synthetic distribution matches ``bench.py``'s ML-20M generator: uniform
users, zipf-ish item popularity, per-user history capped at 256.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import tempfile
import time

import numpy as np

RANK = 16


def chunked_synthetic_source(
    n_edges: int,
    n_users: int,
    n_items: int,
    seed: int = 0,
    chunk_rows: int = 1 << 20,
    implicit: bool = True,
):
    """Deterministic ``ChunkSource`` over the bench's synthetic
    distribution. Each chunk draws from its own per-index stream, so any
    edge count generates with O(chunk) host memory and two passes see the
    identical stream. ``implicit`` emits all-ones values (the uniform
    stream that triggers the block store's value elision); otherwise 1..5
    ratings ride along."""

    def source():
        for lo in range(0, n_edges, chunk_rows):
            n = min(chunk_rows, n_edges - lo)
            rng = np.random.default_rng((seed << 20) + lo // chunk_rows)
            users = rng.integers(0, n_users, size=n, dtype=np.int64)
            items = (
                np.minimum(rng.random(n) ** 2.2, 0.999999) * n_items
            ).astype(np.int64)
            if implicit:
                vals = np.ones(n, np.float32)
            else:
                vals = rng.integers(1, 6, size=n).astype(np.float32)
            yield users, items, vals, None

    return source


def _materialize(source):
    us, its, vs = [], [], []
    for uu, ii, vv, _tt in source():
        us.append(uu)
        its.append(ii)
        vs.append(vv)
    return np.concatenate(us), np.concatenate(its), np.concatenate(vs)


def _sync(model) -> None:
    # als_fit/als_fit_streamed return HOST factors: the fetch is the sync
    float(model.user_factors[0, 0])


def _config(rank: int, iterations: int, implicit: bool, buckets: int,
            max_len: int):
    from predictionio_tpu.parallel.als import ALSConfig

    return ALSConfig(
        rank=rank, iterations=iterations, reg=0.05, alpha=10.0,
        implicit=implicit, max_len=max_len, buckets=buckets, solver="auto",
    )


def peak_rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def run_ab(
    edges: int = 1_500_000,
    users: int = 40_000,
    items: int = 8_000,
    rank: int = RANK,
    iterations: int = 3,
    implicit: bool = True,
    buckets: int = 2,
    max_len: int = 256,
    feed: str = "both",
    cache_dir: str | None = None,
    device_budget_bytes: int = 0,
) -> dict:
    """Equal-shape resident-vs-streamed A/B; see the module docstring."""
    from predictionio_tpu.parallel.als import (
        als_fit,
        als_fit_streamed,
        build_als_data,
    )
    from predictionio_tpu.parallel.mesh import local_mesh
    from predictionio_tpu.parallel.stream import (
        StreamStats,
        build_streamed_als_data,
        reship_bytes_per_half_step,
        stream_bytes_per_half_step,
    )

    source = chunked_synthetic_source(edges, users, items, implicit=implicit)
    cfg = _config(rank, iterations, implicit, buckets, max_len)
    mesh = local_mesh(1, 1)
    itemsize = 2 if cfg.dtype == "bfloat16" else 4
    rep: dict = {
        "edges": edges, "users": users, "items": items, "rank": rank,
        "iterations": iterations, "implicit": implicit, "feed": feed,
    }

    tmp_ctx = None
    if cache_dir is None:
        tmp_ctx = tempfile.TemporaryDirectory(prefix="pio-als-stream-")
        cache_dir = tmp_ctx.name
    try:
        resident_model = None
        if feed in ("both", "resident"):
            uu, ii, vv = _materialize(source)
            t0 = time.perf_counter()
            data = build_als_data(uu, ii, vv, users, items, cfg)
            build_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            resident_model = als_fit(data, cfg, mesh)
            _sync(resident_model)
            fit_s = time.perf_counter() - t0
            real = data.by_row.retained_edges or int(
                sum(b.mask.sum() for b in data.by_row.blocks)
            )
            rep["resident"] = {
                "build_seconds": round(build_s, 3),
                "fit_seconds": round(fit_s, 3),
                "sec_per_iter": round(fit_s / iterations, 4),
                "edges_per_sec": round(real * iterations / fit_s, 1),
                "reship_bytes_per_half_step": reship_bytes_per_half_step(
                    data, rank, itemsize
                ),
            }
            del uu, ii, vv

        if feed in ("both", "streamed"):
            t0 = time.perf_counter()
            sd = build_streamed_als_data(
                source, users, items, cfg, cache_dir
            )
            build_s = time.perf_counter() - t0
            stats = StreamStats()
            t0 = time.perf_counter()
            streamed_model = als_fit_streamed(
                sd, cfg, mesh, stats=stats,
                device_budget_bytes=device_budget_bytes,
            )
            _sync(streamed_model)
            fit_s = time.perf_counter() - t0
            reship = reship_bytes_per_half_step(sd, rank, itemsize)
            rep["streamed"] = {
                "build_seconds": round(build_s, 3),
                "fit_seconds": round(fit_s, 3),
                "sec_per_iter": round(fit_s / iterations, 4),
                "edges_per_sec": round(
                    sd.real_edges * iterations / fit_s, 1
                ),
                "h2d_bytes_per_half_step": stats.bytes_per_half_step,
                "h2d_modeled_bytes_per_half_step": stream_bytes_per_half_step(
                    sd, implicit
                ),
                "reship_bytes_per_half_step": reship,
                "reship_ratio": round(
                    reship / max(stats.bytes_per_half_step, 1.0), 2
                ),
                "blocks": len(sd.by_row.specs) + len(sd.by_col.specs),
                "blocks_pinned": stats.blocks_pinned,
                "max_inflight_blocks": stats.max_inflight_blocks,
            }
            if resident_model is not None:
                rep["factors_identical"] = bool(
                    np.array_equal(
                        resident_model.user_factors,
                        streamed_model.user_factors,
                    )
                    and np.array_equal(
                        resident_model.item_factors,
                        streamed_model.item_factors,
                    )
                )
                rep["factors_equivalent"] = bool(
                    np.allclose(
                        resident_model.user_factors,
                        streamed_model.user_factors,
                        atol=5e-4, rtol=1e-3,
                    )
                )
        if "resident" in rep and "streamed" in rep:
            rep["streamed_vs_resident_eps"] = round(
                rep["streamed"]["edges_per_sec"]
                / max(rep["resident"]["edges_per_sec"], 1e-9),
                3,
            )
    finally:
        if tmp_ctx is not None:
            tmp_ctx.cleanup()
    return rep


def run_scale(
    edges: int = 100_000_000,
    users: int | None = None,
    items: int | None = None,
    rank: int = RANK,
    iterations: int = 1,
    buckets: int = 4,
    max_len: int = 256,
    cache_dir: str | None = None,
    device_budget_bytes: int = 0,
    keep_cache: bool = False,
) -> dict:
    """One streamed epoch at ``edges`` scale (implicit all-ones synthetic,
    ML-20M-shaped entity ratios). Host memory stays O(block): the edge
    set exists only on disk, as spill then packed blocks."""
    from predictionio_tpu.parallel.als import als_fit_streamed
    from predictionio_tpu.parallel.mesh import local_mesh
    from predictionio_tpu.parallel.stream import (
        StreamStats,
        build_streamed_als_data,
        reship_bytes_per_half_step,
        stream_bytes_per_half_step,
    )

    # ML-20M entity ratios scaled with the edge count (the bench's
    # full-scale shape at 20M edges; sqrt scaling like bench.py)
    scale = max(edges / 20_000_000, 1e-9)
    users = users or int(138_000 * max(scale, 1) ** 0.5)
    items = items or int(27_000 * max(scale, 1) ** 0.5)
    cfg = _config(rank, iterations, True, buckets, max_len)
    source = chunked_synthetic_source(edges, users, items, implicit=True)

    tmp_ctx = None
    if cache_dir is None:
        tmp_ctx = tempfile.TemporaryDirectory(prefix="pio-als-scale-")
        cache_dir = tmp_ctx.name
    try:
        rss0 = peak_rss_mb()
        t0 = time.perf_counter()
        sd = build_streamed_als_data(source, users, items, cfg, cache_dir)
        build_s = time.perf_counter() - t0
        stats = StreamStats()
        mesh = local_mesh(1, 1)
        t0 = time.perf_counter()
        model = als_fit_streamed(
            sd, cfg, mesh, stats=stats,
            device_budget_bytes=device_budget_bytes,
        )
        _sync(model)
        fit_s = time.perf_counter() - t0
        itemsize = 2 if cfg.dtype == "bfloat16" else 4
        reship = reship_bytes_per_half_step(sd, rank, itemsize)
        store_bytes = sum(
            s.idx_bytes() + s.val_bytes() + s.nobs_bytes()
            for side in (sd.by_row, sd.by_col) for s in side.specs
        )
        block_bytes = max(
            s.idx_bytes() + s.val_bytes() + s.nobs_bytes()
            for side in (sd.by_row, sd.by_col) for s in side.specs
        )
        return {
            "edges": edges,
            "users": users,
            "items": items,
            "real_edges": sd.real_edges,
            "iterations": iterations,
            "build_seconds": round(build_s, 2),
            "spill_seconds": sd.manifest.get("spill_seconds"),
            "pack_seconds": sd.manifest.get("pack_seconds"),
            "fit_seconds": round(fit_s, 2),
            "sec_per_iter": round(fit_s / iterations, 3),
            "edges_per_sec": round(sd.real_edges * iterations / fit_s, 1),
            "blocks": len(sd.by_row.specs) + len(sd.by_col.specs),
            "block_bytes_max": block_bytes,
            "store_bytes": store_bytes,
            "h2d_bytes_per_half_step": stats.bytes_per_half_step,
            "h2d_modeled_bytes_per_half_step": stream_bytes_per_half_step(
                sd, True
            ),
            "reship_bytes_per_half_step": reship,
            "reship_ratio": round(
                reship / max(stats.bytes_per_half_step, 1.0), 2
            ),
            "max_inflight_blocks": stats.max_inflight_blocks,
            "peak_rss_mb": round(peak_rss_mb(), 1),
            "peak_rss_mb_before": round(rss0, 1),
        }
    finally:
        if tmp_ctx is not None and not keep_cache:
            tmp_ctx.cleanup()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--edges", type=int, default=1_500_000)
    p.add_argument("--users", type=int, default=None)
    p.add_argument("--items", type=int, default=None)
    p.add_argument("--rank", type=int, default=RANK)
    p.add_argument("--iterations", type=int, default=2)
    p.add_argument("--buckets", type=int, default=2)
    p.add_argument("--max-len", type=int, default=256)
    p.add_argument("--budget-bytes", type=int, default=0,
                   help="device pin budget for streamed blocks")
    p.add_argument("--cache-dir", default=None)
    p.add_argument(
        "--feed", choices=("both", "resident", "streamed", "scale"),
        default="both",
        help="'scale' runs the streaming-only big-edge mode (lifts the "
        "resident path's memory cap)",
    )
    args = p.parse_args()
    if args.feed == "scale" or args.edges > 20_000_000:
        rep = run_scale(
            edges=args.edges,
            users=args.users,
            items=args.items,
            rank=args.rank,
            iterations=args.iterations,
            buckets=args.buckets,
            max_len=args.max_len,
            cache_dir=args.cache_dir,
            device_budget_bytes=args.budget_bytes,
        )
    else:
        rep = run_ab(
            edges=args.edges,
            users=args.users or 40_000,
            items=args.items or 8_000,
            rank=args.rank,
            iterations=args.iterations,
            buckets=args.buckets,
            max_len=args.max_len,
            feed=args.feed,
            cache_dir=args.cache_dir,
            device_budget_bytes=args.budget_bytes,
        )
    print(json.dumps(rep, indent=1))


if __name__ == "__main__":
    if os.environ.get("JAX_PLATFORMS") is None:
        import jax

        jax.config.update("jax_platforms", "cpu")
    main()
