"""``pio train / deploy / undeploy / eval / batchpredict`` verbs.

Behavioral model: reference ``tools/.../{RunWorkflow,RunServer}.scala`` +
``Console.scala`` dispatch (apache/predictionio layout, unverified --
SURVEY.md section 2.4 #27/#28). Where the reference shells out to
spark-submit, these verbs invoke the workflow runtime in-process; `--`
passthrough args become runtime conf overrides (e.g.
``-- --mesh-shape 2,4``).
"""

from __future__ import annotations

import argparse
import os

from predictionio_tpu.workflow.context import WorkflowParams
from predictionio_tpu.workflow.json_extractor import load_engine_variant


def register(sub: argparse._SubParsersAction) -> None:
    train = sub.add_parser("train", help="train an engine (reads engine.json)")
    _add_variant_args(train)
    train.add_argument("--batch", default="", help="batch label recorded on the instance")
    train.add_argument("--skip-sanity-check", action="store_true")
    train.add_argument(
        "--resume",
        action="store_true",
        help="continue the variant's latest crashed/preempted run from its"
        " step checkpoints instead of starting over",
    )
    train.add_argument(
        "--snapshot-mode",
        choices=("off", "use", "refresh"),
        default=None,
        help="training-snapshot cache: 'use' replays the on-disk columnar"
        " spill (building it on first run), 'refresh' first appends events"
        " ingested since; default off (always scan the event store)",
    )
    train.add_argument(
        "--snapshot-dir",
        default=None,
        help="snapshot root (default $PIO_FS_BASEDIR/snapshots)",
    )
    train.add_argument(
        "--als-solver",
        choices=("auto", "xla", "pallas"),
        default=None,
        help="ALS half-step tail: 'pallas' = fused gather->Gram TPU kernel"
        " (no [rows, L, K] HBM intermediate), 'xla' = einsum path; default"
        " auto (pallas on accelerators, xla on CPU). Overrides the"
        " engine.json alsSolver param for this run",
    )
    train.add_argument(
        "--als-feed",
        choices=("resident", "streamed"),
        default=None,
        help="how ALS reads training data: 'resident' materializes rating"
        " arrays in host memory, 'streamed' trains straight from the"
        " snapshot's on-disk columnar chunks (needs --snapshot-mode"
        " use/refresh; bounded host memory for catalogs bigger than RAM)."
        " Overrides the engine.json alsFeed param for this run",
    )
    train.add_argument(
        "--profile",
        nargs="?",
        const="__default__",
        default=None,
        metavar="DIR",
        help="capture a jax.profiler trace (tensorboard/xprof-loadable) AND"
        " a per-step telemetry journal (wall time, edges/sec, achieved HBM"
        " GB/s, recompile count) into DIR (default:"
        " <engine-dir>/pio-profile)",
    )
    from predictionio_tpu.obs.logs import add_logging_arguments

    add_logging_arguments(train)
    train.add_argument("passthrough", nargs="*", help="runtime conf after --")
    train.set_defaults(func=cmd_train)

    deploy = sub.add_parser("deploy", help="deploy the latest trained instance")
    _add_variant_args(deploy)
    deploy.add_argument("--ip", default="0.0.0.0")
    deploy.add_argument("--port", type=int, default=8000)
    deploy.add_argument("--engine-instance-id", default=None)
    deploy.add_argument(
        "--model-version", type=int, default=None, metavar="N",
        help="deploy an exact model-registry version (the continuous-"
        "learning registry `pio retrain` publishes into) instead of the"
        " latest trained instance -- the rollback lever; fails loudly on a"
        " missing or corrupt version",
    )
    deploy.add_argument("--feedback", action="store_true")
    deploy.add_argument("--event-server-ip", default="localhost")
    deploy.add_argument("--event-server-port", type=int, default=7070)
    deploy.add_argument("--event-server-scheme", default="http",
                        choices=("http", "https"),
                        help="https when the event server uses --ssl-cert")
    deploy.add_argument("--accesskey", default="")
    # python analogue of the reference's --key-store TLS option
    deploy.add_argument("--ssl-cert", default=None, help="PEM cert: serve HTTPS")
    deploy.add_argument("--ssl-key", default=None, help="PEM key (if not in cert)")
    deploy.add_argument(
        "--batch-window-ms", type=float, default=2.0,
        help="micro-batching latency deadline: how long a query may wait "
        "for batchmates (0 disables batching)",
    )
    deploy.add_argument(
        "--max-batch-size", type=int, default=64,
        help="micro-batching flush size (1 disables batching)",
    )
    deploy.add_argument(
        "--batch-buckets", default="1,4,16,64,128",
        help="comma-separated padded batch shapes; jitted scorers compile "
        "once per bucket",
    )
    deploy.add_argument(
        "--frontend-workers", type=int, default=0, metavar="N",
        help="multi-process serving tier: N SO_REUSEPORT frontend "
        "processes parse/validate HTTP and feed this process's scorer "
        "through shared-memory rings ('add a core' = 'add a worker'); "
        "0 (default) serves single-process",
    )
    deploy.add_argument(
        "--scorer-shards", type=int, default=0, metavar="N",
        help="sharded serving fabric: hash-partition the user factor"
        " table across N scorer processes (item-side state replicated),"
        " each hot-swapping per shard behind the SO_REUSEPORT frontend"
        " tier; 0/1 (default) serves unsharded. Sizing: see"
        " PIO_SHARD_BUDGET_BYTES in docs/operations.md",
    )
    deploy.add_argument(
        "--frontend-ring-slots", type=int, default=128, metavar="SLOTS",
        help="per-worker request/completion ring capacity; a full request "
        "ring answers 429 + Retry-After (scorer backpressure)",
    )
    deploy.add_argument(
        "--frontend-max-inflight", type=int, default=16, metavar="N",
        help="concurrent requests the scorer admits before letting the "
        "rings back up (the backpressure horizon and the micro-batcher's "
        "coalescing ceiling; with --dispatch sync, also the dispatcher "
        "thread count)",
    )
    deploy.add_argument(
        "--dispatch", choices=("async", "sync"), default="async",
        help="scorer dispatch model with --frontend-workers: 'async' "
        "(ring consumer submits straight into the micro-batcher; zero "
        "dispatcher threads and 2 wakeups on the query path) or 'sync' "
        "(dispatcher thread pool -- the pre-async model, kept for A/B; "
        "also used whenever batching is disabled)",
    )
    deploy.add_argument(
        "--pin-cpus", action=argparse.BooleanOptionalAction,
        default=os.environ.get("PIO_PIN_CPUS", "") not in ("", "0"),
        help="sched_setaffinity: pin each frontend worker to one core "
        "from the top of the affinity set, the scorer keeps the rest "
        "(default from PIO_PIN_CPUS=1; --no-pin-cpus overrides it); "
        "needs --frontend-workers and >=2 cores",
    )
    deploy.add_argument(
        "--no-tracing", action="store_true",
        help="disable the span tracer (/traces.json reports enabled=false;"
        " the off path allocates no spans)",
    )
    deploy.add_argument(
        "--trace-sample", type=float, default=None, metavar="RATE",
        help="head-sampling rate (0..1) for headerless root traces;"
        " requests with a traceparent header always trace (default:"
        " $PIO_TRACE_SAMPLE or 0.125)",
    )
    deploy.add_argument(
        "--slow-query-ms", type=float, default=None, metavar="MS",
        help="log one span-summary line for any query trace slower than"
        " this (off by default)",
    )
    add_logging_arguments(deploy)
    deploy.set_defaults(func=cmd_deploy)

    retrain = sub.add_parser(
        "retrain",
        help="continuous learning: tail the ingest WAL, fold new events"
        " into the model, hot-swap running query servers (--follow loops;"
        " without it one catch-up cycle runs)",
    )
    _add_variant_args(retrain)
    retrain.add_argument(
        "--follow", action="store_true",
        help="keep following the WAL until interrupted (the online loop);"
        " default is one catch-up cycle",
    )
    retrain.add_argument(
        "--interval", type=float, default=2.0, metavar="SEC",
        help="seconds between WAL polls in --follow mode",
    )
    retrain.add_argument(
        "--notify", action="append", default=[], metavar="URL",
        help="query server base URL to hot-swap after each publish"
        " (repeatable; default http://localhost:8000 -- pass --notify ''"
        " for batch mode, where publishing to the registry is the"
        " reflection boundary)",
    )
    retrain.add_argument(
        "--wal-dir", default=None,
        help="ingest WAL directory to tail (default $PIO_FS_BASEDIR/wal;"
        " must match the event server's --wal-dir)",
    )
    retrain.add_argument(
        "--registry-dir", default=None,
        help="model registry root (default $PIO_FS_BASEDIR/registry)",
    )
    retrain.add_argument(
        "--registry-keep", type=int, default=5, metavar="N",
        help="retained model versions (each is a rollback target)",
    )
    retrain.add_argument(
        "--max-touched-frac", type=float, default=0.2, metavar="F",
        help="staleness budget: touched-user fraction beyond which a full"
        " retrain replaces fold-in",
    )
    retrain.add_argument(
        "--max-item-growth-frac", type=float, default=0.05, metavar="F",
        help="staleness budget: new-item fraction beyond which a full"
        " retrain replaces fold-in (fold-in gives new items zero factors)",
    )
    retrain.add_argument(
        "--no-full-retrain", action="store_true",
        help="never escalate to a full retrain (log and keep serving"
        " stale instead; schedule retrains out of band)",
    )
    retrain.add_argument(
        "--max-cycles", type=int, default=0, metavar="N",
        help="stop after N cycles (0 = until interrupted; test/bench knob)",
    )
    retrain.add_argument(
        "--scorer-shards", type=int, default=0, metavar="N",
        help="publish per-shard model blobs alongside the full blob so a"
        " `pio deploy --scorer-shards N` fabric swaps without ever"
        " loading the full model in one shard; fold-in republishes only"
        " the shards whose users were touched (0 = full blob only)",
    )
    add_logging_arguments(retrain)
    retrain.set_defaults(func=cmd_retrain)

    undeploy = sub.add_parser("undeploy", help="stop a deployed engine server")
    undeploy.add_argument("--ip", default="localhost")
    undeploy.add_argument("--port", type=int, default=8000)
    undeploy.add_argument("--ssl", action="store_true",
                          help="server was deployed with --ssl-cert")
    undeploy.set_defaults(func=cmd_undeploy)

    ev = sub.add_parser(
        "eval",
        help="run an evaluation (dotted Evaluation, or --replay for the"
        " time-travel offline replay harness)",
    )
    ev.add_argument(
        "evaluation", nargs="?", default=None,
        help="dotted path to an Evaluation object/callable (omit with --replay)",
    )
    ev.add_argument("paramsgen", nargs="?", default=None,
                    help="dotted path to an EngineParamsGenerator")
    ev.add_argument("--engine-dir", default=".")
    ev.add_argument(
        "--variant", default=None,
        help="engine variant JSON for --replay (default engine.json)",
    )
    ev.add_argument("--output-path", default=None, help="also write results JSON here")
    ev.add_argument(
        "--replay", action="store_true",
        help="offline replay evaluation: cut the event timeline at a"
        " boundary, train on the prefix (or pin a registry version),"
        " score every held-out user in one batched pass, report ranking"
        " metrics + the scan-vs-mips retrieval guard as JSON",
    )
    ev.add_argument(
        "--split-time", default=None, metavar="ISO8601",
        help="replay boundary: train < t, holdout >= t (e.g."
        " 2024-03-01T00:00:00Z; naive times are UTC, same parse as event"
        " ingestion so the cut is microsecond-exact)",
    )
    ev.add_argument(
        "--split-frac", type=float, default=None, metavar="F",
        help="replay boundary as a fraction of the time-sorted event"
        " stream (0 < F < 1); resolves to a concrete event timestamp so"
        " the split is replayable (default 0.8 when --split-time absent)",
    )
    ev.add_argument("--k", type=int, default=10,
                    help="ranking cutoff for metrics and queries (default 10)")
    ev.add_argument(
        "--metrics", default=None,
        help="comma-separated metric names (default: all; see the metric"
        " catalog in the unknown-metric error or docs/evaluation.md)",
    )
    ev.add_argument(
        "--model-version", type=int, default=None, metavar="N",
        help="evaluate an exact model-registry version (what `pio deploy"
        " --model-version N` would serve) instead of training on the"
        " prefix; the report's model block carries its lineage",
    )
    ev.add_argument(
        "--registry-dir", default=None,
        help="model registry root for --model-version"
        " (default $PIO_FS_BASEDIR/registry)",
    )
    ev.add_argument(
        "--snapshot-mode", choices=("off", "use", "refresh"), default=None,
        help="training-snapshot cache for the replay read (same semantics"
        " as `pio train --snapshot-mode`)",
    )
    ev.add_argument("--snapshot-dir", default=None,
                    help="snapshot root (default $PIO_FS_BASEDIR/snapshots)")
    ev.add_argument(
        "--no-retrieval-guard", action="store_true",
        help="skip the scan-vs-mips shortlist-recall/identity guard"
        " (runs by default when the algorithm has a retrieval surface)",
    )
    ev.set_defaults(func=cmd_eval)

    from predictionio_tpu.analysis.engine import add_check_arguments

    check = sub.add_parser(
        "check",
        help="static analysis: jax drift-shim + interprocedural "
        "concurrency lint (thread roles, locksets, race detection; "
        "rule catalog: docs/static_analysis.md, or --explain RULE)",
    )
    add_check_arguments(check)
    check.set_defaults(func=cmd_check)

    bp = sub.add_parser("batchpredict", help="bulk offline predictions")
    _add_variant_args(bp)
    bp.add_argument("--input", required=True, help="JSON-lines query file")
    bp.add_argument("--output", required=True, help="JSON-lines prediction output")
    bp.add_argument("--engine-instance-id", default=None)
    bp.set_defaults(func=cmd_batchpredict)


def _add_variant_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--engine-dir", default=".", help="engine directory (holds engine.json)"
    )
    parser.add_argument(
        "--variant", default=None, help="engine variant JSON (default engine.json)"
    )


def _load_variant(args: argparse.Namespace):
    path = args.variant or os.path.join(args.engine_dir, "engine.json")
    return load_engine_variant(path)


def cmd_train(args: argparse.Namespace) -> int:
    from predictionio_tpu.obs.logs import configure_logging
    from predictionio_tpu.workflow.core_workflow import run_train

    configure_logging(args.log_format)
    variant = _load_variant(args)
    variant.runtime_conf.update(_parse_passthrough(args.passthrough))
    if args.profile:
        profile_dir = (
            os.path.join(args.engine_dir, "pio-profile")
            if args.profile == "__default__"
            else args.profile
        )
        variant.runtime_conf["pio.profile"] = profile_dir
    # runtime conf reaches components holding a ctx; the env mirrors it for
    # ctx-free layers (PEventStore.dataset) in this same process
    if args.snapshot_mode:
        variant.runtime_conf["pio.snapshot_mode"] = args.snapshot_mode
        os.environ["PIO_SNAPSHOT_MODE"] = args.snapshot_mode
    if args.snapshot_dir:
        variant.runtime_conf["pio.snapshot_dir"] = args.snapshot_dir
        os.environ["PIO_SNAPSHOT_DIR"] = args.snapshot_dir
    if args.als_solver:
        variant.runtime_conf["pio.als_solver"] = args.als_solver
    if args.als_feed:
        variant.runtime_conf["pio.als_feed"] = args.als_feed
    params = WorkflowParams(
        batch=args.batch,
        skip_sanity_check=args.skip_sanity_check,
        resume=args.resume,
    )
    instance = run_train(variant, params)
    print(f"Training completed. Engine instance ID: {instance.id}")
    return 0


def cmd_deploy(args: argparse.Namespace) -> int:
    from predictionio_tpu.obs.logs import configure_logging
    from predictionio_tpu.workflow.create_server import (
        FeedbackConfig,
        run_query_server,
    )
    from predictionio_tpu.workflow.microbatch import BatchConfig

    configure_logging(args.log_format)
    variant = _load_variant(args)
    feedback = None
    if args.feedback:
        feedback = FeedbackConfig(
            event_server_url=(
                f"{args.event_server_scheme}://"
                f"{args.event_server_ip}:{args.event_server_port}"
            ),
            access_key=args.accesskey,
        )
    try:
        buckets = tuple(
            int(b) for b in args.batch_buckets.split(",") if b.strip()
        )
    except ValueError:
        raise SystemExit(
            f"Error: --batch-buckets must be comma-separated integers, "
            f"got {args.batch_buckets!r}"
        )
    frontend = None
    if args.scorer_shards > 1 and (args.ssl_cert or args.ssl_key):
        raise SystemExit(
            "Error: --scorer-shards does not support TLS "
            "(--ssl-cert/--ssl-key); terminate TLS in front of the "
            "frontend tier or deploy single-process"
        )
    if args.frontend_workers > 0:
        if args.ssl_cert or args.ssl_key:
            raise SystemExit(
                "Error: --frontend-workers does not support TLS "
                "(--ssl-cert/--ssl-key); terminate TLS in front of the "
                "frontend tier or deploy single-process"
            )
        from predictionio_tpu.serving.procserver import FrontendConfig

        frontend = FrontendConfig(
            workers=args.frontend_workers,
            ring_slots=args.frontend_ring_slots,
            max_inflight=args.frontend_max_inflight,
            dispatch=args.dispatch,
            pin_cpus=args.pin_cpus,
        )
    from predictionio_tpu.online.registry import RegistryError

    try:
        run_query_server(
            variant,
            host=args.ip,
            port=args.port,
            instance_id=args.engine_instance_id,
            model_version=args.model_version,
            feedback=feedback,
            ssl_cert=args.ssl_cert,
            ssl_key=args.ssl_key,
            batching=BatchConfig(
                max_batch_size=args.max_batch_size,
                window_ms=args.batch_window_ms,
                buckets=buckets,
            ),
            tracing=False if args.no_tracing else None,
            trace_sample=args.trace_sample,
            slow_query_ms=args.slow_query_ms,
            frontend=frontend,
            scorer_shards=args.scorer_shards,
        )
    except RegistryError as exc:
        # --model-version names an exact artifact; a missing or corrupt one
        # must be an actionable error, never a silent fallback deploy
        raise SystemExit(f"Error: {exc}")
    return 0


def cmd_retrain(args: argparse.Namespace) -> int:
    from predictionio_tpu.obs.logs import configure_logging
    from predictionio_tpu.online.foldin import StalenessBudget
    from predictionio_tpu.online.loop import RetrainConfig, RetrainLoop

    configure_logging(args.log_format)
    variant = _load_variant(args)
    notify = [u for u in (args.notify or ["http://localhost:8000"]) if u]
    config = RetrainConfig(
        interval_s=args.interval,
        wal_dir=args.wal_dir,
        registry_dir=args.registry_dir,
        registry_keep=args.registry_keep,
        notify_urls=notify,
        budget=StalenessBudget(
            max_touched_frac=args.max_touched_frac,
            max_item_growth_frac=args.max_item_growth_frac,
        ),
        max_cycles=args.max_cycles if args.follow else 1,
        allow_full_retrain=not args.no_full_retrain,
        scorer_shards=args.scorer_shards,
    )
    try:
        loop = RetrainLoop(variant, config)
    except (LookupError, ValueError) as exc:
        raise SystemExit(f"Error: {exc}")
    import signal

    signal.signal(signal.SIGTERM, lambda *_: loop.stop())
    try:
        counts = loop.run_follow()
    except KeyboardInterrupt:
        counts = dict(loop.cycles)
    print(
        "Retrain loop finished: "
        + ", ".join(f"{k}={v}" for k, v in sorted(counts.items()) if v)
    )
    return 0


def cmd_undeploy(args: argparse.Namespace) -> int:
    import ssl
    import urllib.request

    import http.client

    # try the flagged scheme first; fall back to the other scheme ONLY on
    # errors that look like a scheme mismatch (TLS handshake noise / bad
    # status line), so a plainly-down server reports its real error once
    schemes = ("https", "http") if args.ssl else ("http", "https")
    insecure = ssl.create_default_context()
    insecure.check_hostname = False
    insecure.verify_mode = ssl.CERT_NONE
    first_exc = None
    for attempt, scheme in enumerate(schemes):
        url = f"{scheme}://{args.ip}:{args.port}/stop"
        try:
            urllib.request.urlopen(
                urllib.request.Request(url, method="POST", data=b""),
                timeout=5,
                context=insecure if scheme == "https" else None,
            )
            print("Engine server stopping.")
            return 0
        except Exception as exc:
            if attempt == 0:
                first_exc = exc
                root = getattr(exc, "reason", exc)
                mismatch = isinstance(
                    root, (ssl.SSLError, http.client.BadStatusLine)
                )
                if not mismatch:
                    break
    print(
        f"Error: cannot reach engine server at {args.ip}:{args.port}: {first_exc}"
    )
    return 1


def _resolve_dotted(dotted: str, engine_dir: str):
    """Resolve a dotted path to an Evaluation/EngineParamsGenerator, calling
    it if it is a class or factory function."""
    from predictionio_tpu.controller.metrics import EngineParamsGenerator, Evaluation
    from predictionio_tpu.workflow.json_extractor import (
        EngineConfigError,
        resolve_dotted,
    )

    try:
        obj = resolve_dotted(dotted, engine_dir)
    except EngineConfigError as exc:
        raise SystemExit(f"Error: {exc}")
    if isinstance(obj, (Evaluation, EngineParamsGenerator)):
        return obj
    return obj()


def _cmd_replay_eval(args: argparse.Namespace) -> int:
    import json

    from predictionio_tpu.eval.replay import run_replay_eval
    from predictionio_tpu.online.registry import RegistryError

    variant = _load_variant(args)
    # env mirror for ctx-free layers, same as cmd_train
    if args.snapshot_mode:
        variant.runtime_conf["pio.snapshot_mode"] = args.snapshot_mode
        os.environ["PIO_SNAPSHOT_MODE"] = args.snapshot_mode
    if args.snapshot_dir:
        variant.runtime_conf["pio.snapshot_dir"] = args.snapshot_dir
        os.environ["PIO_SNAPSHOT_DIR"] = args.snapshot_dir
    try:
        report = run_replay_eval(
            variant,
            split_time=args.split_time,
            split_frac=args.split_frac,
            k=args.k,
            metrics=args.metrics,
            model_version=args.model_version,
            registry_dir=args.registry_dir,
            retrieval_guard=not args.no_retrieval_guard,
        )
    except (ValueError, NotImplementedError, RegistryError) as exc:
        # exit-2 contract (mirrors `pio check --rules`): a bad metric name,
        # malformed boundary, unsupported engine, or GC'd pinned version is
        # an actionable one-liner, never a traceback
        print(f"Error: {exc}")
        return 2
    text = json.dumps(report, indent=2, sort_keys=True)
    print(text)
    if args.output_path:
        with open(args.output_path, "w") as f:
            f.write(text + "\n")
        print(f"Results written to {args.output_path}")
    return 0


def cmd_eval(args: argparse.Namespace) -> int:
    from predictionio_tpu.controller.metrics import (
        EngineParamsGenerator,
        Evaluation,
    )
    from predictionio_tpu.workflow.core_workflow import run_evaluation

    if args.replay:
        return _cmd_replay_eval(args)
    if not args.evaluation:
        print(
            "Error: pio eval needs a dotted Evaluation path, or --replay"
            " for the offline replay harness"
        )
        return 2
    evaluation = _resolve_dotted(args.evaluation, args.engine_dir)
    if not isinstance(evaluation, Evaluation):
        raise SystemExit(
            f"Error: {args.evaluation!r} did not yield an Evaluation"
        )
    if args.paramsgen:
        generator = _resolve_dotted(args.paramsgen, args.engine_dir)
    else:
        from predictionio_tpu.controller.engine import EngineParams

        generator = EngineParamsGenerator([EngineParams()])
    if not isinstance(generator, EngineParamsGenerator):
        raise SystemExit(f"Error: {args.paramsgen!r} did not yield an EngineParamsGenerator")
    instance = run_evaluation(
        evaluation,
        generator,
        evaluation_class=args.evaluation,
        generator_class=args.paramsgen or "",
    )
    print(instance.evaluator_results)
    if args.output_path:
        with open(args.output_path, "w") as f:
            f.write(instance.evaluator_results_json)
        print(f"Results written to {args.output_path}")
    print(f"Evaluation instance ID: {instance.id}")
    return 0


def cmd_check(args: argparse.Namespace) -> int:
    from predictionio_tpu.analysis.engine import run_with_args

    return run_with_args(args)


def cmd_batchpredict(args: argparse.Namespace) -> int:
    from predictionio_tpu.workflow.batch_predict import run_batch_predict

    variant = _load_variant(args)
    count = run_batch_predict(
        variant, args.input, args.output, instance_id=args.engine_instance_id
    )
    print(f"Batch predict completed: {count} queries -> {args.output}")
    return 0


def _parse_passthrough(tokens: list[str]) -> dict:
    """``-- --mesh-shape 2,4 --key value`` -> runtime conf entries."""
    conf = {}
    i = 0
    while i < len(tokens):
        tok = tokens[i]
        if tok.startswith("--"):
            key = tok[2:].replace("-", "_")
            if i + 1 < len(tokens) and not tokens[i + 1].startswith("--"):
                value = tokens[i + 1]
                i += 1
            else:
                value = "true"
            if key in ("mesh_shape", "dcn_mesh_shape"):
                conf[f"pio.{key}"] = [int(x) for x in value.split(",")]
            elif key == "mesh_axes":
                conf["pio.mesh_axes"] = value.split(",")
            else:
                conf[f"pio.{key}"] = value
        i += 1
    return conf
