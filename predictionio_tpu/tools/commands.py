"""Registration point for ``pio`` subcommands.

App/accesskey/train/deploy/eval/import/export verbs attach here as their
subsystems land (SURVEY.md section 2.4 #27 lists the full reference verb set).
"""

from __future__ import annotations

import argparse


def register(sub: argparse._SubParsersAction) -> None:
    from predictionio_tpu.tools import (
        app_commands,
        build_commands,
        daemon_commands,
        engine_commands,
        import_export,
        server_commands,
        top_command,
    )

    app_commands.register(sub)
    build_commands.register(sub)
    daemon_commands.register(sub)
    engine_commands.register(sub)
    import_export.register(sub)
    server_commands.register(sub)
    top_command.register(sub)
