"""L6 CLI + ops tooling (``pio`` verbs, import/export, dashboard, admin)."""
