"""The ``pio`` command-line console.

Behavioral model: reference ``tools/.../console/{Console,Pio}.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.4 #27). Verb
set and flag names kept; process orchestration targets the JAX runtime
instead of spark-submit.

This module grows with the framework; verbs are registered in
``predictionio_tpu.tools.commands``.
"""

from __future__ import annotations

import argparse
import sys

from predictionio_tpu.version import __version__


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pio",
        description="predictionio_tpu: TPU-native machine learning server",
    )
    sub = parser.add_subparsers(dest="command")

    sub.add_parser("version", help="print version")

    status = sub.add_parser("status", help="verify configuration and storage connectivity")
    status.set_defaults(func=cmd_status)

    from predictionio_tpu.tools import commands

    commands.register(sub)
    return parser


def cmd_status(args: argparse.Namespace) -> int:
    from predictionio_tpu.data import storage

    print(f"pio (predictionio_tpu) {__version__}")
    print("Storage configuration:")
    for repo, cfg in storage.config_summary().items():
        detail = ", ".join(f"{k}={v}" for k, v in cfg.items() if k not in ("source",))
        print(f"  {repo}: source={cfg['source']} ({detail})")
    failures = storage.verify_all_data_objects()
    if failures:
        print("Storage check FAILED:")
        for failure in failures:
            print(f"  - {failure}")
        return 1
    print("Storage check OK. Your system is all ready to go.")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 2
    if args.command == "version":
        print(__version__)
        return 0
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
