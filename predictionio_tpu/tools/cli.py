"""The ``pio`` command-line console.

Behavioral model: reference ``tools/.../console/{Console,Pio}.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.4 #27). Verb
set and flag names kept; process orchestration targets the JAX runtime
instead of spark-submit.

This module grows with the framework; verbs are registered in
``predictionio_tpu.tools.commands``.
"""

from __future__ import annotations

import argparse
import os
import sys

from predictionio_tpu.version import __version__


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pio",
        description="predictionio_tpu: TPU-native machine learning server",
    )
    sub = parser.add_subparsers(dest="command")

    sub.add_parser("version", help="print version")

    status = sub.add_parser("status", help="verify configuration and storage connectivity")
    status.set_defaults(func=cmd_status)

    from predictionio_tpu.tools import commands

    commands.register(sub)
    return parser


def cmd_status(args: argparse.Namespace) -> int:
    from predictionio_tpu.data import storage

    print(f"pio (predictionio_tpu) {__version__}")
    # the accelerator is this framework's execution substrate (the role
    # SPARK_HOME verification played in the reference's `pio status`).
    # Probe it in a BOUNDED subprocess: initializing a registered-but-
    # wedged tunnel plugin blocks forever, and the diagnostic command a
    # user runs to debug a broken setup must always answer.
    import subprocess

    probe = (
        "from predictionio_tpu.utils.platform import ensure_backend\n"
        "import jax\n"
        "p = ensure_backend()\n"
        "ds = jax.devices()\n"
        "print('PIO_ACCEL|' + p + '|' + str(len(ds)) + '|' + ds[0].device_kind)\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", probe],
            capture_output=True,
            text=True,
            timeout=float(os.environ.get("PIO_STATUS_PROBE_TIMEOUT_S", "60")),
        )
        fields = next(
            (
                line.split("|")
                for line in proc.stdout.splitlines()
                if line.startswith("PIO_ACCEL|")
            ),
            None,
        )
        if fields is None:
            print("Accelerator: probe failed -- training will fall back to CPU")
        elif fields[1] == "cpu":
            print("Accelerator: none (CPU backend) -- training and serving"
                  " run on the host")
        else:
            print(f"Accelerator: {fields[1]} x{fields[2]} ({fields[3]})")
    except subprocess.TimeoutExpired:
        print("Accelerator: probe timed out -- the accelerator plugin may be"
              " wedged; trains fall back to CPU (utils/platform ladder)")
    print("Storage configuration:")
    for repo, cfg in storage.config_summary().items():
        detail = ", ".join(f"{k}={v}" for k, v in cfg.items() if k not in ("source",))
        print(f"  {repo}: source={cfg['source']} ({detail})")
    failures = storage.verify_all_data_objects()
    if failures:
        print("Storage check FAILED:")
        for failure in failures:
            print(f"  - {failure}")
        return 1
    print("Storage check OK. Your system is all ready to go.")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 2
    if args.command == "version":
        print(__version__)
        return 0
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
