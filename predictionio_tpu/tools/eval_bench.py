"""Replay-evaluation quality bench: the standing accuracy trip-wire.

Usage::

    python -m predictionio_tpu.tools.eval_bench [--events 4000]

Builds a seeded rating stream against a fresh file-backed store, runs one
``pio eval --replay`` pass (train on the prefix, score every held-out
user through the template's batched scorer), and reports:

- ``eval_ndcg_at_k`` / ``eval_hit_rate_at_k`` -- the ranking quality
  numbers ``bench.py`` tracks round over round, so a speed PR that
  quietly degrades recommendations moves a committed metric;
- ``mips_recall_at_k`` / ``response_identity_rate`` -- the scan-vs-mips
  retrieval guard: the quantized two-stage retriever's top-k overlap
  with (and byte-identity against) the exact scan on the SAME model and
  split. 1.0 / 1.0 at the default shortlist budget is the contract.

The stream is clique-structured (each user sticks to one item genre) so
the metrics sit far above the random-ranking floor and a real regression
is visible, not lost in noise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

from predictionio_tpu.data import storage as storage_registry
from predictionio_tpu.tools.ingest_bench import _Env

APP = "EvalBenchApp"
APP_ID = 1


def _engine_json(workdir: str, rank: int, iterations: int) -> str:
    path = os.path.join(workdir, "engine.json")
    with open(path, "w") as f:
        json.dump(
            {
                "id": "eval-bench",
                "engineFactory": (
                    "predictionio_tpu.models.recommendation.engine"
                    ".engine_factory"
                ),
                "datasource": {"params": {"appName": APP}},
                "algorithms": [
                    {
                        "name": "als",
                        "params": {
                            "rank": rank,
                            "numIterations": iterations,
                            "seed": 7,
                            "checkpointInterval": 0,
                        },
                    }
                ],
            },
            f,
        )
    return path


def _populate(le, events: int, users: int, items: int, genres: int = 4) -> None:
    """Clique-structured stream: user u rates mostly genre ``u % genres``
    items (fixed time base, 13 ms spacing -- replayable boundaries).

    Size the catalog so each genre pool is wider than one user's event
    budget: then every user's holdout window holds in-genre items THEY
    never rated but their genre-mates trained, and the unseenOnly-scored
    ndcg measures collaborative generalization instead of the
    seen-filtered noise floor."""
    import datetime as _dt

    from predictionio_tpu.data import DataMap, Event

    rng = np.random.default_rng(11)
    base = _dt.datetime(2024, 1, 1, tzinfo=_dt.timezone.utc)
    per_genre = max(items // genres, 1)
    batch = []
    for k in range(events):
        u = int(rng.integers(0, users))
        g = u % genres
        if rng.random() < 0.85:
            item = g * per_genre + int(rng.integers(0, per_genre))
            rating = float(rng.integers(4, 6))
        else:
            item = int(rng.integers(0, items))
            rating = float(rng.integers(1, 3))
        batch.append(
            Event(
                event="rate",
                entity_type="user",
                entity_id=f"u{u}",
                target_entity_type="item",
                target_entity_id=f"i{item}",
                properties=DataMap({"rating": rating}),
                event_time=base + _dt.timedelta(milliseconds=13 * k),
            )
        )
    le.batch_insert(batch, app_id=APP_ID)


def run_eval_quality(
    events: int = 4_000,
    users: int = 80,
    items: int = 192,
    rank: int = 8,
    iterations: int = 4,
    split_frac: float = 0.8,
    k: int = 10,
    workdir: str | None = None,
) -> dict:
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.eval.replay import run_replay_eval
    from predictionio_tpu.workflow.json_extractor import load_engine_variant

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="pio_eval_bench_")
    with _Env(workdir):
        storage_registry.get_meta_data_apps().insert(App(name=APP))
        le = storage_registry.get_l_events()
        le.init_channel(APP_ID)
        _populate(le, events, users, items)
        variant = load_engine_variant(_engine_json(workdir, rank, iterations))
        t0 = time.perf_counter()
        report = run_replay_eval(variant, split_frac=split_frac, k=k)
        wall = time.perf_counter() - t0
    if own_tmp:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    guard = report.get("retrieval_guard") or {}
    return {
        "events": events, "users": users, "items": items, "rank": rank,
        "split_frac": split_frac, "k": k,
        "holdout_users": report["split"]["holdout_users"],
        f"eval_ndcg_at_{k}": report["metrics"][f"ndcg_at_{k}"],
        f"eval_hit_rate_at_{k}": report["metrics"][f"hit_rate_at_{k}"],
        f"mips_recall_at_{k}": guard.get(f"shortlist_recall_at_{k}"),
        "response_identity_rate": guard.get("response_identity_rate"),
        "shortlist": guard.get("shortlist"),
        "replay_seconds": round(wall, 3),
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--events", type=int, default=4_000)
    parser.add_argument("--users", type=int, default=80)
    parser.add_argument("--items", type=int, default=192)
    parser.add_argument("--rank", type=int, default=8)
    parser.add_argument("--iterations", type=int, default=4)
    parser.add_argument("--split-frac", type=float, default=0.8)
    parser.add_argument("--k", type=int, default=10)
    args = parser.parse_args(argv)
    print(
        json.dumps(
            run_eval_quality(
                events=args.events,
                users=args.users,
                items=args.items,
                rank=args.rank,
                iterations=args.iterations,
                split_frac=args.split_frac,
                k=args.k,
            ),
            indent=2,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
