"""Admin server: REST mirror of the app/accesskey CLI (default :7071).

Behavioral model: reference ``tools/.../admin/{AdminServer,AdminAPI,
CommandClient}.scala`` (apache/predictionio layout, unverified -- SURVEY.md
section 2.4 #32, experimental upstream). Routes:

- ``GET  /``                      server info
- ``GET  /cmd/app``               list apps
- ``POST /cmd/app``               create app {name, description?}
- ``GET  /cmd/app/<name>``        app details
- ``DELETE /cmd/app/<name>``      delete app + data
- ``DELETE /cmd/app/<name>/data`` wipe event data
"""

from __future__ import annotations

import json

from predictionio_tpu.data import storage
from predictionio_tpu.utils.http import (
    Request,
    Response,
    ServiceThread,
    instrumented_router,
    make_server,
)

DEFAULT_PORT = 7071


class AdminService:
    def __init__(self):
        self.router, self.metrics = instrumented_router()
        self.router.add("GET", "/", self.handle_info)
        self.router.add("GET", "/cmd/app", self.handle_list)
        self.router.add("POST", "/cmd/app", self.handle_create)
        self.router.add("GET", "/cmd/app/<name>", self.handle_show)
        self.router.add("DELETE", "/cmd/app/<name>", self.handle_delete)
        self.router.add("DELETE", "/cmd/app/<name>/data", self.handle_data_delete)

    def handle_info(self, request: Request) -> Response:
        from predictionio_tpu.version import __version__

        return Response(200, {"status": "alive", "version": __version__})

    def handle_list(self, request: Request) -> Response:
        keys = storage.get_meta_data_access_keys()
        return Response(
            200,
            [
                {
                    "name": app.name,
                    "id": app.id,
                    "description": app.description,
                    "accessKeys": [k.key for k in keys.get_by_app_id(app.id)],
                }
                for app in storage.get_meta_data_apps().get_all()
            ],
        )

    def handle_create(self, request: Request) -> Response:
        from predictionio_tpu.tools.app_ops import create_app

        try:
            body = request.json() or {}
        except json.JSONDecodeError:
            return Response(400, {"message": "malformed JSON body"})
        name = body.get("name")
        if not name:
            return Response(400, {"message": "field 'name' is required"})
        try:
            app, key = create_app(name, body.get("description", ""))
        except ValueError as exc:
            return Response(409, {"message": str(exc)})
        return Response(201, {"name": name, "id": app.id, "accessKey": key})

    def _app(self, request: Request):
        return storage.get_meta_data_apps().get_by_name(request.path_params["name"])

    def handle_show(self, request: Request) -> Response:
        app = self._app(request)
        if app is None:
            return Response(404, {"message": "app not found"})
        keys = storage.get_meta_data_access_keys().get_by_app_id(app.id)
        channels = storage.get_meta_data_channels().get_by_app(app.id)
        return Response(
            200,
            {
                "name": app.name,
                "id": app.id,
                "description": app.description,
                "accessKeys": [{"key": k.key, "events": k.events} for k in keys],
                "channels": [{"name": c.name, "id": c.id} for c in channels],
            },
        )

    def handle_delete(self, request: Request) -> Response:
        from predictionio_tpu.tools.app_ops import delete_app_cascade

        app = self._app(request)
        if app is None:
            return Response(404, {"message": "app not found"})
        delete_app_cascade(app)
        return Response(200, {"message": f"app {app.name!r} deleted"})

    def handle_data_delete(self, request: Request) -> Response:
        from predictionio_tpu.tools.app_ops import delete_app_data

        app = self._app(request)
        if app is None:
            return Response(404, {"message": "app not found"})
        # REST wipe covers every channel (matches its 'wipe event data' doc)
        delete_app_data(app, all_channels=True)
        return Response(200, {"message": "event data deleted"})


def create_admin_server(host: str = "0.0.0.0", port: int = DEFAULT_PORT) -> ServiceThread:
    service = AdminService()
    return ServiceThread(make_server(service.router, host, port, "pio-adminserver"))


def run_admin_server(host: str = "0.0.0.0", port: int = DEFAULT_PORT) -> None:
    thread = create_admin_server(host, port)
    print(f"Admin server listening on http://{host}:{port}")
    thread.server.serve_forever()
