"""``pio import`` / ``pio export``: bulk event transfer.

Behavioral model: reference ``tools/.../imprt/FileToEvents.scala`` +
``tools/.../export/EventsToFile.scala`` (apache/predictionio layout,
unverified -- SURVEY.md section 2.4 #30). Formats match the reference:
JSON-lines (one event JSON object per line, identical to the REST wire
shape) for both directions, plus parquet export (EventsToFile's second
format; pyarrow). Import additionally accepts parquet files produced by
the exporter, so export -> import round-trips either format.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Iterator

from predictionio_tpu.data import storage
from predictionio_tpu.data.event import Event, EventValidationError

#: parquet columns, in the wire-contract field names. `properties` is the
#: JSON-encoded object (parquet nesting buys nothing for a free-form map).
_PARQUET_FIELDS = (
    "eventId", "event", "entityType", "entityId", "targetEntityType",
    "targetEntityId", "properties", "eventTime", "prId", "creationTime",
)


def register(sub: argparse._SubParsersAction) -> None:
    imp = sub.add_parser("import", help="import events into an app")
    imp.add_argument("--appid", type=int, required=True)
    imp.add_argument("--channel", default=None)
    imp.add_argument("--input", required=True)
    imp.add_argument(
        "--format", choices=["json", "parquet"], default=None,
        help="default: parquet when --input ends with .parquet, else json-lines",
    )
    imp.set_defaults(func=cmd_import)

    exp = sub.add_parser("export", help="export an app's events to a file")
    exp.add_argument("--appid", type=int, required=True)
    exp.add_argument("--channel", default=None)
    exp.add_argument("--output", required=True)
    exp.add_argument("--format", choices=["json", "parquet"], default="json")
    exp.set_defaults(func=cmd_export)


def _channel_id(app_id: int, channel_name: str | None) -> int | None:
    if channel_name is None:
        return None
    for ch in storage.get_meta_data_channels().get_by_app(app_id):
        if ch.name == channel_name:
            return ch.id
    raise SystemExit(f"Error: channel {channel_name!r} not found in app {app_id}")


def _pyarrow():
    try:
        import pyarrow
        import pyarrow.parquet
    except ImportError as exc:  # baked into most images; be clear when not
        raise SystemExit(
            "Error: parquet format requires pyarrow; use --format json"
        ) from exc
    return pyarrow


def _iter_parquet_rows(path: str) -> Iterator[tuple[int, dict]]:
    """(row_number, raw-row-dict) pairs from an exported parquet file.

    `properties` stays a JSON STRING here: decoding happens in the
    consumer's per-row try block, so one bad cell is a counted rejection
    rather than an exception out of the for-statement that aborts the
    whole import mid-way."""
    pa = _pyarrow()
    f = pa.parquet.ParquetFile(path)
    rowno = 0
    for batch in f.iter_batches(batch_size=5000):
        for row in batch.to_pylist():
            rowno += 1
            yield rowno, {k: v for k, v in row.items() if v is not None}


def _iter_json_lines(path: str) -> Iterator[tuple[int, str]]:
    """(line_number, raw-json-line) pairs; parsing stays with the caller so
    a bad line is a per-row error, not an aborted import."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if line:
                yield lineno, line


def cmd_import(args: argparse.Namespace) -> int:
    if storage.get_meta_data_apps().get(args.appid) is None:
        print(f"Error: app id {args.appid} does not exist.")
        return 1
    channel_id = _channel_id(args.appid, args.channel)
    le = storage.get_l_events()
    le.init_channel(args.appid, channel_id)
    imported = errors = 0
    batch: list[Event] = []

    def flush():
        nonlocal imported
        if batch:
            le.batch_insert(batch, args.appid, channel_id)
            imported += len(batch)
            batch.clear()

    fmt = args.format or (
        "parquet" if args.input.endswith(".parquet") else "json"
    )
    rows = _iter_parquet_rows(args.input) if fmt == "parquet" else _iter_json_lines(args.input)
    for lineno, raw in rows:
        try:
            obj = json.loads(raw) if isinstance(raw, str) else dict(raw)
            if isinstance(obj.get("properties"), str):  # parquet cell
                obj["properties"] = json.loads(obj["properties"])
            batch.append(Event.from_json_obj(obj))
        except (json.JSONDecodeError, EventValidationError) as exc:
            errors += 1
            print(f"  row {lineno}: {exc}", file=sys.stderr)
            continue
        if len(batch) >= 5000:
            flush()
    flush()
    print(f"Imported {imported} events" + (f" ({errors} rejected)" if errors else "") + ".")
    return 0 if errors == 0 else 1


def cmd_export(args: argparse.Namespace) -> int:
    if storage.get_meta_data_apps().get(args.appid) is None:
        print(f"Error: app id {args.appid} does not exist.")
        return 1
    channel_id = _channel_id(args.appid, args.channel)
    events = storage.get_l_events().find(args.appid, channel_id)
    if args.format == "parquet":
        count = _export_parquet(events, args.output)
    else:
        count = 0
        with open(args.output, "w") as f:
            for event in events:
                f.write(json.dumps(event.to_json_obj()) + "\n")
                count += 1
    print(f"Exported {count} events to {args.output}.")
    return 0


def _export_parquet(events, output: str) -> int:
    pa = _pyarrow()
    schema = pa.schema([(name, pa.string()) for name in _PARQUET_FIELDS])
    count = 0
    with pa.parquet.ParquetWriter(output, schema) as writer:
        chunk: list[dict] = []

        def flush():
            nonlocal count
            if chunk:
                writer.write_table(
                    pa.Table.from_pylist(chunk, schema=schema)
                )
                count += len(chunk)
                chunk.clear()

        for event in events:
            obj = event.to_json_obj()
            row = {name: obj.get(name) for name in _PARQUET_FIELDS}
            if row.get("properties") is not None:
                row["properties"] = json.dumps(row["properties"])
            chunk.append(row)
            if len(chunk) >= 5000:
                flush()
        flush()
    return count
