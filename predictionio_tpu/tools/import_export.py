"""``pio import`` / ``pio export``: bulk JSON-lines event transfer.

Behavioral model: reference ``tools/.../imprt/FileToEvents.scala`` +
``tools/.../export/EventsToFile.scala`` (apache/predictionio layout,
unverified -- SURVEY.md section 2.4 #30). Same file format: one event JSON
object per line, identical to the REST wire shape.
"""

from __future__ import annotations

import argparse
import json
import sys

from predictionio_tpu.data import storage
from predictionio_tpu.data.event import Event, EventValidationError


def register(sub: argparse._SubParsersAction) -> None:
    imp = sub.add_parser("import", help="import JSON-lines events into an app")
    imp.add_argument("--appid", type=int, required=True)
    imp.add_argument("--channel", default=None)
    imp.add_argument("--input", required=True)
    imp.set_defaults(func=cmd_import)

    exp = sub.add_parser("export", help="export an app's events to JSON-lines")
    exp.add_argument("--appid", type=int, required=True)
    exp.add_argument("--channel", default=None)
    exp.add_argument("--output", required=True)
    exp.add_argument("--format", choices=["json"], default="json")
    exp.set_defaults(func=cmd_export)


def _channel_id(app_id: int, channel_name: str | None) -> int | None:
    if channel_name is None:
        return None
    for ch in storage.get_meta_data_channels().get_by_app(app_id):
        if ch.name == channel_name:
            return ch.id
    raise SystemExit(f"Error: channel {channel_name!r} not found in app {app_id}")


def cmd_import(args: argparse.Namespace) -> int:
    if storage.get_meta_data_apps().get(args.appid) is None:
        print(f"Error: app id {args.appid} does not exist.")
        return 1
    channel_id = _channel_id(args.appid, args.channel)
    le = storage.get_l_events()
    le.init_channel(args.appid, channel_id)
    imported = errors = 0
    batch: list[Event] = []

    def flush():
        nonlocal imported
        if batch:
            le.batch_insert(batch, args.appid, channel_id)
            imported += len(batch)
            batch.clear()

    with open(args.input) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                batch.append(Event.from_json_obj(json.loads(line)))
            except (json.JSONDecodeError, EventValidationError) as exc:
                errors += 1
                print(f"  line {lineno}: {exc}", file=sys.stderr)
                continue
            if len(batch) >= 5000:
                flush()
    flush()
    print(f"Imported {imported} events" + (f" ({errors} rejected)" if errors else "") + ".")
    return 0 if errors == 0 else 1


def cmd_export(args: argparse.Namespace) -> int:
    if storage.get_meta_data_apps().get(args.appid) is None:
        print(f"Error: app id {args.appid} does not exist.")
        return 1
    channel_id = _channel_id(args.appid, args.channel)
    count = 0
    with open(args.output, "w") as f:
        for event in storage.get_l_events().find(args.appid, channel_id):
            f.write(json.dumps(event.to_json_obj()) + "\n")
            count += 1
    print(f"Exported {count} events to {args.output}.")
    return 0
