"""Pre-commit hook entry point: ``pio check`` over the staged diff.

``python -m predictionio_tpu.tools.precommit`` runs
``pio check --changed --format text`` -- the report scoped to files git
says changed vs HEAD, per-module rules run only on those files, the
interprocedural J/C/R/S/P analyses still see the whole package (a leak
in a changed file whose release lives two modules away, or an ack whose
covering commit lives in a callee, is exactly what the call-graph
credit exists for). The run is budgeted at < 2 s on a
one-file diff (test-asserted in ``tests/test_analysis.py``), so it sits
comfortably inside a commit hook.

Wire it via the committed ``.pre-commit-config.yaml`` sample at the
repo root::

    pre-commit install

or as a plain git hook::

    echo 'python -m predictionio_tpu.tools.precommit' > .git/hooks/pre-commit
    chmod +x .git/hooks/pre-commit

Exit status follows ``pio check``: 0 = clean, 1 = findings/stale
baseline entries (the commit is blocked), 2 = usage error. Extra
arguments pass straight through (e.g. ``--format json``).
"""

from __future__ import annotations

import sys


def main(argv: "list[str] | None" = None) -> int:
    from predictionio_tpu.analysis.engine import run_cli

    args = list(sys.argv[1:] if argv is None else argv)
    forwarded = ["--changed"]
    if not any(a.startswith("--format") for a in args):
        forwarded += ["--format", "text"]
    return run_cli(forwarded + args)


if __name__ == "__main__":
    raise SystemExit(main())
