"""``pio build / run / template`` verbs.

Behavioral model: reference ``tools/.../console/{Console,Template}.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.4 #27/#29).
The reference ``pio build`` shells out to ``sbt package``/``assembly`` and
checks ``template.json`` pio-version compatibility; engines here are Python
packages, so ``build`` validates the engine directory instead: engine.json
parses, the engine factory imports and constructs, and (optionally
``--clean``) stale bytecode caches are dropped.

``pio run`` is the reference's "run arbitrary main class with the pio
classpath" escape hatch -- here: run a python script/module with the runtime
importable and the engine dir on ``sys.path``.

``pio template list/get`` [<=0.12 era; removed upstream v0.13 when templates
became plain git clones] serves the in-repo gallery: zero-egress container,
so "get" scaffolds from the bundled ``examples/`` instead of GitHub.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

from predictionio_tpu.version import __version__


def register(sub: argparse._SubParsersAction) -> None:
    build = sub.add_parser("build", help="validate and prepare an engine directory")
    build.add_argument("--engine-dir", default=".", help="engine directory")
    build.add_argument("--variant", default=None, help="engine variant JSON")
    build.add_argument("--clean", action="store_true", help="drop bytecode caches first")
    build.add_argument("--verbose", action="store_true")
    build.set_defaults(func=cmd_build)

    run = sub.add_parser(
        "run", help="run a python script/module with the pio runtime importable"
    )
    run.add_argument("main", help="path to a .py file or a dotted module name")
    run.add_argument("--engine-dir", default=".", help="added to sys.path")
    # REMAINDER: everything after `main` belongs to the target, including
    # option-style tokens like --epochs
    run.add_argument("args", nargs=argparse.REMAINDER, help="argv passed to the target")
    run.set_defaults(func=cmd_run)

    template = sub.add_parser("template", help="list or scaffold engine templates")
    tsub = template.add_subparsers(dest="template_command")
    tlist = tsub.add_parser("list", help="list bundled engine templates")
    tlist.set_defaults(func=cmd_template_list)
    tget = tsub.add_parser("get", help="scaffold a bundled template into a new dir")
    tget.add_argument("name", help="template name (see `pio template list`)")
    tget.add_argument("directory", help="destination engine directory")
    tget.add_argument("--app-name", default=None, help="rewrite datasource appName")
    tget.set_defaults(func=cmd_template_get)
    template.set_defaults(func=lambda args: (template.print_help(), 2)[1])


# ---------------------------------------------------------------------------
# pio build


def _check_template_json(engine_dir: str) -> str | None:
    """Reference parity: template.json carries a minimum pio version
    (``{"pio": {"version": {"min": "0.10.0"}}}``). Returns a warning or None."""
    path = os.path.join(engine_dir, "template.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            spec = json.load(f)
        min_version = spec.get("pio", {}).get("version", {}).get("min")
    except (json.JSONDecodeError, AttributeError) as exc:
        return f"template.json unreadable: {exc}"
    if not min_version:
        return None

    def key(v: str):
        return tuple(int(p) for p in v.split(".") if p.isdigit())

    if key(__version__) < key(str(min_version)):
        return (
            f"template.json requires pio >= {min_version}, this is {__version__}"
        )
    return None


def cmd_build(args: argparse.Namespace) -> int:
    from predictionio_tpu.workflow.json_extractor import (
        EngineConfigError,
        build_engine,
        load_engine_variant,
    )

    engine_dir = os.path.abspath(args.engine_dir)
    if args.clean:
        removed = 0
        for root, dirs, _files in os.walk(engine_dir):
            for d in list(dirs):
                if d == "__pycache__":
                    shutil.rmtree(os.path.join(root, d), ignore_errors=True)
                    dirs.remove(d)
                    removed += 1
        if args.verbose:
            print(f"Removed {removed} __pycache__ dir(s).")

    warning = _check_template_json(engine_dir)
    if warning:
        print(f"Warning: {warning}")

    variant_path = args.variant or os.path.join(engine_dir, "engine.json")
    try:
        variant = load_engine_variant(variant_path)
        engine = build_engine(variant)
    except EngineConfigError as exc:
        print(f"Error: {exc}")
        return 1
    if args.verbose:
        print(f"Engine factory: {variant.engine_factory}")
        print(f"Engine: {type(engine).__name__}")
        for name, _params in variant.engine_params.algorithm_params_list:
            print(f"  algorithm: {name}")
    print("Build finished: engine is importable and engine.json is valid.")
    return 0


# ---------------------------------------------------------------------------
# pio run


def cmd_run(args: argparse.Namespace) -> int:
    import runpy

    engine_dir = os.path.abspath(args.engine_dir)
    if engine_dir not in sys.path:
        sys.path.insert(0, engine_dir)
    old_argv = sys.argv
    sys.argv = [args.main] + list(args.args)
    try:
        if args.main.endswith(".py") or os.path.sep in args.main:
            runpy.run_path(args.main, run_name="__main__")
        else:
            runpy.run_module(args.main, run_name="__main__", alter_sys=True)
    except SystemExit as exc:
        if exc.code is None:
            return 0
        if isinstance(exc.code, int):
            return exc.code
        print(exc.code, file=sys.stderr)
        return 1
    finally:
        sys.argv = old_argv
    return 0


# ---------------------------------------------------------------------------
# pio template


def _examples_root() -> str:
    # repo layout: predictionio_tpu/tools/build_commands.py -> repo/examples
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(here, "examples")


_TEMPLATE_BLURBS = {
    "recommendation": "ALS matrix factorization (MLlib recommender parity)",
    "classification": "Naive Bayes / logistic regression (classification parity)",
    "similarproduct": "item cooccurrence similar-product recommender",
    "ecommerce": "implicit ALS + live business rules (categories, stock)",
    "universal": "Universal-Recommender-style LLR cross-occurrence",
    "ncf": "Neural Collaborative Filtering (NeuMF) on the dp x tp mesh",
    "sequence": "SASRec sequential recommender (ring-attention sp mesh)",
}


def cmd_template_list(args: argparse.Namespace) -> int:
    root = _examples_root()
    if not os.path.isdir(root):
        print("No bundled templates found (examples/ missing).")
        return 1
    print("Bundled engine templates (scaffold with `pio template get <name> <dir>`):")
    for name in sorted(os.listdir(root)):
        if os.path.isdir(os.path.join(root, name)):
            blurb = _TEMPLATE_BLURBS.get(name, "")
            print(f"  {name:18s} {blurb}")
    return 0


def cmd_template_get(args: argparse.Namespace) -> int:
    src = os.path.join(_examples_root(), args.name)
    if not os.path.isdir(src):
        print(f"Error: no bundled template named {args.name!r}; try `pio template list`")
        return 1
    dst = os.path.abspath(args.directory)
    if os.path.exists(dst) and (not os.path.isdir(dst) or os.listdir(dst)):
        print(f"Error: destination {dst} exists and is not empty")
        return 1
    shutil.copytree(src, dst, dirs_exist_ok=True)
    if args.app_name:
        variant_path = os.path.join(dst, "engine.json")
        if os.path.exists(variant_path):
            with open(variant_path) as f:
                variant = json.load(f)
            variant.setdefault("datasource", {}).setdefault("params", {})[
                "appName"
            ] = args.app_name
            with open(variant_path, "w") as f:
                json.dump(variant, f, indent=2)
                f.write("\n")
    print(f"Engine template {args.name!r} scaffolded at {dst}")
    return 0
