"""Concurrent-load latency benchmark for a deployed Query Server.

The reference's serving SLO story is N stateless query servers behind a
load balancer (SURVEY.md section 5.3); the <5 ms p50 target (BASELINE)
is only meaningful under concurrent keep-alive load, not a single
sequential client. This tool drives ``POST /queries.json`` from N
threads, each with its own persistent HTTP connection, and reports the
latency distribution plus aggregate throughput:

    python -m predictionio_tpu.tools.serving_bench \
        --url http://127.0.0.1:8000 --concurrency 8 --requests 400 \
        --query '{"user": "u1", "num": 4}'

Without ``--url`` it runs the **self-contained micro-batching A/B**: a
synthetic catalog is ingested into a throwaway store, the named engine(s)
are trained, and the same concurrent load is driven against two local
servers -- micro-batching disabled vs enabled -- reporting both QPS /
latency distributions and the speedup:

    JAX_PLATFORMS=cpu python -m predictionio_tpu.tools.serving_bench \
        --concurrency 32 --engine both

Prints one JSON line; also importable (``run_load`` / ``run_ab``) for
tests and ``bench.py``.
"""

from __future__ import annotations

import argparse
import http.client
import json
import threading
import time
import urllib.parse
from contextlib import contextmanager as _contextmanager


def _percentile(sorted_ms: list[float], q: float) -> float | None:
    if not sorted_ms:
        return None  # JSON null: NaN is not valid RFC 8259 output
    idx = min(int(q * len(sorted_ms)), len(sorted_ms) - 1)
    return round(sorted_ms[idx], 3)


def run_load(
    url: str,
    query: dict | str,
    clients: int = 8,
    requests: int = 400,
    timeout: float = 30.0,
    client: str = "http",
) -> dict:
    """N keep-alive clients, ``requests`` total POSTs; latency stats in ms.

    Every client thread owns one persistent connection (the reference
    SDKs' connection-pool behavior); failures are counted, not raised,
    so a mid-run hiccup yields a truthful report instead of a stack
    trace.

    ``client="raw"`` swaps ``http.client`` for a minimal raw-socket
    client (~5x less python per request). The load generator shares the
    benchmarked box's cores with the server: with the default client the
    GENERATOR saturates around ~600 qps on the 2-core box, so any server
    faster than that measures the client, not the server. The
    multi-process serving A/B uses raw for exactly this reason; the
    single-process batching/tracing A/Bs keep the historical client so
    their BASELINE.md numbers stay comparable.
    """
    parsed = urllib.parse.urlsplit(url)
    body = query if isinstance(query, str) else json.dumps(query)
    payload = body.encode()
    clients = min(clients, requests) or 1
    base, extra = divmod(requests, clients)
    # distribute the remainder so exactly ``requests`` POSTs are sent
    counts = [base + (1 if k < extra else 0) for k in range(clients)]
    lat_ms: list[list[float]] = [[] for _ in range(clients)]
    failures = [0] * clients
    start_gate = threading.Event()

    def http_client(k: int) -> None:
        conn_cls = (
            http.client.HTTPSConnection
            if parsed.scheme == "https"
            else http.client.HTTPConnection
        )
        conn = conn_cls(parsed.hostname, parsed.port, timeout=timeout)
        start_gate.wait()
        for _ in range(counts[k]):
            t0 = time.perf_counter()
            try:
                conn.request(
                    "POST", "/queries.json", payload,
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    failures[k] += 1
                    continue
            except (OSError, http.client.HTTPException):
                # HTTPException covers malformed responses (a garbled LB
                # status line) -- a dead thread would under-report silently
                failures[k] += 1
                conn.close()
                continue
            lat_ms[k].append((time.perf_counter() - t0) * 1000.0)
        conn.close()

    request_bytes = (
        f"POST /queries.json HTTP/1.1\r\n"
        f"Host: {parsed.hostname}:{parsed.port}\r\n"
        f"Content-Type: application/json\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n"
    ).encode() + payload

    def raw_client(k: int) -> None:
        import socket

        def connect():
            s = socket.create_connection(
                (parsed.hostname, parsed.port), timeout=timeout
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return s

        sock = connect()
        buf = b""
        start_gate.wait()
        for _ in range(counts[k]):
            t0 = time.perf_counter()
            try:
                sock.sendall(request_bytes)
                while b"\r\n\r\n" not in buf:
                    chunk = sock.recv(65536)
                    if not chunk:
                        raise OSError("server closed connection")
                    buf += chunk
                head, _, buf = buf.partition(b"\r\n\r\n")
                status = int(head.split(b" ", 2)[1])
                length = 0
                for line in head.split(b"\r\n")[1:]:
                    if line[:15].lower() == b"content-length:":
                        length = int(line[15:])
                        break
                while len(buf) < length:
                    chunk = sock.recv(65536)
                    if not chunk:
                        raise OSError("truncated response body")
                    buf += chunk
                buf = buf[length:]
                if status != 200:
                    failures[k] += 1
                    continue
            except (OSError, ValueError):
                failures[k] += 1
                try:
                    sock.close()
                except OSError:
                    pass
                buf = b""
                try:
                    sock = connect()
                except OSError:
                    failures[k] += counts[k] - len(lat_ms[k]) - failures[k]
                    return
                continue
            lat_ms[k].append((time.perf_counter() - t0) * 1000.0)
        sock.close()

    worker = raw_client if client == "raw" else http_client
    threads = [
        threading.Thread(target=worker, args=(k,), daemon=True)
        for k in range(clients)
    ]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    start_gate.set()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0

    flat = sorted(x for per in lat_ms for x in per)
    return {
        "clients": clients,
        "requests_ok": len(flat),
        "failures": sum(failures),
        "p50_ms": _percentile(flat, 0.50),
        "p90_ms": _percentile(flat, 0.90),
        "p99_ms": _percentile(flat, 0.99),
        "qps": round(len(flat) / wall_s, 1) if wall_s > 0 else 0.0,
    }


# --------------------------------------------------------------------------
# self-contained micro-batching A/B
# --------------------------------------------------------------------------

#: engines the A/B knows how to train on a synthetic rating stream; params
#: and catalog sizes target the regime micro-batching exists for (scoring
#: cost comparable to or above the per-request HTTP stack cost); training
#: quality is not the point -- few iterations/epochs, serving-shaped catalog
AB_ENGINES: dict[str, dict] = {
    "recommendation": {
        "factory": "predictionio_tpu.models.recommendation.engine.engine_factory",
        "algorithms": [
            {
                "name": "als",
                "params": {
                    "rank": 64,
                    "numIterations": 2,
                    "checkpointInterval": 0,
                },
            }
        ],
        # per-query serving cost is one [items, rank] gemv (a full factor-
        # matrix scan); the batched arm amortizes that scan across the batch
        "defaults": {"users": 500, "items": 100_000, "events": 150_000},
    },
    "ncf": {
        "factory": "predictionio_tpu.models.ncf.engine.engine_factory",
        "algorithms": [
            {
                "name": "ncf",
                "params": {
                    "embedDim": 16,
                    "hidden": [32, 16],
                    "epochs": 1,
                    "usePallas": False,
                    "checkpoint": False,
                },
            }
        ],
        # NCF scores ALL items per query, so compute does not amortize with
        # batch size on CPU (it does on an accelerator, where the batch is
        # one device program); the CPU win is dispatch amortization, which
        # dominates at small catalogs and inverts past ~8k items
        "defaults": {"users": 500, "items": 4_000, "events": 30_000},
    },
}


def _responses_equivalent(a: bytes, b: bytes, rtol: float = 1e-5) -> bool:
    """Same ranking, scores equal up to float accumulation order.

    The ALS templates score a single query with a gemv and a batch with a
    multi-row gemm; BLAS accumulates those in different orders, so scores
    can drift at the ulp level (the same accepted semantic as
    ``batch_predict`` vs ``predict`` -- see test_ncf's batch contract).
    Item identity and order must still match exactly.
    """
    if a == b:
        return True
    try:
        ja, jb = json.loads(a), json.loads(b)
    except ValueError:
        return False
    sa, sb = ja.get("itemScores"), jb.get("itemScores")
    if not isinstance(sa, list) or not isinstance(sb, list):
        return ja == jb
    if [x.get("item") for x in sa] != [x.get("item") for x in sb]:
        return False
    import math

    return all(
        math.isclose(x["score"], y["score"], rel_tol=rtol, abs_tol=1e-8)
        for x, y in zip(sa, sb)
    )


def _ingest_synthetic(app_name: str, users: int, items: int, events: int):
    """Synthetic rating stream: zipf-ish item popularity, every item
    guaranteed at least one event (the vocab must span the catalog)."""
    import numpy as np

    from predictionio_tpu.data import DataMap, Event, storage
    from predictionio_tpu.data.storage.base import App

    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(name=app_name))
    le = storage.get_l_events()
    le.init_channel(app_id)
    rng = np.random.default_rng(7)
    events = max(events, items)  # coverage needs one event per item
    uu = rng.integers(0, users, size=events)
    ii = (np.minimum(rng.random(events) ** 2.0, 0.999999) * items).astype(int)
    ii[:items] = np.arange(items)  # full catalog coverage
    rr = rng.integers(1, 6, size=events)
    le.batch_insert(
        [
            Event(
                event="rate",
                entity_type="user",
                entity_id=f"u{int(u)}",
                target_entity_type="item",
                target_entity_id=f"i{int(i)}",
                properties=DataMap({"rating": float(r)}),
            )
            for u, i, r in zip(uu, ii, rr)
        ],
        app_id=app_id,
    )


@_contextmanager
def _synthetic_deployment(engine: str, users, items, events):
    """A throwaway store with ``engine`` trained on a synthetic catalog;
    yields ``(variant, sizes)``. Shared by every serving A/B harness."""
    import os
    import shutil
    import tempfile

    from predictionio_tpu.data import storage
    from predictionio_tpu.workflow.core_workflow import run_train
    from predictionio_tpu.workflow.json_extractor import load_engine_variant

    if engine not in AB_ENGINES:
        raise ValueError(
            f"unknown A/B engine {engine!r}; choose from {sorted(AB_ENGINES)}"
        )
    spec = AB_ENGINES[engine]
    users = users if users is not None else spec["defaults"]["users"]
    items = items if items is not None else spec["defaults"]["items"]
    events = events if events is not None else spec["defaults"]["events"]
    prev_basedir = os.environ.get("PIO_FS_BASEDIR")
    tmp = tempfile.mkdtemp(prefix="pio_serving_ab_")
    os.environ["PIO_FS_BASEDIR"] = tmp
    storage.reset()
    try:
        app_name = f"ServingAB-{engine}"
        _ingest_synthetic(app_name, users, items, events)
        variant_path = os.path.join(tmp, "engine.json")
        with open(variant_path, "w") as f:
            json.dump(
                {
                    "id": f"serving-ab-{engine}",
                    "engineFactory": spec["factory"],
                    "datasource": {"params": {"appName": app_name}},
                    "algorithms": spec["algorithms"],
                },
                f,
            )
        variant = load_engine_variant(variant_path)
        run_train(variant)
        yield variant, {"users": users, "items": items, "events": events}
    finally:
        if prev_basedir is None:
            os.environ.pop("PIO_FS_BASEDIR", None)
        else:
            os.environ["PIO_FS_BASEDIR"] = prev_basedir
        storage.reset()
        shutil.rmtree(tmp, ignore_errors=True)


def _load_in_subprocess(
    url: str, concurrency: int, n_requests: int, query: dict,
    client: str = "http",
    affinity: "set | None" = None,
) -> dict:
    """Drive ``run_load`` from a child interpreter: a co-resident client
    pool would fight the server threads for the GIL and understate every
    arm. ``affinity`` (the PRE-pin cpu mask, captured before any
    ``--pin-cpus`` arm narrowed this process) is re-applied in the
    child: without it the generator inherits the pinned scorer's
    shrunken mask and the bench measures the generator, not the
    server -- worst at high worker counts, inverting the sweep."""
    import os
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    if affinity is not None:
        # applied by the child's main() after exec -- a preexec_fn would
        # force a bare fork() inside this (JAX-)threaded process
        env["PIO_BENCH_AFFINITY"] = ",".join(str(c) for c in sorted(affinity))
    proc = subprocess.run(
        [
            sys.executable, "-m",
            "predictionio_tpu.tools.serving_bench",
            "--url", url,
            "--concurrency", str(concurrency),
            "--requests", str(n_requests),
            "--query", json.dumps(query),
            "--client", client,
        ],
        capture_output=True, text=True, timeout=600,
        env=env,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"load subprocess failed: {proc.stderr[-500:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _concurrent_bodies(url: str, concurrency: int, users: int) -> list[bytes]:
    """One distinct-user query per client thread, fired together: on a
    batching arm these COALESCE, so comparing the bodies across arms
    checks batched result scattering (a per-slot misalignment would swap
    users' answers), not just the single-query path."""
    import urllib.request

    probes = [
        {"user": f"u{k % users}", "num": 10} for k in range(concurrency)
    ]
    bodies: list = [None] * len(probes)

    def worker(k: int) -> None:
        try:
            req = urllib.request.Request(
                f"{url}/queries.json",
                data=json.dumps(probes[k]).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                bodies[k] = resp.read()
        except Exception as exc:  # surfaced below, never swallowed
            bodies[k] = exc

    threads = [
        threading.Thread(target=worker, args=(k,))
        for k in range(len(probes))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    failed = [b for b in bodies if not isinstance(b, bytes)]
    if failed:
        # an unanswered probe must abort loudly, not compare
        # None==None as "identical"
        raise RuntimeError(
            f"{len(failed)} identity probe(s) failed against {url}: "
            f"{failed[0]!r}"
        )
    return bodies


def _sequential_bodies(url: str, users: int, n: int = 8) -> list[bytes]:
    """One query at a time (batch size 1 everywhere): across arms these
    must be BYTE-identical -- no gemv-vs-gemm accumulation drift excuse,
    because every arm scores the identical batch shape."""
    import urllib.request

    bodies = []
    for k in range(n):
        req = urllib.request.Request(
            f"{url}/queries.json",
            data=json.dumps({"user": f"u{k % users}", "num": 10}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            bodies.append(resp.read())
    return bodies


def _measure_arms(
    variant,
    arms: dict[str, dict],
    concurrency: int,
    requests: int,
    query: dict,
    users: int,
    warmup: int,
    client: str = "http",
) -> tuple[dict, dict]:
    """Serve ``variant`` once per arm (``arms`` maps label ->
    ``create_query_server`` kwargs; a ``frontend_workers`` key routes the
    arm through the multi-process tier instead) and drive the identical
    concurrent load at each; returns (label -> run_load report, label ->
    identity probe bodies).

    Servers run in-process on ephemeral ports; the load clients run in a
    subprocess. Each arm gets a warm-up pass first (per-bucket jit
    compilation must not land in the measured window) plus a coalescing
    identity probe.
    """
    import os as _os

    from predictionio_tpu.workflow.create_server import (
        create_multiproc_query_server,
        create_query_server,
        create_sharded_query_server,
    )

    # captured BEFORE any pinned arm narrows this process's mask: the
    # load-generator children are re-widened to it (see
    # _load_in_subprocess)
    baseline_affinity = (
        _os.sched_getaffinity(0)
        if hasattr(_os, "sched_getaffinity") else None
    )

    def load_in_subprocess(url: str, n_requests: int) -> dict:
        return _load_in_subprocess(
            url, concurrency, n_requests, query, client=client,
            affinity=baseline_affinity,
        )

    def concurrent_bodies(url: str) -> list[bytes]:
        return _concurrent_bodies(url, concurrency, users)

    reports: dict[str, dict] = {}
    responses: dict[str, list[bytes]] = {}
    sequential: dict[str, list[bytes]] = {}
    for label, server_kwargs in arms.items():
        server_kwargs = dict(server_kwargs)
        workers = server_kwargs.pop("frontend_workers", 0)
        shards = server_kwargs.pop("scorer_shards", 0)
        if shards:
            # the sharded fabric owns its scorer subprocesses end to end;
            # there is no in-process service handle to close
            handle = create_sharded_query_server(
                variant, host="127.0.0.1", port=0, scorer_shards=shards,
                frontend=workers or None, **server_kwargs,
            )
            service = None
        elif workers:
            handle, service = create_multiproc_query_server(
                variant, host="127.0.0.1", port=0, frontend=workers,
                **server_kwargs,
            )
        else:
            handle, service = create_query_server(
                variant, host="127.0.0.1", port=0, **server_kwargs
            )
        handle.start()
        url = f"http://127.0.0.1:{handle.port}"
        try:
            # warm-up: compile every batch bucket outside the clock
            load_in_subprocess(url, warmup)
            # identity probes (outside the clock): sequential = byte
            # identity at batch size 1, concurrent = scatter check under
            # coalescing (documented ulp drift across batch shapes)
            sequential[label] = _sequential_bodies(url, users)
            responses[label] = concurrent_bodies(url)
            reports[label] = load_in_subprocess(url, requests)
            if service is not None and service.scorer_stats is not None:
                # the measured wakeup budget: the async arm must show
                # <=2 wakeups/request and zero query-path dispatcher
                # threads. Read from the served /metrics gauges -- the
                # bench records the exact number operators see, with ONE
                # definition of the formula (the service's mirror hook)
                gauges = _scorer_gauges(url)
                reports[label]["wakeups_per_request"] = gauges.get(
                    "pio_scorer_wakeups_per_request"
                )
                threads = gauges.get("pio_scorer_dispatch_threads")
                reports[label]["dispatch_threads"] = (
                    int(threads) if threads is not None else None
                )
        finally:
            handle.stop()
            if service is not None:
                service.close()
    return reports, responses, sequential


def _scorer_gauges(url: str) -> dict[str, float]:
    """The scorer's wakeup-budget gauges from its live /metrics."""
    import urllib.request

    try:
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as resp:
            text = resp.read().decode("utf-8", "replace")
    except Exception:
        return {}
    out: dict[str, float] = {}
    for line in text.splitlines():
        for name in (
            "pio_scorer_wakeups_per_request", "pio_scorer_dispatch_threads"
        ):
            if line.startswith(name + " "):
                try:
                    out[name] = float(line.rsplit(" ", 1)[1])
                except ValueError:
                    pass
    return out


def run_ab(
    engine: str = "recommendation",
    concurrency: int = 32,
    requests: int = 960,
    users: int | None = None,
    items: int | None = None,
    events: int | None = None,
    window_ms: float = 5.0,
    max_batch_size: int = 64,
) -> dict:
    """Train ``engine`` on a synthetic catalog in a throwaway store, then
    measure the same concurrent load with micro-batching off vs on.
    Returns both ``run_load`` reports plus ``qps_speedup``. Responses are
    identical across arms by construction (same model, same query), which
    the identity probe spot-checks under coalescing load."""
    from predictionio_tpu.workflow.microbatch import BatchConfig

    with _synthetic_deployment(engine, users, items, events) as (variant, sizes):
        arms = {
            "batching_off": {"batching": BatchConfig(window_ms=0.0)},
            "batching_on": {
                "batching": BatchConfig(
                    window_ms=window_ms, max_batch_size=max_batch_size
                )
            },
        }
        reports, responses, _sequential = _measure_arms(
            variant, arms, concurrency, requests,
            {"user": "u1", "num": 10}, sizes["users"],
            warmup=max(4 * max_batch_size, concurrency),
        )
    out: dict = {
        "engine": engine,
        "concurrency": concurrency,
        "requests": requests,
        **sizes,
        "window_ms": window_ms,
        "max_batch_size": max_batch_size,
        **reports,
    }
    out["responses_identical"] = (
        responses["batching_off"] == responses["batching_on"]
    )
    out["responses_equivalent"] = all(
        _responses_equivalent(a, b)
        for a, b in zip(responses["batching_off"], responses["batching_on"])
    )
    off, on = out["batching_off"]["qps"], out["batching_on"]["qps"]
    out["qps_speedup"] = round(on / off, 2) if off else None
    return out


def _set_blas_threads(n: int) -> "int | None":
    """Best-effort runtime OpenBLAS thread cap; returns the previous
    value (to restore) or None when no OpenBLAS is loaded.

    Why the serving A/B caps BLAS at 1: OpenBLAS worker threads
    BUSY-SPIN between gemms, and on the 2-core box that spin (from the
    scorer's per-batch factor-matrix gemm) stole whole scheduler quanta
    from the frontend worker processes -- measured as a 3-8x qps
    collapse of the process tier with multi-second completion-ring
    backups. Capped to 1 the gemm runs on the dispatching thread and
    every process gets scheduled. Applied identically to every arm.
    """
    import ctypes
    import re

    try:
        with open("/proc/self/maps") as f:
            paths = sorted({
                m.group(1)
                for line in f
                if (m := re.search(r"(/\S*openblas\S*\.so\S*)", line))
            })
        for path in paths:
            lib = ctypes.CDLL(path)
            for suffix in ("64_", "64", "_", ""):
                get = getattr(lib, f"openblas_get_num_threads{suffix}", None)
                set_ = getattr(lib, f"openblas_set_num_threads{suffix}", None)
                if get is not None and set_ is not None:
                    prev = int(get())
                    set_(int(n))
                    return prev
    except Exception:
        pass
    return None


def run_multiproc_ab(
    engine: str = "recommendation",
    concurrency: int = 32,
    requests: int = 2000,
    workers: tuple = (1, 2),
    users: int | None = None,
    items: int | None = None,
    events: int | None = None,
    window_ms: float = 2.0,
    max_batch_size: int = 64,
    max_inflight: int | None = None,
    dispatch: "str | tuple" = "async",
    pin_cpus: bool = False,
) -> dict:
    """The multi-process serving A/B: the single-process
    ``ThreadingHTTPServer`` tier vs N ``SO_REUSEPORT`` frontend workers
    feeding the shared-memory ring, identical micro-batched scorer and
    identical concurrent load (raw-socket clients -- the stock
    ``http.client`` generator saturates around ~600 qps on the 2-core
    box, below the process tier's ceiling, so it would measure itself).
    Reports per-arm ``run_load`` stats, per-worker-count speedups, and
    the coalescing identity probe (bodies must be byte-identical across
    every arm: all of them are produced by the same scorer router).

    ``dispatch`` picks the scorer dispatch model per process-tier arm:
    ``"async"`` (ring consumer -> micro-batcher future -> flusher
    callback; zero dispatcher threads), ``"sync"`` (the dispatcher-pool
    tier), or a tuple of both for the sync-vs-async A/B -- arms are then
    labeled ``workers_N_sync`` / ``workers_N_async`` and the report adds
    ``qps_async_over_sync_workers_N``. ``pin_cpus`` turns on the
    ``sched_setaffinity`` plan (frontends one core each off the top,
    scorer keeps the rest) for every process-tier arm; combine with a
    ``workers`` sweep like ``(1, 2, 4, 8)`` on real multi-core hardware.
    """
    from predictionio_tpu.workflow.microbatch import BatchConfig

    from predictionio_tpu.serving.procserver import FrontendConfig

    modes = (dispatch,) if isinstance(dispatch, str) else tuple(dispatch)
    batching = BatchConfig(window_ms=window_ms, max_batch_size=max_batch_size)
    arms: dict[str, dict] = {"singleproc": {"batching": batching}}
    for n in sorted(set(int(w) for w in workers if int(w) > 0)):
        for mode in modes:
            fe = FrontendConfig(workers=n, dispatch=mode, pin_cpus=pin_cpus)
            if max_inflight is not None:
                fe.max_inflight = max_inflight
            label = (
                f"workers_{n}" if len(modes) == 1 else f"workers_{n}_{mode}"
            )
            arms[label] = {
                "batching": batching, "frontend_workers": fe,
            }
    prev_blas = _set_blas_threads(1)
    try:
        with _synthetic_deployment(engine, users, items, events) as (variant, sizes):
            reports, responses, sequential = _measure_arms(
                variant, arms, concurrency, requests,
                {"user": "u1", "num": 10}, sizes["users"],
                warmup=max(4 * max_batch_size, concurrency, 256),
                client="raw",
            )
    finally:
        if prev_blas is not None:
            _set_blas_threads(prev_blas)
    out: dict = {
        "engine": engine,
        "concurrency": concurrency,
        "requests": requests,
        **sizes,
        "window_ms": window_ms,
        "max_batch_size": max_batch_size,
        **reports,
    }
    # batch-size-1 probes: byte identity is REQUIRED across arms (every
    # arm's body is produced by the same scorer code over the same shape)
    seq_base = sequential["singleproc"]
    out["responses_identical"] = all(
        sequential[label] == seq_base for label in arms
    )
    # coalescing probes: scatter correctness; across arms batch
    # composition is timing-dependent, so scores may carry the
    # documented ulp-level gemv-vs-gemm accumulation drift
    base = responses["singleproc"]
    out["responses_equivalent"] = all(
        _responses_equivalent(a, b)
        for label in arms
        for a, b in zip(base, responses[label])
    ) and all(
        _responses_equivalent(a, b)
        for label in arms
        for a, b in zip(seq_base, sequential[label])
    )
    sp = reports["singleproc"]["qps"]
    for label in arms:
        if label == "singleproc" or not sp:
            continue
        out[f"qps_speedup_{label}"] = round(reports[label]["qps"] / sp, 2)
    if len(modes) > 1:
        # the dispatch-model A/B: async over sync at identical worker count
        for n in sorted(set(int(w) for w in workers if int(w) > 0)):
            sync_qps = reports.get(f"workers_{n}_sync", {}).get("qps")
            async_qps = reports.get(f"workers_{n}_async", {}).get("qps")
            if sync_qps and async_qps:
                out[f"qps_async_over_sync_workers_{n}"] = round(
                    async_qps / sync_qps, 2
                )
    best = max(
        (reports[label]["qps"] for label in arms if label != "singleproc"),
        default=0.0,
    )
    out["qps_speedup"] = round(best / sp, 2) if sp else None
    out["dispatch"] = list(modes)
    out["pin_cpus"] = pin_cpus
    return out


def run_sharded_ab(
    engine: str = "recommendation",
    concurrency: int = 32,
    requests: int = 2000,
    shards: tuple = (1, 2, 4),
    users: int | None = None,
    items: int | None = None,
    events: int | None = None,
    window_ms: float = 2.0,
    max_batch_size: int = 64,
    frontend_workers: int = 1,
) -> dict:
    """The sharded serving sweep: one arm per scorer shard count. Shard
    count 1 is the single-process ``ThreadingHTTPServer`` tier (the
    fabric's floor is 2 -- one shard IS the unsharded server); each
    n >= 2 arm is a full fabric: ``frontend_workers`` SO_REUSEPORT
    frontends routing ``hash(user) % n`` over n scorer shard processes,
    each holding one partition of the user factor table with the item
    side replicated. Identical raw-socket load at every arm.

    Batch-size-1 probe bodies must be BYTE-identical across every arm:
    a shard scores its partition's users with the same code over the
    same shapes as the unsharded scorer (partitioning selects rows, it
    never changes arithmetic), so any divergence is a routing or
    scatter bug, not drift. Coalescing probes use the equivalence check
    (batch composition is timing-dependent per arm, same as the
    multi-process A/B).

    OpenBLAS is capped at 1 thread in this process (parent-side arms)
    AND via ``OPENBLAS_NUM_THREADS`` for the shard children -- the
    shard processes each load their own BLAS, and n spinning pools on a
    small box would measure scheduler thrash, not sharding.
    """
    import os

    from predictionio_tpu.serving.procserver import FrontendConfig
    from predictionio_tpu.workflow.microbatch import BatchConfig

    batching = BatchConfig(window_ms=window_ms, max_batch_size=max_batch_size)
    counts = sorted(set(int(n) for n in shards if int(n) > 0))
    arms: dict[str, dict] = {}
    for n in counts:
        if n == 1:
            arms["shards_1"] = {"batching": batching}
        else:
            arms[f"shards_{n}"] = {
                "batching": batching,
                "scorer_shards": n,
                "frontend_workers": FrontendConfig(
                    workers=frontend_workers, spawn_timeout_s=180.0
                ),
            }
    if "shards_1" not in arms:
        # the sweep is meaningless without the unsharded baseline
        arms = {"shards_1": {"batching": batching}, **arms}
        counts = [1] + counts
    prev_blas = _set_blas_threads(1)
    prev_env = os.environ.get("OPENBLAS_NUM_THREADS")
    os.environ["OPENBLAS_NUM_THREADS"] = "1"
    try:
        with _synthetic_deployment(engine, users, items, events) as (variant, sizes):
            reports, responses, sequential = _measure_arms(
                variant, arms, concurrency, requests,
                {"user": "u1", "num": 10}, sizes["users"],
                warmup=max(4 * max_batch_size, concurrency, 256),
                client="raw",
            )
    finally:
        if prev_env is None:
            os.environ.pop("OPENBLAS_NUM_THREADS", None)
        else:
            os.environ["OPENBLAS_NUM_THREADS"] = prev_env
        if prev_blas is not None:
            _set_blas_threads(prev_blas)
    out: dict = {
        "engine": engine,
        "concurrency": concurrency,
        "requests": requests,
        **sizes,
        "window_ms": window_ms,
        "max_batch_size": max_batch_size,
        "frontend_workers": frontend_workers,
        "shards": counts,
        **reports,
    }
    seq_base = sequential["shards_1"]
    out["responses_identical"] = all(
        sequential[label] == seq_base for label in arms
    )
    base = responses["shards_1"]
    out["responses_equivalent"] = all(
        _responses_equivalent(a, b)
        for label in arms
        for a, b in zip(base, responses[label])
    ) and all(
        _responses_equivalent(a, b)
        for label in arms
        for a, b in zip(seq_base, sequential[label])
    )
    sp = reports["shards_1"]["qps"]
    for label in arms:
        if label == "shards_1" or not sp:
            continue
        out[f"qps_speedup_{label}"] = round(reports[label]["qps"] / sp, 2)
    best = max(
        (reports[label]["qps"] for label in arms if label != "shards_1"),
        default=0.0,
    )
    out["qps_speedup"] = round(best / sp, 2) if sp and best else None
    return out


def run_trace_ab(
    engine: str = "recommendation",
    concurrency: int = 32,
    requests: int = 960,
    users: int | None = None,
    items: int | None = None,
    events: int | None = None,
    window_ms: float = 5.0,
    max_batch_size: int = 64,
    rounds: int = 3,
) -> dict:
    """The tracing-overhead A/B: identical micro-batched serving with the
    span tracer disabled vs enabled in its PRODUCTION DEFAULT config —
    headerless roots head-sampled at ``PIO_TRACE_SAMPLE`` (1-in-8), the
    load clients sending no ``traceparent`` (a real internet-facing
    workload's shape) — same concurrent load. ``overhead_pct`` is the qps
    cost of tracing; the acceptance bar is < 2% at 32 clients (bench
    secondary ``trace_overhead_pct``). Full always-on tracing
    (``--trace-sample 1``) measures ~10% on the 2-core box — that is the
    number sampling exists to amortize.

    Methodology: the box's throughput DRIFTS upward across sequential
    measurements (the in-process jax compile cache and CPython warm up
    across server instances -- measured ~20%+ from first arm to last,
    10x the effect under test), so a single off-then-on pass attributes
    the drift to whichever arm ran first. Both servers are therefore
    kept alive side by side, warmed identically, and measured in
    ``rounds`` interleaved pairs whose within-round order alternates;
    ``overhead_pct`` is the median of the per-round ratios, which
    cancels any drift slower than one round.

    Tracing may only add headers, never bodies. Bodies across arms are
    compared with the batching A/B's equivalence check rather than
    bytewise: batch-bucket composition is timing-dependent, and bucket
    size reaches the scores as the documented ulp-level gemv-vs-gemm
    accumulation drift (``responses_identical`` would flap on scheduling
    noise even with tracing compiled out entirely).
    """
    from predictionio_tpu.workflow.create_server import create_query_server
    from predictionio_tpu.workflow.microbatch import BatchConfig

    query = {"user": "u1", "num": 10}
    batching = BatchConfig(window_ms=window_ms, max_batch_size=max_batch_size)
    arms = {"tracing_off": False, "tracing_on": True}
    warmup = max(4 * max_batch_size, concurrency)
    qps: dict[str, list[float]] = {label: [] for label in arms}
    reports: dict[str, dict] = {}
    responses: dict[str, list[bytes]] = {}

    with _synthetic_deployment(engine, users, items, events) as (variant, sizes):
        servers = {}
        try:
            for label, tracing in arms.items():
                thread, service = create_query_server(
                    variant, host="127.0.0.1", port=0,
                    batching=batching, tracing=tracing,
                )
                thread.start()
                servers[label] = (
                    thread, service, f"http://127.0.0.1:{thread.port}"
                )
            for label, (_, _, url) in servers.items():
                _load_in_subprocess(url, concurrency, warmup, query)
                responses[label] = _concurrent_bodies(
                    url, concurrency, sizes["users"]
                )
            # one unmeasured priming pair at full load: the first measured
            # pass after warmup consistently spikes (allocator/scheduler
            # settling), and a transient in either arm lands straight in
            # the round-0 ratio
            for label in arms:
                _load_in_subprocess(
                    servers[label][2], concurrency, requests, query
                )
            for r in range(rounds):
                order = list(arms)
                if r % 2:
                    order.reverse()
                for label in order:
                    rep = _load_in_subprocess(
                        servers[label][2], concurrency, requests, query
                    )
                    qps[label].append(rep["qps"])
                    reports[label] = rep  # last round's latency profile
        finally:
            for thread, service, _ in servers.values():
                thread.stop()
                service.close()

    for label in arms:
        reports[label]["qps_rounds"] = qps[label]
        reports[label]["qps"] = sorted(qps[label])[len(qps[label]) // 2]
    out: dict = {
        "engine": engine,
        "concurrency": concurrency,
        "requests": requests,
        "rounds": rounds,
        **sizes,
        **reports,
    }
    out["responses_identical"] = (
        responses["tracing_off"] == responses["tracing_on"]
    )
    out["responses_equivalent"] = all(
        _responses_equivalent(a, b)
        for a, b in zip(responses["tracing_off"], responses["tracing_on"])
    )
    per_round = [
        round((off - on) / off * 100.0, 2)
        for off, on in zip(qps["tracing_off"], qps["tracing_on"])
        if off
    ]
    out["overhead_pct_rounds"] = per_round
    out["overhead_pct"] = (
        sorted(per_round)[len(per_round) // 2] if per_round else None
    )
    return out


def main(argv: list[str] | None = None) -> int:
    import os

    mask = os.environ.get("PIO_BENCH_AFFINITY")
    if mask and hasattr(os, "sched_setaffinity"):
        # the load-generator child of a --pin-cpus A/B: re-widen to the
        # pre-pin mask the parent recorded, so the generator never
        # measures itself time-slicing the pinned scorer's cores
        try:
            os.sched_setaffinity(0, {int(c) for c in mask.split(",")})
        except (OSError, ValueError):
            pass
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--url", default=None,
        help="target server; omit to run the self-contained batching A/B",
    )
    ap.add_argument(
        "--clients", "--concurrency", dest="clients", type=int, default=None,
        help="concurrent keep-alive clients (default: 8 load / 32 A/B)",
    )
    ap.add_argument("--requests", type=int, default=None,
                    help="total POSTs (default: 400 load / 960 A/B)")
    ap.add_argument("--query", default='{"user": "u1", "num": 4}')
    ap.add_argument(
        "--engine", default="both",
        choices=tuple(AB_ENGINES) + ("both",),
        help="A/B mode: which engine(s) to train and serve",
    )
    ap.add_argument("--batch-window-ms", type=float, default=5.0)
    ap.add_argument("--max-batch-size", type=int, default=64)
    ap.add_argument("--users", type=int, default=None,
                    help="A/B catalog size override (default: per engine)")
    ap.add_argument("--items", type=int, default=None)
    ap.add_argument("--events", type=int, default=None)
    ap.add_argument(
        "--trace-overhead", action="store_true",
        help="run the tracing on/off overhead A/B instead of the"
        " batching A/B",
    )
    ap.add_argument(
        "--client", choices=("http", "raw"), default="http",
        help="load-generator flavor for --url mode: http.client (the"
        " historical baseline client) or a minimal raw-socket client"
        " (~5x less generator python; use when the server outruns the"
        " generator)",
    )
    ap.add_argument(
        "--frontend-workers", default=None, metavar="N[,N...]",
        help="run the multi-process serving sweep instead: single-process"
        " vs SO_REUSEPORT frontend tiers; a single N sweeps 1, 2 and N"
        " workers, a comma list (e.g. '1,2,4,8') sweeps exactly those",
    )
    ap.add_argument(
        "--scorer-shards", default=None, metavar="N[,N...]",
        help="run the sharded serving sweep instead: one arm per scorer"
        " shard count (1 = the single-process baseline; each N>=2 arm"
        " is a full hash-partitioned shard fabric); e.g. '1,2,4'",
    )
    ap.add_argument(
        "--dispatch", choices=("async", "sync", "both"), default="async",
        help="scorer dispatch model for the multi-process sweep arms:"
        " async fast path (default), the sync dispatcher pool, or both"
        " (the sync-vs-async A/B; labels arms workers_N_sync/_async)",
    )
    ap.add_argument(
        "--pin-cpus", action="store_true",
        help="pin frontend workers and scorer to disjoint cores"
        " (sched_setaffinity) in every multi-process sweep arm",
    )
    args = ap.parse_args(argv)
    if args.url:
        print(
            json.dumps(
                run_load(
                    args.url, args.query, args.clients or 8,
                    args.requests or 400, client=args.client,
                )
            )
        )
        return 0
    if args.scorer_shards is not None:
        engines = (
            ["recommendation"] if args.engine == "both" else [args.engine]
        )
        try:
            sweep = tuple(
                int(n) for n in str(args.scorer_shards).split(",")
                if n.strip()
            )
        except ValueError:
            ap.error(
                f"--scorer-shards must be an int or comma list, got "
                f"{args.scorer_shards!r}"
            )
        if len(sweep) == 1:
            sweep = (1,) + sweep
        report = {
            name: run_sharded_ab(
                name,
                concurrency=args.clients or 32,
                requests=args.requests or 2000,
                shards=sweep,
                users=args.users,
                items=args.items,
                events=args.events,
                window_ms=args.batch_window_ms,
                max_batch_size=args.max_batch_size,
            )
            for name in engines
        }
        print(json.dumps(report))
        return 0
    if args.frontend_workers is not None:
        engines = (
            ["recommendation"] if args.engine == "both" else [args.engine]
        )
        try:
            sweep = tuple(
                int(w) for w in str(args.frontend_workers).split(",")
                if w.strip()
            )
        except ValueError:
            ap.error(
                f"--frontend-workers must be an int or comma list, got "
                f"{args.frontend_workers!r}"
            )
        if len(sweep) == 1:
            sweep = (1, 2) + sweep
        dispatch = (
            ("sync", "async") if args.dispatch == "both" else args.dispatch
        )
        report = {
            name: run_multiproc_ab(
                name,
                concurrency=args.clients or 32,
                requests=args.requests or 2000,
                workers=sweep,
                users=args.users,
                items=args.items,
                events=args.events,
                window_ms=args.batch_window_ms,
                max_batch_size=args.max_batch_size,
                dispatch=dispatch,
                pin_cpus=args.pin_cpus,
            )
            for name in engines
        }
        print(json.dumps(report))
        return 0
    engines = list(AB_ENGINES) if args.engine == "both" else [args.engine]
    ab = run_trace_ab if args.trace_overhead else run_ab
    report = {
        name: ab(
            name,
            concurrency=args.clients or 32,
            requests=args.requests or 960,
            users=args.users,
            items=args.items,
            events=args.events,
            window_ms=args.batch_window_ms,
            max_batch_size=args.max_batch_size,
        )
        for name in engines
    }
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
