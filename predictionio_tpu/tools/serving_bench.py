"""Concurrent-load latency benchmark for a deployed Query Server.

The reference's serving SLO story is N stateless query servers behind a
load balancer (SURVEY.md section 5.3); the <5 ms p50 target (BASELINE)
is only meaningful under concurrent keep-alive load, not a single
sequential client. This tool drives ``POST /queries.json`` from N
threads, each with its own persistent HTTP connection, and reports the
latency distribution plus aggregate throughput:

    python -m predictionio_tpu.tools.serving_bench \
        --url http://127.0.0.1:8000 --clients 8 --requests 400 \
        --query '{"user": "u1", "num": 4}'

Prints one JSON line; also importable (``run_load``) for tests.
"""

from __future__ import annotations

import argparse
import http.client
import json
import threading
import time
import urllib.parse


def _percentile(sorted_ms: list[float], q: float) -> float | None:
    if not sorted_ms:
        return None  # JSON null: NaN is not valid RFC 8259 output
    idx = min(int(q * len(sorted_ms)), len(sorted_ms) - 1)
    return round(sorted_ms[idx], 3)


def run_load(
    url: str,
    query: dict | str,
    clients: int = 8,
    requests: int = 400,
    timeout: float = 30.0,
) -> dict:
    """N keep-alive clients, ``requests`` total POSTs; latency stats in ms.

    Every client thread owns one persistent connection (the reference
    SDKs' connection-pool behavior); failures are counted, not raised,
    so a mid-run hiccup yields a truthful report instead of a stack
    trace.
    """
    parsed = urllib.parse.urlsplit(url)
    body = query if isinstance(query, str) else json.dumps(query)
    payload = body.encode()
    clients = min(clients, requests) or 1
    base, extra = divmod(requests, clients)
    # distribute the remainder so exactly ``requests`` POSTs are sent
    counts = [base + (1 if k < extra else 0) for k in range(clients)]
    lat_ms: list[list[float]] = [[] for _ in range(clients)]
    failures = [0] * clients
    start_gate = threading.Event()

    def client(k: int) -> None:
        conn_cls = (
            http.client.HTTPSConnection
            if parsed.scheme == "https"
            else http.client.HTTPConnection
        )
        conn = conn_cls(parsed.hostname, parsed.port, timeout=timeout)
        start_gate.wait()
        for _ in range(counts[k]):
            t0 = time.perf_counter()
            try:
                conn.request(
                    "POST", "/queries.json", payload,
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    failures[k] += 1
                    continue
            except (OSError, http.client.HTTPException):
                # HTTPException covers malformed responses (a garbled LB
                # status line) -- a dead thread would under-report silently
                failures[k] += 1
                conn.close()
                continue
            lat_ms[k].append((time.perf_counter() - t0) * 1000.0)
        conn.close()

    threads = [
        threading.Thread(target=client, args=(k,), daemon=True)
        for k in range(clients)
    ]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    start_gate.set()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0

    flat = sorted(x for per in lat_ms for x in per)
    return {
        "clients": clients,
        "requests_ok": len(flat),
        "failures": sum(failures),
        "p50_ms": _percentile(flat, 0.50),
        "p90_ms": _percentile(flat, 0.90),
        "p99_ms": _percentile(flat, 0.99),
        "qps": round(len(flat) / wall_s, 1) if wall_s > 0 else 0.0,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=400)
    ap.add_argument("--query", default='{"user": "u1", "num": 4}')
    args = ap.parse_args(argv)
    print(
        json.dumps(
            run_load(args.url, args.query, args.clients, args.requests)
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
