"""``pio eventserver`` (and later dashboard/adminserver) verbs."""

from __future__ import annotations

import argparse


def register(sub: argparse._SubParsersAction) -> None:
    es = sub.add_parser("eventserver", help="start the Event Server")
    es.add_argument("--ip", default="0.0.0.0")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--stats", action="store_true", help="enable /stats.json")
    es.add_argument("--ssl-cert", default=None, help="PEM cert: serve HTTPS")
    es.add_argument("--ssl-key", default=None, help="PEM key (if not in cert)")
    es.set_defaults(func=cmd_eventserver)

    db = sub.add_parser("dashboard", help="start the evaluation dashboard")
    db.add_argument("--ip", default="0.0.0.0")
    db.add_argument("--port", type=int, default=9000)
    db.set_defaults(func=cmd_dashboard)

    admin = sub.add_parser("adminserver", help="start the admin REST server")
    admin.add_argument("--ip", default="0.0.0.0")
    admin.add_argument("--port", type=int, default=7071)
    admin.set_defaults(func=cmd_adminserver)

    shell = sub.add_parser("shell", help="interactive console with the runtime preloaded")
    shell.set_defaults(func=cmd_shell)


def cmd_eventserver(args: argparse.Namespace) -> int:
    from predictionio_tpu.data.api.eventserver import run_event_server

    run_event_server(
        host=args.ip, port=args.port, stats=args.stats,
        ssl_cert=args.ssl_cert, ssl_key=args.ssl_key,
    )
    return 0


def cmd_dashboard(args: argparse.Namespace) -> int:
    from predictionio_tpu.tools.dashboard import run_dashboard

    run_dashboard(host=args.ip, port=args.port)
    return 0


def cmd_adminserver(args: argparse.Namespace) -> int:
    from predictionio_tpu.tools.adminserver import run_admin_server

    run_admin_server(host=args.ip, port=args.port)
    return 0


def cmd_shell(args: argparse.Namespace) -> int:
    from predictionio_tpu.tools.shell import run_shell

    return run_shell()
