"""``pio eventserver`` (and later dashboard/adminserver) verbs."""

from __future__ import annotations

import argparse


def register(sub: argparse._SubParsersAction) -> None:
    es = sub.add_parser("eventserver", help="start the Event Server")
    es.add_argument("--ip", default="0.0.0.0")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--stats", action="store_true", help="enable /stats.json")
    es.set_defaults(func=cmd_eventserver)


def cmd_eventserver(args: argparse.Namespace) -> int:
    from predictionio_tpu.data.api.eventserver import run_event_server

    run_event_server(host=args.ip, port=args.port, stats=args.stats)
    return 0
