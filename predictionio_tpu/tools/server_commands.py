"""``pio eventserver`` (and later dashboard/adminserver) verbs."""

from __future__ import annotations

import argparse


def register(sub: argparse._SubParsersAction) -> None:
    es = sub.add_parser("eventserver", help="start the Event Server")
    es.add_argument("--ip", default="0.0.0.0")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--stats", action="store_true", help="enable /stats.json")
    es.add_argument("--ssl-cert", default=None, help="PEM cert: serve HTTPS")
    es.add_argument("--ssl-key", default=None, help="PEM key (if not in cert)")
    es.add_argument(
        "--plugin", action="append", default=[], metavar="MODULE:CLASS",
        help="EventServerPlugin to load (repeatable), e.g. my.mod:MyBlocker",
    )
    es.add_argument(
        "--ingest-mode", choices=("sync", "wal"), default="sync",
        help="sync: one storage commit per request (default);"
        " wal: durable WAL ack + background group commit",
    )
    es.add_argument(
        "--ingest-queue-size", type=int, default=2048,
        help="bounded ingest queue; a full queue returns 429 (wal mode)",
    )
    es.add_argument(
        "--group-commit-ms", type=float, default=5.0,
        help="max wait to grow a commit batch (wal mode)",
    )
    es.add_argument(
        "--fsync-policy", choices=("always", "interval", "never"),
        default="always", help="WAL durability vs throughput trade-off",
    )
    es.add_argument(
        "--wal-dir", default=None,
        help="WAL directory (default $PIO_FS_BASEDIR/wal)",
    )
    es.add_argument(
        "--wal-partitions", type=int, default=1, metavar="P",
        help="hash-shard the WAL into P independent durability streams"
        " (per-entity ordering preserved; fsyncs proceed in parallel)."
        " Fixed at log creation: an existing log's on-disk count wins"
        " (wal mode)",
    )
    es.add_argument(
        "--frontend-workers", type=int, default=0, metavar="M",
        help="spawn M SO_REUSEPORT frontend worker processes in front of"
        " the ingest pipeline (0 = single-process listener, the default)",
    )
    es.add_argument(
        "--no-tracing", action="store_true",
        help="disable the span tracer (/traces.json reports enabled=false;"
        " the off path allocates no spans)",
    )
    es.add_argument(
        "--trace-sample", type=float, default=None, metavar="RATE",
        help="head-sampling rate (0..1) for headerless root traces;"
        " requests with a traceparent header always trace (default:"
        " $PIO_TRACE_SAMPLE or 0.125)",
    )
    es.add_argument(
        "--slow-commit-ms", type=float, default=None, metavar="MS",
        help="log one span-summary line for any group commit slower than"
        " this (off by default)",
    )
    from predictionio_tpu.obs.logs import add_logging_arguments

    add_logging_arguments(es)
    es.set_defaults(func=cmd_eventserver)

    db = sub.add_parser("dashboard", help="start the evaluation dashboard")
    db.add_argument("--ip", default="0.0.0.0")
    db.add_argument("--port", type=int, default=9000)
    add_logging_arguments(db)
    db.set_defaults(func=cmd_dashboard)

    admin = sub.add_parser("adminserver", help="start the admin REST server")
    admin.add_argument("--ip", default="0.0.0.0")
    admin.add_argument("--port", type=int, default=7071)
    add_logging_arguments(admin)
    admin.set_defaults(func=cmd_adminserver)

    shell = sub.add_parser("shell", help="interactive console with the runtime preloaded")
    shell.set_defaults(func=cmd_shell)


def load_plugins(specs: list[str]) -> list:
    """Instantiate ``module.path:ClassName`` EventServerPlugin specs."""
    import importlib

    plugins = []
    for spec in specs:
        module_path, sep, class_name = spec.partition(":")
        if not sep or not module_path or not class_name:
            raise SystemExit(f"--plugin {spec!r}: expected MODULE:CLASS")
        try:
            cls = getattr(importlib.import_module(module_path), class_name)
        except (ImportError, AttributeError) as exc:
            raise SystemExit(f"--plugin {spec!r}: {exc}")
        plugins.append(cls())
    return plugins


def cmd_eventserver(args: argparse.Namespace) -> int:
    from predictionio_tpu.data.api.eventserver import run_event_server
    from predictionio_tpu.data.ingest import IngestConfig
    from predictionio_tpu.obs.logs import configure_logging

    configure_logging(args.log_format)
    run_event_server(
        host=args.ip, port=args.port, stats=args.stats,
        ssl_cert=args.ssl_cert, ssl_key=args.ssl_key,
        plugins=load_plugins(args.plugin),
        ingest_config=IngestConfig(
            mode=args.ingest_mode,
            queue_size=args.ingest_queue_size,
            group_commit_ms=args.group_commit_ms,
            fsync_policy=args.fsync_policy,
            wal_dir=args.wal_dir,
            wal_partitions=args.wal_partitions,
        ),
        tracing=False if args.no_tracing else None,
        trace_sample=args.trace_sample,
        slow_commit_ms=args.slow_commit_ms,
        frontend_workers=args.frontend_workers,
    )
    return 0


def cmd_dashboard(args: argparse.Namespace) -> int:
    from predictionio_tpu.obs.logs import configure_logging
    from predictionio_tpu.tools.dashboard import run_dashboard

    configure_logging(args.log_format)
    run_dashboard(host=args.ip, port=args.port)
    return 0


def cmd_adminserver(args: argparse.Namespace) -> int:
    from predictionio_tpu.obs.logs import configure_logging
    from predictionio_tpu.tools.adminserver import run_admin_server

    configure_logging(args.log_format)
    run_admin_server(host=args.ip, port=args.port)
    return 0


def cmd_shell(args: argparse.Namespace) -> int:
    from predictionio_tpu.tools.shell import run_shell

    return run_shell()
