"""``pio eventserver`` (and later dashboard/adminserver) verbs."""

from __future__ import annotations

import argparse


def register(sub: argparse._SubParsersAction) -> None:
    es = sub.add_parser("eventserver", help="start the Event Server")
    es.add_argument("--ip", default="0.0.0.0")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--stats", action="store_true", help="enable /stats.json")
    es.add_argument("--ssl-cert", default=None, help="PEM cert: serve HTTPS")
    es.add_argument("--ssl-key", default=None, help="PEM key (if not in cert)")
    es.add_argument(
        "--plugin", action="append", default=[], metavar="MODULE:CLASS",
        help="EventServerPlugin to load (repeatable), e.g. my.mod:MyBlocker",
    )
    es.add_argument(
        "--ingest-mode", choices=("sync", "wal"), default="sync",
        help="sync: one storage commit per request (default);"
        " wal: durable WAL ack + background group commit",
    )
    es.add_argument(
        "--ingest-queue-size", type=int, default=2048,
        help="bounded ingest queue; a full queue returns 429 (wal mode)",
    )
    es.add_argument(
        "--group-commit-ms", type=float, default=5.0,
        help="max wait to grow a commit batch (wal mode)",
    )
    es.add_argument(
        "--fsync-policy", choices=("always", "interval", "never"),
        default="always", help="WAL durability vs throughput trade-off",
    )
    es.add_argument(
        "--wal-dir", default=None,
        help="WAL directory (default $PIO_FS_BASEDIR/wal)",
    )
    es.set_defaults(func=cmd_eventserver)

    db = sub.add_parser("dashboard", help="start the evaluation dashboard")
    db.add_argument("--ip", default="0.0.0.0")
    db.add_argument("--port", type=int, default=9000)
    db.set_defaults(func=cmd_dashboard)

    admin = sub.add_parser("adminserver", help="start the admin REST server")
    admin.add_argument("--ip", default="0.0.0.0")
    admin.add_argument("--port", type=int, default=7071)
    admin.set_defaults(func=cmd_adminserver)

    shell = sub.add_parser("shell", help="interactive console with the runtime preloaded")
    shell.set_defaults(func=cmd_shell)


def load_plugins(specs: list[str]) -> list:
    """Instantiate ``module.path:ClassName`` EventServerPlugin specs."""
    import importlib

    plugins = []
    for spec in specs:
        module_path, sep, class_name = spec.partition(":")
        if not sep or not module_path or not class_name:
            raise SystemExit(f"--plugin {spec!r}: expected MODULE:CLASS")
        try:
            cls = getattr(importlib.import_module(module_path), class_name)
        except (ImportError, AttributeError) as exc:
            raise SystemExit(f"--plugin {spec!r}: {exc}")
        plugins.append(cls())
    return plugins


def cmd_eventserver(args: argparse.Namespace) -> int:
    from predictionio_tpu.data.api.eventserver import run_event_server
    from predictionio_tpu.data.ingest import IngestConfig

    run_event_server(
        host=args.ip, port=args.port, stats=args.stats,
        ssl_cert=args.ssl_cert, ssl_key=args.ssl_key,
        plugins=load_plugins(args.plugin),
        ingest_config=IngestConfig(
            mode=args.ingest_mode,
            queue_size=args.ingest_queue_size,
            group_commit_ms=args.group_commit_ms,
            fsync_policy=args.fsync_policy,
            wal_dir=args.wal_dir,
        ),
    )
    return 0


def cmd_dashboard(args: argparse.Namespace) -> int:
    from predictionio_tpu.tools.dashboard import run_dashboard

    run_dashboard(host=args.ip, port=args.port)
    return 0


def cmd_adminserver(args: argparse.Namespace) -> int:
    from predictionio_tpu.tools.adminserver import run_admin_server

    run_admin_server(host=args.ip, port=args.port)
    return 0


def cmd_shell(args: argparse.Namespace) -> int:
    from predictionio_tpu.tools.shell import run_shell

    return run_shell()
