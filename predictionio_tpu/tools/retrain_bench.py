"""Continuous-learning freshness A/B: ingest -> visible-in-query latency.

Usage::

    python -m predictionio_tpu.tools.retrain_bench [--probes 5]

Measures ``online_freshness_seconds`` -- the wall time between an event's
durable ingest (WAL append + storage flush + checkpoint, the exact cycle
the event server's group-commit pipeline runs) and the FIRST
``/queries.json`` response that reflects it -- under concurrent serving
load, for two arms sharing one deployment:

- **foldin**  -- ``pio retrain --follow`` semantics: the loop tails the
  WAL, refreshes the snapshot, fold-in-solves the touched user rows, and
  hot-swaps the query server (``online.loop``);
- **full**    -- the same loop forced to escalate (``max_touched_frac=0``):
  every delta triggers a complete ``run_train`` + swap, the pre-PR-9
  freshness floor.

Each probe ingests one event for a PREVIOUSLY UNKNOWN user and polls the
query server until that user's recommendations turn non-empty -- a
response only a model reflecting the event can produce. Load clients
hammer known users throughout; the report asserts their error count is
zero (hot swaps must drop nothing).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

from predictionio_tpu.data import storage as storage_registry
from predictionio_tpu.tools.ingest_bench import _Env

APP = "RetrainBenchApp"
APP_ID = 1


def _engine_json(workdir: str, rank: int, iterations: int) -> str:
    path = os.path.join(workdir, "engine.json")
    with open(path, "w") as f:
        json.dump(
            {
                "id": "retrain-bench",
                "engineFactory": (
                    "predictionio_tpu.models.recommendation.engine"
                    ".engine_factory"
                ),
                "datasource": {"params": {"appName": APP}},
                "algorithms": [
                    {
                        "name": "als",
                        "params": {
                            "rank": rank,
                            "numIterations": iterations,
                            "seed": 7,
                            "checkpointInterval": 0,
                        },
                    }
                ],
            },
            f,
        )
    return path


def _populate(le, events: int, users: int, items: int) -> None:
    import datetime as _dt

    from predictionio_tpu.data import DataMap, Event

    rng = np.random.default_rng(17)
    base = _dt.datetime.now(_dt.timezone.utc) - _dt.timedelta(hours=1)
    le.batch_insert(
        [
            Event(
                event="rate",
                entity_type="user",
                entity_id=f"u{rng.integers(0, users)}",
                target_entity_type="item",
                target_entity_id=f"i{rng.integers(0, items)}",
                properties=DataMap({"rating": float(rng.integers(1, 6))}),
                event_time=base + _dt.timedelta(milliseconds=13 * k),
            )
            for k in range(events)
        ],
        app_id=APP_ID,
    )


def _timed_events(events: int, users: int, items: int) -> list:
    """The seeded rating stream with a FIXED time base (13 ms spacing):
    every index maps to one replayable timestamp, so the quality arm's
    split boundary is an exact `--split-time`, not a wall-clock race."""
    import datetime as _dt

    from predictionio_tpu.data import DataMap, Event

    rng = np.random.default_rng(17)
    base = _dt.datetime(2024, 1, 1, tzinfo=_dt.timezone.utc)
    return [
        Event(
            event="rate",
            entity_type="user",
            entity_id=f"u{rng.integers(0, users)}",
            target_entity_type="item",
            target_entity_id=f"i{rng.integers(0, items)}",
            properties=DataMap({"rating": float(rng.integers(1, 6))}),
            event_time=base + _dt.timedelta(milliseconds=13 * k),
        )
        for k in range(events)
    ]


def _ingest_one(wal, le, user: str, item: str) -> float:
    """One durable ingest through the WAL pipeline's exact cycle; returns
    the ack time (the freshness clock's zero). Against a
    :class:`PartitionedWal` the event lands in the partition its entity
    hashes to -- the event server's routing rule."""
    from predictionio_tpu.data import DataMap, Event
    from predictionio_tpu.data.ingest import partition_of, wal_payload

    event = Event(
        event="rate",
        entity_type="user",
        entity_id=user,
        target_entity_type="item",
        target_entity_id=item,
        properties=DataMap({"rating": 5.0}),
    ).with_id()
    target = (
        wal.part(partition_of(event, wal.partitions))
        if hasattr(wal, "parts")
        else wal
    )
    seqno = target.append(wal_payload(event, APP_ID, None))
    target.sync()
    t_ack = time.perf_counter()
    le.insert_batch([(event, APP_ID, None)], on_duplicate="ignore")
    target.checkpoint(seqno)
    return t_ack


def _post_query(url: str, body: dict, timeout: float = 15.0):
    req = urllib.request.Request(
        f"{url}/queries.json",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read().decode())


def _measure_arm(
    label: str,
    server_url: str,
    variant,
    wal,
    budget,
    probes: int,
    load_clients: int,
    freshness_timeout_s: float,
    interval_s: float,
    ingest_load_clients: int = 0,
) -> dict:
    from predictionio_tpu.online.loop import RetrainConfig, RetrainLoop

    loop = RetrainLoop(
        variant,
        RetrainConfig(
            interval_s=interval_s,
            notify_urls=[server_url],
            budget=budget,
        ),
    )
    loop_thread = threading.Thread(target=loop.run_follow, daemon=True)
    loop_thread.start()

    stop = threading.Event()
    load_errors = [0]
    load_count = [0]

    def load_worker(k: int) -> None:
        rng = np.random.default_rng(100 + k)
        while not stop.is_set():
            try:
                status, _ = _post_query(
                    server_url, {"user": f"u{rng.integers(0, 20)}", "num": 3}
                )
                if status != 200:
                    load_errors[0] += 1
            except Exception:
                load_errors[0] += 1
            load_count[0] += 1

    ingest_load_count = [0]
    ingest_load_errors = [0]

    def ingest_load_worker(k: int) -> None:
        """Sustained background write pressure on KNOWN users: every event
        rides the full durable cycle, so the follower must keep folding
        this stream while the probes measure freshness."""
        rng = np.random.default_rng(500 + k)
        le = storage_registry.get_l_events()
        while not stop.is_set():
            try:
                _ingest_one(
                    wal, le,
                    user=f"u{rng.integers(0, 20)}",
                    item=f"i{rng.integers(0, 10)}",
                )
                ingest_load_count[0] += 1
            except Exception:
                ingest_load_errors[0] += 1
            time.sleep(0.005)

    workers = [
        threading.Thread(target=load_worker, args=(k,), daemon=True)
        for k in range(load_clients)
    ] + [
        threading.Thread(target=ingest_load_worker, args=(k,), daemon=True)
        for k in range(ingest_load_clients)
    ]
    for w in workers:
        w.start()

    latencies = []
    timeouts = 0
    try:
        for k in range(probes):
            user = f"fresh-{label}-{k}"
            t_ack = _ingest_one(wal, le=storage_registry.get_l_events(),
                                user=user, item=f"i{k % 10}")
            deadline = t_ack + freshness_timeout_s
            seen = None
            while time.perf_counter() < deadline:
                try:
                    status, body = _post_query(server_url, {"user": user, "num": 3})
                except Exception:
                    time.sleep(0.05)
                    continue
                if status == 200 and body.get("itemScores"):
                    seen = time.perf_counter()
                    break
                time.sleep(0.05)
            if seen is None:
                timeouts += 1
            else:
                latencies.append(seen - t_ack)
    finally:
        stop.set()
        loop.stop()
        loop_thread.join(timeout=30)
        for w in workers:
            w.join(timeout=10)
    return {
        "probes": probes,
        "timeouts": timeouts,
        "freshness_s_median": (
            round(statistics.median(latencies), 3) if latencies else None
        ),
        "freshness_s_max": round(max(latencies), 3) if latencies else None,
        "load_requests": load_count[0],
        "load_errors": load_errors[0],
        "ingest_load_events": ingest_load_count[0],
        "ingest_load_errors": ingest_load_errors[0],
        "cycles": dict(loop.cycles),
    }


def run_ab(
    events: int = 2_000,
    users: int = 60,
    items: int = 30,
    rank: int = 8,
    iterations: int = 3,
    probes: int = 4,
    load_clients: int = 2,
    freshness_timeout_s: float = 30.0,
    interval_s: float = 0.2,
    workdir: str | None = None,
    full_retrain_arm: bool = True,
    wal_partitions: int = 1,
    ingest_load_clients: int = 0,
) -> dict:
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.data.wal import PartitionedWal
    from predictionio_tpu.online.foldin import StalenessBudget
    from predictionio_tpu.workflow.core_workflow import run_train
    from predictionio_tpu.workflow.create_server import create_query_server
    from predictionio_tpu.workflow.json_extractor import load_engine_variant

    report: dict = {
        "events": events, "users": users, "items": items, "rank": rank,
        "wal_partitions": wal_partitions,
        "ingest_load_clients": ingest_load_clients,
    }
    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="pio_retrain_bench_")
    with _Env(workdir):
        storage_registry.get_meta_data_apps().insert(App(name=APP))
        le = storage_registry.get_l_events()
        le.init_channel(APP_ID)
        _populate(le, events, users, items)
        variant = load_engine_variant(_engine_json(workdir, rank, iterations))
        t0 = time.perf_counter()
        run_train(variant)
        report["train_seconds"] = round(time.perf_counter() - t0, 3)

        wal = PartitionedWal(os.path.join(workdir, "wal"),
                             partitions=wal_partitions)
        thread, service = create_query_server(variant, host="127.0.0.1", port=0)
        thread.start()
        url = f"http://127.0.0.1:{thread.port}"
        try:
            report["foldin"] = _measure_arm(
                "fold", url, variant, wal, StalenessBudget(
                    max_touched_frac=1.0, max_item_growth_frac=1.0,
                    max_user_growth_frac=10.0,
                ),
                probes, load_clients, freshness_timeout_s, interval_s,
                ingest_load_clients=ingest_load_clients,
            )
            if full_retrain_arm:
                report["full_retrain"] = _measure_arm(
                    "full", url, variant, wal,
                    StalenessBudget(max_touched_frac=0.0),
                    probes, load_clients, freshness_timeout_s, interval_s,
                    ingest_load_clients=ingest_load_clients,
                )
                a = report["foldin"].get("freshness_s_median")
                b = report["full_retrain"].get("freshness_s_median")
                if a and b:
                    report["foldin_speedup"] = round(b / a, 2)
        finally:
            thread.stop()
            service.close()
            wal.close()
    if own_tmp:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return report


def run_quality(
    events: int = 2_000,
    users: int = 60,
    items: int = 30,
    rank: int = 8,
    iterations: int = 3,
    base_frac: float = 0.6,
    split_frac: float = 0.8,
    k: int = 10,
    workdir: str | None = None,
) -> dict:
    """The freshness A/B's quality counterpart: does fold-in COST accuracy?

    Leakage-free staging on one seeded, fixed-time-base stream:

    1. the prefix ``[0, base_frac)`` trains the base model (``run_train``);
    2. the window ``[base_frac, split_frac)`` arrives through the durable
       ingest cycle (store + WAL), and ONE ``pio retrain`` catch-up cycle
       folds it in, publishing a registry generation;
    3. the holdout ``[split_frac, 1)`` lands store-only -- the future
       neither arm may see at train time;
    4. ``pio eval --replay`` at the boundary scores the folded generation
       (``--model-version``) against a forced-full-retrain on the exact
       same prefix, reporting the NDCG@k the shortcut gave up.
    """
    from predictionio_tpu.data.ingest import wal_payload
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.data.wal import WriteAheadLog
    from predictionio_tpu.eval.replay import run_replay_eval
    from predictionio_tpu.online.foldin import StalenessBudget
    from predictionio_tpu.online.loop import RetrainConfig, RetrainLoop
    from predictionio_tpu.online.registry import ModelRegistry
    from predictionio_tpu.workflow.core_workflow import run_train
    from predictionio_tpu.workflow.json_extractor import load_engine_variant

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="pio_retrain_quality_")
    i_base = int(events * base_frac)
    i_split = int(events * split_frac)
    stream = _timed_events(events, users, items)
    t_split_iso = stream[i_split].event_time.isoformat()
    ndcg_key = f"ndcg_at_{k}"
    report: dict = {
        "events": events, "users": users, "items": items, "rank": rank,
        "base_events": i_base, "window_events": i_split - i_base,
        "holdout_events": events - i_split, "split_time": t_split_iso,
    }
    with _Env(workdir):
        storage_registry.get_meta_data_apps().insert(App(name=APP))
        le = storage_registry.get_l_events()
        le.init_channel(APP_ID)
        le.batch_insert(stream[:i_base], app_id=APP_ID)
        variant = load_engine_variant(_engine_json(workdir, rank, iterations))
        run_train(variant)

        wal = WriteAheadLog(os.path.join(workdir, "wal"))
        try:
            window = [e.with_id() for e in stream[i_base:i_split]]
            seqno = 0
            for event in window:
                seqno = wal.append(wal_payload(event, APP_ID, None))
            wal.sync()
            le.insert_batch([(e, APP_ID, None) for e in window],
                            on_duplicate="ignore")
            wal.checkpoint(seqno)
            loop = RetrainLoop(
                variant,
                RetrainConfig(
                    interval_s=0.1,
                    budget=StalenessBudget(
                        max_touched_frac=1.0,
                        max_item_growth_frac=1.0,
                        max_user_growth_frac=10.0,
                    ),
                    max_cycles=1,
                ),
            )
            report["cycles"] = loop.run_follow()
            entry = ModelRegistry.for_variant(variant).latest()
            if entry is None:
                raise RuntimeError(
                    "fold-in cycle published no registry generation"
                )
            report["folded_version"] = entry.version
            report["folded_source"] = entry.source
            # the future: store-only, invisible to both arms' training
            le.batch_insert(stream[i_split:], app_id=APP_ID)
            folded = run_replay_eval(
                variant, split_time=t_split_iso, k=k,
                model_version=entry.version, retrieval_guard=False,
            )
            full = run_replay_eval(
                variant, split_time=t_split_iso, k=k, retrieval_guard=False,
            )
        finally:
            wal.close()
    report["folded_metrics"] = folded["metrics"]
    report["full_retrain_metrics"] = full["metrics"]
    report["holdout_users"] = folded["split"]["holdout_users"]
    a, b = folded["metrics"][ndcg_key], full["metrics"][ndcg_key]
    report["ndcg_delta_full_minus_folded"] = (
        round(b - a, 6) if a is not None and b is not None else None
    )
    if own_tmp:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return report


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--events", type=int, default=2_000)
    parser.add_argument("--users", type=int, default=60)
    parser.add_argument("--items", type=int, default=30)
    parser.add_argument("--rank", type=int, default=8)
    parser.add_argument("--iterations", type=int, default=3)
    parser.add_argument("--probes", type=int, default=4)
    parser.add_argument("--load-clients", type=int, default=2)
    parser.add_argument("--wal-partitions", type=int, default=1,
                        help="ingest WAL partition count (the follower"
                        " discovers the layout off disk)")
    parser.add_argument("--ingest-load-clients", type=int, default=0,
                        help="background durable-ingest writer threads"
                        " running during each freshness arm")
    parser.add_argument("--no-full-retrain-arm", action="store_true")
    parser.add_argument(
        "--quality", action="store_true",
        help="measure fold-in accuracy instead of freshness: folded model"
        " vs forced-full-retrain on the same held-out replay split"
        " (NDCG delta)",
    )
    parser.add_argument("--split-frac", type=float, default=0.8,
                        help="--quality replay boundary (default 0.8)")
    parser.add_argument("--k", type=int, default=10,
                        help="--quality ranking cutoff (default 10)")
    args = parser.parse_args(argv)
    if args.quality:
        report = run_quality(
            events=args.events,
            users=args.users,
            items=args.items,
            rank=args.rank,
            iterations=args.iterations,
            split_frac=args.split_frac,
            k=args.k,
        )
    else:
        report = run_ab(
            events=args.events,
            users=args.users,
            items=args.items,
            rank=args.rank,
            iterations=args.iterations,
            probes=args.probes,
            load_clients=args.load_clients,
            full_retrain_arm=not args.no_full_retrain_arm,
            wal_partitions=args.wal_partitions,
            ingest_load_clients=args.ingest_load_clients,
        )
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
