"""Continuous-learning freshness A/B: ingest -> visible-in-query latency.

Usage::

    python -m predictionio_tpu.tools.retrain_bench [--probes 5]

Measures ``online_freshness_seconds`` -- the wall time between an event's
durable ingest (WAL append + storage flush + checkpoint, the exact cycle
the event server's group-commit pipeline runs) and the FIRST
``/queries.json`` response that reflects it -- under concurrent serving
load, for two arms sharing one deployment:

- **foldin**  -- ``pio retrain --follow`` semantics: the loop tails the
  WAL, refreshes the snapshot, fold-in-solves the touched user rows, and
  hot-swaps the query server (``online.loop``);
- **full**    -- the same loop forced to escalate (``max_touched_frac=0``):
  every delta triggers a complete ``run_train`` + swap, the pre-PR-9
  freshness floor.

Each probe ingests one event for a PREVIOUSLY UNKNOWN user and polls the
query server until that user's recommendations turn non-empty -- a
response only a model reflecting the event can produce. Load clients
hammer known users throughout; the report asserts their error count is
zero (hot swaps must drop nothing).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

from predictionio_tpu.data import storage as storage_registry
from predictionio_tpu.tools.ingest_bench import _Env

APP = "RetrainBenchApp"
APP_ID = 1


def _engine_json(workdir: str, rank: int, iterations: int) -> str:
    path = os.path.join(workdir, "engine.json")
    with open(path, "w") as f:
        json.dump(
            {
                "id": "retrain-bench",
                "engineFactory": (
                    "predictionio_tpu.models.recommendation.engine"
                    ".engine_factory"
                ),
                "datasource": {"params": {"appName": APP}},
                "algorithms": [
                    {
                        "name": "als",
                        "params": {
                            "rank": rank,
                            "numIterations": iterations,
                            "seed": 7,
                            "checkpointInterval": 0,
                        },
                    }
                ],
            },
            f,
        )
    return path


def _populate(le, events: int, users: int, items: int) -> None:
    import datetime as _dt

    from predictionio_tpu.data import DataMap, Event

    rng = np.random.default_rng(17)
    base = _dt.datetime.now(_dt.timezone.utc) - _dt.timedelta(hours=1)
    le.batch_insert(
        [
            Event(
                event="rate",
                entity_type="user",
                entity_id=f"u{rng.integers(0, users)}",
                target_entity_type="item",
                target_entity_id=f"i{rng.integers(0, items)}",
                properties=DataMap({"rating": float(rng.integers(1, 6))}),
                event_time=base + _dt.timedelta(milliseconds=13 * k),
            )
            for k in range(events)
        ],
        app_id=APP_ID,
    )


def _ingest_one(wal, le, user: str, item: str) -> float:
    """One durable ingest through the WAL pipeline's exact cycle; returns
    the ack time (the freshness clock's zero)."""
    from predictionio_tpu.data import DataMap, Event
    from predictionio_tpu.data.ingest import wal_payload

    event = Event(
        event="rate",
        entity_type="user",
        entity_id=user,
        target_entity_type="item",
        target_entity_id=item,
        properties=DataMap({"rating": 5.0}),
    ).with_id()
    seqno = wal.append(wal_payload(event, APP_ID, None))
    wal.sync()
    t_ack = time.perf_counter()
    le.insert_batch([(event, APP_ID, None)], on_duplicate="ignore")
    wal.checkpoint(seqno)
    return t_ack


def _post_query(url: str, body: dict, timeout: float = 15.0):
    req = urllib.request.Request(
        f"{url}/queries.json",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read().decode())


def _measure_arm(
    label: str,
    server_url: str,
    variant,
    wal,
    budget,
    probes: int,
    load_clients: int,
    freshness_timeout_s: float,
    interval_s: float,
) -> dict:
    from predictionio_tpu.online.loop import RetrainConfig, RetrainLoop

    loop = RetrainLoop(
        variant,
        RetrainConfig(
            interval_s=interval_s,
            notify_urls=[server_url],
            budget=budget,
        ),
    )
    loop_thread = threading.Thread(target=loop.run_follow, daemon=True)
    loop_thread.start()

    stop = threading.Event()
    load_errors = [0]
    load_count = [0]

    def load_worker(k: int) -> None:
        rng = np.random.default_rng(100 + k)
        while not stop.is_set():
            try:
                status, _ = _post_query(
                    server_url, {"user": f"u{rng.integers(0, 20)}", "num": 3}
                )
                if status != 200:
                    load_errors[0] += 1
            except Exception:
                load_errors[0] += 1
            load_count[0] += 1

    workers = [
        threading.Thread(target=load_worker, args=(k,), daemon=True)
        for k in range(load_clients)
    ]
    for w in workers:
        w.start()

    latencies = []
    timeouts = 0
    try:
        for k in range(probes):
            user = f"fresh-{label}-{k}"
            t_ack = _ingest_one(wal, le=storage_registry.get_l_events(),
                                user=user, item=f"i{k % 10}")
            deadline = t_ack + freshness_timeout_s
            seen = None
            while time.perf_counter() < deadline:
                try:
                    status, body = _post_query(server_url, {"user": user, "num": 3})
                except Exception:
                    time.sleep(0.05)
                    continue
                if status == 200 and body.get("itemScores"):
                    seen = time.perf_counter()
                    break
                time.sleep(0.05)
            if seen is None:
                timeouts += 1
            else:
                latencies.append(seen - t_ack)
    finally:
        stop.set()
        loop.stop()
        loop_thread.join(timeout=30)
        for w in workers:
            w.join(timeout=10)
    return {
        "probes": probes,
        "timeouts": timeouts,
        "freshness_s_median": (
            round(statistics.median(latencies), 3) if latencies else None
        ),
        "freshness_s_max": round(max(latencies), 3) if latencies else None,
        "load_requests": load_count[0],
        "load_errors": load_errors[0],
        "cycles": dict(loop.cycles),
    }


def run_ab(
    events: int = 2_000,
    users: int = 60,
    items: int = 30,
    rank: int = 8,
    iterations: int = 3,
    probes: int = 4,
    load_clients: int = 2,
    freshness_timeout_s: float = 30.0,
    interval_s: float = 0.2,
    workdir: str | None = None,
    full_retrain_arm: bool = True,
) -> dict:
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.data.wal import WriteAheadLog
    from predictionio_tpu.online.foldin import StalenessBudget
    from predictionio_tpu.workflow.core_workflow import run_train
    from predictionio_tpu.workflow.create_server import create_query_server
    from predictionio_tpu.workflow.json_extractor import load_engine_variant

    report: dict = {
        "events": events, "users": users, "items": items, "rank": rank,
    }
    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="pio_retrain_bench_")
    with _Env(workdir):
        storage_registry.get_meta_data_apps().insert(App(name=APP))
        le = storage_registry.get_l_events()
        le.init_channel(APP_ID)
        _populate(le, events, users, items)
        variant = load_engine_variant(_engine_json(workdir, rank, iterations))
        t0 = time.perf_counter()
        run_train(variant)
        report["train_seconds"] = round(time.perf_counter() - t0, 3)

        wal = WriteAheadLog(os.path.join(workdir, "wal"))
        thread, service = create_query_server(variant, host="127.0.0.1", port=0)
        thread.start()
        url = f"http://127.0.0.1:{thread.port}"
        try:
            report["foldin"] = _measure_arm(
                "fold", url, variant, wal, StalenessBudget(
                    max_touched_frac=1.0, max_item_growth_frac=1.0,
                    max_user_growth_frac=10.0,
                ),
                probes, load_clients, freshness_timeout_s, interval_s,
            )
            if full_retrain_arm:
                report["full_retrain"] = _measure_arm(
                    "full", url, variant, wal,
                    StalenessBudget(max_touched_frac=0.0),
                    probes, load_clients, freshness_timeout_s, interval_s,
                )
                a = report["foldin"].get("freshness_s_median")
                b = report["full_retrain"].get("freshness_s_median")
                if a and b:
                    report["foldin_speedup"] = round(b / a, 2)
        finally:
            thread.stop()
            service.close()
            wal.close()
    if own_tmp:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return report


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--events", type=int, default=2_000)
    parser.add_argument("--users", type=int, default=60)
    parser.add_argument("--items", type=int, default=30)
    parser.add_argument("--rank", type=int, default=8)
    parser.add_argument("--iterations", type=int, default=3)
    parser.add_argument("--probes", type=int, default=4)
    parser.add_argument("--load-clients", type=int, default=2)
    parser.add_argument("--no-full-retrain-arm", action="store_true")
    args = parser.parse_args(argv)
    report = run_ab(
        events=args.events,
        users=args.users,
        items=args.items,
        rank=args.rank,
        iterations=args.iterations,
        probes=args.probes,
        load_clients=args.load_clients,
        full_retrain_arm=not args.no_full_retrain_arm,
    )
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
