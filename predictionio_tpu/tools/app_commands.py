"""``pio app ...`` and ``pio accesskey ...`` verbs.

Behavioral model: reference ``tools/.../console/{App,AccessKey}.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.4 #27): app
new prints appId + access key; channel management validates names; accesskey
supports per-key event whitelists.
"""

from __future__ import annotations

import argparse

from predictionio_tpu.data import storage
from predictionio_tpu.data.storage.base import AccessKey, App, Channel


def register(sub: argparse._SubParsersAction) -> None:
    app = sub.add_parser("app", help="manage apps")
    app_sub = app.add_subparsers(dest="subcommand", required=True)

    new = app_sub.add_parser("new", help="create a new app")
    new.add_argument("name")
    new.add_argument("--description", default="")
    new.add_argument("--access-key", default="", help="use this access key instead of generating one")
    new.set_defaults(func=cmd_app_new)

    app_sub.add_parser("list", help="list apps").set_defaults(func=cmd_app_list)

    show = app_sub.add_parser("show", help="show app details")
    show.add_argument("name")
    show.set_defaults(func=cmd_app_show)

    delete = app_sub.add_parser("delete", help="delete an app and its data")
    delete.add_argument("name")
    delete.add_argument("--force", "-f", action="store_true")
    delete.set_defaults(func=cmd_app_delete)

    data_delete = app_sub.add_parser("data-delete", help="delete an app's event data")
    data_delete.add_argument("name")
    data_delete.add_argument("--channel", default=None)
    data_delete.add_argument("--all", action="store_true", help="delete all channels' data")
    data_delete.add_argument("--force", "-f", action="store_true")
    data_delete.set_defaults(func=cmd_app_data_delete)

    ch_new = app_sub.add_parser("channel-new", help="add a channel to an app")
    ch_new.add_argument("name")
    ch_new.add_argument("channel")
    ch_new.set_defaults(func=cmd_channel_new)

    ch_del = app_sub.add_parser("channel-delete", help="remove a channel and its data")
    ch_del.add_argument("name")
    ch_del.add_argument("channel")
    ch_del.add_argument("--force", "-f", action="store_true")
    ch_del.set_defaults(func=cmd_channel_delete)

    ak = sub.add_parser("accesskey", help="manage access keys")
    ak_sub = ak.add_subparsers(dest="subcommand", required=True)

    ak_new = ak_sub.add_parser("new", help="create an access key for an app")
    ak_new.add_argument("app_name")
    ak_new.add_argument("events", nargs="*", help="optional event whitelist")
    ak_new.add_argument("--access-key", default="")
    ak_new.set_defaults(func=cmd_accesskey_new)

    ak_list = ak_sub.add_parser("list", help="list access keys")
    ak_list.add_argument("app_name", nargs="?")
    ak_list.set_defaults(func=cmd_accesskey_list)

    ak_del = ak_sub.add_parser("delete", help="delete an access key")
    ak_del.add_argument("key")
    ak_del.set_defaults(func=cmd_accesskey_delete)


def _require_app(name: str) -> App:
    app = storage.get_meta_data_apps().get_by_name(name)
    if app is None:
        raise SystemExit(f"Error: app {name!r} does not exist.")
    return app


def cmd_app_new(args: argparse.Namespace) -> int:
    from predictionio_tpu.tools.app_ops import create_app

    try:
        app, key = create_app(args.name, args.description, args.access_key)
    except ValueError as exc:
        print(f"Error: {exc}.")
        return 1
    print("App created:")
    print(f"  Name: {args.name}")
    print(f"  ID: {app.id}")
    print(f"  Access Key: {key}")
    return 0


def cmd_app_list(args: argparse.Namespace) -> int:
    keys = storage.get_meta_data_access_keys()
    print(f"{'Name':<24} {'ID':<6} Access Key")
    for app in storage.get_meta_data_apps().get_all():
        app_keys = keys.get_by_app_id(app.id)
        first = app_keys[0].key if app_keys else ""
        print(f"{app.name:<24} {app.id:<6} {first}")
    return 0


def cmd_app_show(args: argparse.Namespace) -> int:
    app = _require_app(args.name)
    print(f"  Name: {app.name}")
    print(f"  ID: {app.id}")
    print(f"  Description: {app.description}")
    for ak in storage.get_meta_data_access_keys().get_by_app_id(app.id):
        allowed = ", ".join(ak.events) if ak.events else "(all)"
        print(f"  Access Key: {ak.key} | Events: {allowed}")
    for ch in storage.get_meta_data_channels().get_by_app(app.id):
        print(f"  Channel: {ch.name} (ID {ch.id})")
    return 0


def cmd_app_delete(args: argparse.Namespace) -> int:
    from predictionio_tpu.tools.app_ops import delete_app_cascade

    app = _require_app(args.name)
    if not args.force:
        confirm = input(f"Delete app {app.name!r} and ALL its data? (YES to confirm): ")
        if confirm != "YES":
            print("Aborted.")
            return 1
    delete_app_cascade(app)
    print(f"App {app.name!r} deleted.")
    return 0


def cmd_app_data_delete(args: argparse.Namespace) -> int:
    from predictionio_tpu.tools.app_ops import delete_app_data

    app = _require_app(args.name)
    if not args.force:
        confirm = input(f"Delete event data of app {app.name!r}? (YES to confirm): ")
        if confirm != "YES":
            print("Aborted.")
            return 1
    try:
        delete_app_data(app, channel_name=args.channel, all_channels=args.all)
    except LookupError as exc:
        print(f"Error: {exc}.")
        return 1
    print("Event data deleted.")
    return 0


def cmd_channel_new(args: argparse.Namespace) -> int:
    app = _require_app(args.name)
    if not Channel.is_valid_name(args.channel):
        print(f"Error: invalid channel name {args.channel!r}.")
        return 1
    channels = storage.get_meta_data_channels()
    if any(c.name == args.channel for c in channels.get_by_app(app.id)):
        print(f"Error: channel {args.channel!r} already exists.")
        return 1
    ch_id = channels.insert(Channel(name=args.channel, app_id=app.id))
    storage.get_l_events().init_channel(app.id, ch_id)
    print(f"Channel {args.channel!r} created (ID {ch_id}).")
    return 0


def cmd_channel_delete(args: argparse.Namespace) -> int:
    app = _require_app(args.name)
    channels = storage.get_meta_data_channels()
    match = [c for c in channels.get_by_app(app.id) if c.name == args.channel]
    if not match:
        print(f"Error: channel {args.channel!r} does not exist.")
        return 1
    if not args.force:
        confirm = input(f"Delete channel {args.channel!r} and its data? (YES to confirm): ")
        if confirm != "YES":
            print("Aborted.")
            return 1
    storage.get_l_events().remove_channel(app.id, match[0].id)
    channels.delete(match[0].id)
    print(f"Channel {args.channel!r} deleted.")
    return 0


def cmd_accesskey_new(args: argparse.Namespace) -> int:
    app = _require_app(args.app_name)
    key = storage.get_meta_data_access_keys().insert(
        AccessKey(key=args.access_key, app_id=app.id, events=list(args.events))
    )
    print(f"Access Key: {key}")
    return 0


def cmd_accesskey_list(args: argparse.Namespace) -> int:
    keys = storage.get_meta_data_access_keys()
    records = (
        keys.get_by_app_id(_require_app(args.app_name).id)
        if args.app_name
        else keys.get_all()
    )
    print(f"{'Access Key':<68} {'App ID':<7} Allowed Events")
    for ak in records:
        allowed = ", ".join(ak.events) if ak.events else "(all)"
        print(f"{ak.key:<68} {ak.app_id:<7} {allowed}")
    return 0


def cmd_accesskey_delete(args: argparse.Namespace) -> int:
    keys = storage.get_meta_data_access_keys()
    if keys.get(args.key) is None:
        print("Error: access key not found.")
        return 1
    keys.delete(args.key)
    print("Access key deleted.")
    return 0
