"""Training-data extraction A/B: cold SQL scan vs columnar snapshot replay.

Usage::

    python -m predictionio_tpu.tools.train_bench [--events 2000000]

Three measured phases against a fresh file-backed sqlite store:

- **cold**    -- the pre-snapshot ``pio train`` input path: TWO full
  ``iter_interaction_chunks`` SQL scans (pass-1 counts + pass-2 retention)
  through ``store_coo_chunks``'s per-row python decode;
- **build**   -- ``SnapshotStore.build``: ONE bounded SQL scan spilled into
  memory-mapped column files (what the first snapshot-enabled train pays);
- **replay**  -- both passes replayed from the memmap through
  ``snapshot_coo_chunks``'s vectorized decode (what every later pass,
  process, and train pays) -- the ``train_data_eps`` headline number;

plus an exactness phase: build a snapshot, ingest more events,
**incrementally refresh**, and assert the refreshed snapshot's
``build_als_data_sharded`` output is BIT-identical (same vocab ids, same
bucketed CSR blocks) to a cold SQL rebuild over the same bounded prefix.

Extraction events/sec counts SOURCE rows per wall second for one full
two-pass read (both sides do two passes, so the ratio is the honest
train-input speedup). The synthetic stream mixes "rate" events carrying a
numeric rating with property-less "buy" events, exercising both the
rating and the default-value decode paths.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import json
import sys
import tempfile
import time

import numpy as np

from predictionio_tpu.data import storage as storage_registry
from predictionio_tpu.tools.ingest_bench import _Env

APP_ID = 1
EVENT_NAMES = ["rate", "buy"]


def _populate(
    le, n_events: int, n_users: int, n_items: int, seed: int = 7,
    start: _dt.datetime | None = None, batch: int = 20_000,
) -> float:
    """Insert ``n_events`` synthetic interactions with strictly increasing
    event times; returns insert seconds."""
    from predictionio_tpu.data import DataMap, Event

    rng = np.random.default_rng(seed)
    base = start or _dt.datetime(2024, 1, 1, tzinfo=_dt.timezone.utc)
    t0 = time.perf_counter()
    for lo in range(0, n_events, batch):
        n = min(batch, n_events - lo)
        uu = rng.integers(0, n_users, n)
        ii = rng.integers(0, n_items, n)
        rr = rng.integers(1, 6, n)
        events = [
            Event(
                event="buy" if (lo + k) % 5 == 0 else "rate",
                entity_type="user",
                entity_id=f"u{uu[k]}",
                target_entity_type="item",
                target_entity_id=f"i{ii[k]}",
                properties=(
                    DataMap({})
                    if (lo + k) % 5 == 0
                    else DataMap({"rating": float(rr[k])})
                ),
                event_time=base + _dt.timedelta(milliseconds=37 * (lo + k)),
            )
            for k in range(n)
        ]
        le.batch_insert(events, app_id=APP_ID)
    return time.perf_counter() - t0


def _two_pass(source) -> tuple[float, int]:
    """One full two-pass read (counts, then consume): (seconds, edges)."""
    from predictionio_tpu.parallel.reader import _grow_bincount

    t0 = time.perf_counter()
    cnt_u = np.zeros(0, np.int64)
    cnt_i = np.zeros(0, np.int64)
    for uu, ii, _vv, _tt in source():
        cnt_u = _grow_bincount(cnt_u, uu)
        cnt_i = _grow_bincount(cnt_i, ii)
    edges = 0
    for uu, _ii, vv, tt in source():
        edges += len(uu)
        float(vv[-1] if len(vv) else 0.0)
        float(tt[-1] if len(tt) else 0.0)
    return time.perf_counter() - t0, edges


def als_data_identical(a, b) -> list[str]:
    """Field-by-field bit-equality of two ALSData layouts; returns the
    list of differences (empty = identical)."""
    diffs: list[str] = []
    for side_name in ("by_row", "by_col"):
        sa, sb = getattr(a, side_name), getattr(b, side_name)
        for attr in ("num_rows", "total_slots", "global_rows", "retained_edges"):
            if getattr(sa, attr) != getattr(sb, attr):
                diffs.append(f"{side_name}.{attr}")
        if not np.array_equal(sa.slot_of, sb.slot_of):
            diffs.append(f"{side_name}.slot_of")
        if len(sa.blocks) != len(sb.blocks):
            diffs.append(f"{side_name}.blocks(len)")
            continue
        for bi, (ba, bb) in enumerate(zip(sa.blocks, sb.blocks)):
            for attr in ("indices", "values", "mask"):
                if not np.array_equal(getattr(ba, attr), getattr(bb, attr)):
                    diffs.append(f"{side_name}.blocks[{bi}].{attr}")
    return diffs


def _refresh_identity_check(
    workdir: str, n_events: int, n_users: int, n_items: int,
    chunk_rows: int,
) -> dict:
    """Snapshot -> ingest more -> refresh -> train must equal a cold
    bounded rebuild bit-for-bit."""
    from predictionio_tpu.data.snapshot import SnapshotSpec, SnapshotStore
    from predictionio_tpu.parallel.als import ALSConfig
    from predictionio_tpu.parallel.mesh import local_mesh
    from predictionio_tpu.parallel.reader import (
        build_als_data_sharded,
        snapshot_coo_chunks,
        store_coo_chunks,
    )

    report: dict = {"events_initial": n_events, "events_appended": n_events // 4}
    with _Env(workdir):
        le = storage_registry.get_l_events()
        le.init_channel(APP_ID)
        _populate(le, n_events, n_users, n_items, seed=11)
        t1 = _dt.datetime.now(_dt.timezone.utc)
        spec = SnapshotSpec(
            app_id=APP_ID, event_names=tuple(EVENT_NAMES)
        )
        store = SnapshotStore(workdir + "/snapshots", spec)
        store.build(le, t1, chunk_rows=chunk_rows)
        # second batch lands AFTER the first snapshot's coverage boundary
        # and strictly BEFORE the next bound t2 (bounds are arbitrary
        # instants, not wall-clock "now")
        _populate(
            le, n_events // 4, n_users, n_items, seed=13,
            start=t1 + _dt.timedelta(milliseconds=1),
        )
        t2 = t1 + _dt.timedelta(hours=12)
        t0 = time.perf_counter()
        snap = store.refresh(le, t2, chunk_rows=chunk_rows)
        report["refresh_seconds"] = round(time.perf_counter() - t0, 3)
        report["rows_after_refresh"] = len(snap)

        mesh = local_mesh(1, 1)
        cfg = ALSConfig(rank=4, buckets=2, max_len=64)
        cold_src, cold_u, cold_i = store_coo_chunks(
            le, APP_ID, event_names=EVENT_NAMES, chunk_rows=chunk_rows,
            until_time=t2,
        )
        cold = build_als_data_sharded(cold_src, None, None, cfg, mesh)
        snap_src, snap_u, snap_i = snapshot_coo_chunks(
            snap, chunk_rows=chunk_rows
        )
        warm = build_als_data_sharded(snap_src, None, None, cfg, mesh)
        diffs = als_data_identical(cold, warm)
        if cold_u.ids != snap_u.ids:
            diffs.append("user_vocab")
        if cold_i.ids != snap_i.ids:
            diffs.append("item_vocab")
        report["differences"] = diffs
        report["bit_identical"] = not diffs
    return report


def run_ab(
    events: int = 2_000_000,
    users: int = 100_000,
    items: int = 20_000,
    identity_events: int = 200_000,
    chunk_rows: int = 262_144,
    workdir: str | None = None,
) -> dict:
    from predictionio_tpu.data.snapshot import SnapshotSpec, SnapshotStore
    from predictionio_tpu.parallel.reader import (
        snapshot_coo_chunks,
        store_coo_chunks,
    )

    report: dict = {"events": events, "users": users, "items": items}
    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="pio_train_bench_")

    with _Env(workdir + "/ab"):
        le = storage_registry.get_l_events()
        le.init_channel(APP_ID)
        report["populate_seconds"] = round(
            _populate(le, events, users, items), 3
        )
        until = _dt.datetime.now(_dt.timezone.utc)

        # -- A: cold SQL extraction (two scans, per-row decode) ------------
        source, _u, _i = store_coo_chunks(
            le, APP_ID, event_names=EVENT_NAMES, chunk_rows=chunk_rows,
            until_time=until,
        )
        seconds, edges = _two_pass(source)
        report["cold"] = {
            "seconds": round(seconds, 3),
            "eps": round(events / seconds, 1),
            "edges": edges,
        }

        # -- B: snapshot build (ONE scan + spill), then memmap replay ------
        spec = SnapshotSpec(app_id=APP_ID, event_names=tuple(EVENT_NAMES))
        store = SnapshotStore(workdir + "/ab/snapshots", spec)
        t0 = time.perf_counter()
        snap = store.build(le, until, chunk_rows=chunk_rows)
        report["snapshot_build"] = {
            "seconds": round(time.perf_counter() - t0, 3),
            "rows": len(snap),
        }
        source, _u, _i = snapshot_coo_chunks(snap, chunk_rows=chunk_rows)
        seconds, edges_replay = _two_pass(source)
        report["replay"] = {
            "seconds": round(seconds, 3),
            "eps": round(events / seconds, 1),
            "edges": edges_replay,
        }
        report["edges_match"] = edges_replay == edges
        report["eps_speedup"] = (
            round(report["replay"]["eps"] / report["cold"]["eps"], 2)
            if report["cold"]["eps"]
            else None
        )

    if identity_events:
        report["refresh_identity"] = _refresh_identity_check(
            workdir + "/identity", identity_events, max(users // 10, 50),
            max(items // 10, 20), chunk_rows,
        )

    if own_tmp:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return report


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--events", type=int, default=2_000_000)
    parser.add_argument("--users", type=int, default=100_000)
    parser.add_argument("--items", type=int, default=20_000)
    parser.add_argument("--identity-events", type=int, default=200_000,
                        help="events in the refresh bit-identity phase"
                        " (0 disables; it needs jax for the ALS pack)")
    parser.add_argument("--chunk-rows", type=int, default=262_144)
    args = parser.parse_args(argv)
    report = run_ab(
        events=args.events,
        users=args.users,
        items=args.items,
        identity_events=args.identity_events,
        chunk_rows=args.chunk_rows,
    )
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
