"""``pio start-all / stop-all`` daemon management.

Behavioral model: reference ``bin/pio-start-all``, ``bin/pio-stop-all``,
``bin/pio-daemon.sh`` (apache/predictionio layout, unverified -- SURVEY.md
section 2.1 #2): bring up / tear down the long-running services as detached
background processes. Pidfiles and logs live under ``$PIO_FS_BASEDIR``:

    $PIO_FS_BASEDIR/pids/<service>.pid
    $PIO_FS_BASEDIR/logs/<service>.log

``start-all`` launches the Event Server, the dashboard, and the admin
server (each via ``python -m predictionio_tpu.tools.cli <verb>``);
``stop-all`` terminates whatever the pidfiles point at, ignoring stale
entries. The query server is managed by ``pio deploy``/``undeploy``.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def register(sub: argparse._SubParsersAction) -> None:
    start = sub.add_parser(
        "start-all", help="start event server, dashboard, admin server as daemons"
    )
    start.add_argument("--event-server-port", type=int, default=7070)
    start.add_argument("--dashboard-port", type=int, default=9000)
    start.add_argument("--admin-port", type=int, default=7071)
    start.add_argument("--stats", action="store_true", help="event server /stats.json")
    start.set_defaults(func=cmd_start_all)

    stop = sub.add_parser("stop-all", help="stop daemons started by start-all")
    stop.set_defaults(func=cmd_stop_all)


def _base_dir() -> str:
    from predictionio_tpu.data.storage import base_dir

    return base_dir()


def _pid_path(service: str) -> str:
    return os.path.join(_base_dir(), "pids", f"{service}.pid")


def _log_path(service: str) -> str:
    return os.path.join(_base_dir(), "logs", f"{service}.log")


def _alive(pid: int) -> bool:
    """True when pid is OUR daemon: alive AND (where /proc allows checking)
    running the pio CLI module. A recycled pid from a stale pidfile must
    never be signalled."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        pass  # EPERM = the process EXISTS, just owned by someone else
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().decode("utf-8", "replace")
    except OSError:
        return True  # no /proc (macOS etc.): best-effort liveness only
    return "predictionio_tpu" in cmdline


def _read_pid(service: str) -> int | None:
    try:
        with open(_pid_path(service)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def _spawn(service: str, argv: list[str]) -> int:
    os.makedirs(os.path.dirname(_pid_path(service)), exist_ok=True)
    os.makedirs(os.path.dirname(_log_path(service)), exist_ok=True)
    log = open(_log_path(service), "a")
    proc = subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.tools.cli", *argv],
        stdout=log,
        stderr=subprocess.STDOUT,
        stdin=subprocess.DEVNULL,
        start_new_session=True,  # detach from this CLI's process group
    )
    log.close()
    with open(_pid_path(service), "w") as f:
        f.write(str(proc.pid))
    return proc.pid


_SERVICES = ("eventserver", "dashboard", "adminserver")


def cmd_start_all(args: argparse.Namespace) -> int:
    plans = {
        "eventserver": ["eventserver", "--port", str(args.event_server_port)]
        + (["--stats"] if args.stats else []),
        "dashboard": ["dashboard", "--port", str(args.dashboard_port)],
        "adminserver": ["adminserver", "--port", str(args.admin_port)],
    }
    rc = 0
    for service in _SERVICES:
        existing = _read_pid(service)
        if existing is not None and _alive(existing):
            print(f"{service}: already running (pid {existing})")
            continue
        pid = _spawn(service, plans[service])
        time.sleep(0.3)
        if _alive(pid):
            print(f"{service}: started (pid {pid}, log {_log_path(service)})")
        else:
            print(f"{service}: FAILED to start -- see {_log_path(service)}")
            rc = 1
    return rc


def cmd_stop_all(args: argparse.Namespace) -> int:
    stopped = 0
    for service in _SERVICES:
        pid = _read_pid(service)
        pidfile = _pid_path(service)
        if pid is None:
            continue
        drop_pidfile = True
        if _alive(pid):
            try:
                os.kill(pid, signal.SIGTERM)
                print(f"{service}: stopped (pid {pid})")
                stopped += 1
            except OSError as exc:
                # daemon still running: keep the pidfile so a later
                # (privileged) stop-all can still find it
                print(f"{service}: could not stop pid {pid}: {exc}")
                drop_pidfile = False
        else:
            print(f"{service}: not running (stale pidfile)")
        if drop_pidfile:
            try:
                os.unlink(pidfile)
            except OSError:
                pass
    if stopped == 0:
        print("Nothing to stop.")
    return 0
