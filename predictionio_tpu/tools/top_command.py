"""``pio top``: live qps/latency/queue/batch view over running services.

Thin CLI shell around ``obs.top`` (the poll/compute/render pieces live
there so they are testable without a terminal). Point it at any mix of
query servers and event servers::

    pio top http://localhost:8000 http://localhost:7070
"""

from __future__ import annotations

import argparse


def register(sub: argparse._SubParsersAction) -> None:
    top = sub.add_parser(
        "top",
        help="live service stats: qps, p50/p99, error rate, ingest queue"
        " depth, batch occupancy, slowest traces (polls /metrics +"
        " /traces.json)",
    )
    top.add_argument(
        "urls",
        nargs="*",
        default=["http://localhost:8000"],
        help="service base URLs (default: the query server on :8000)",
    )
    top.add_argument(
        "--interval", type=float, default=2.0,
        help="seconds between polls (rates are deltas between polls)",
    )
    top.add_argument(
        "--iterations", type=int, default=0,
        help="stop after N frames (0 = run until interrupted)",
    )
    top.add_argument(
        "--no-clear", action="store_true",
        help="append frames instead of redrawing (log-friendly)",
    )
    top.set_defaults(func=cmd_top)


def cmd_top(args: argparse.Namespace) -> int:
    from predictionio_tpu.obs.top import run_top

    try:
        run_top(
            args.urls or ["http://localhost:8000"],
            interval=args.interval,
            iterations=args.iterations,
            clear=not args.no_clear,
        )
    except KeyboardInterrupt:
        pass
    return 0
