"""Dashboard: evaluation-results web UI (default :9000).

Behavioral model: reference ``tools/.../dashboard/Dashboard.scala`` (apache/
predictionio layout, unverified -- SURVEY.md section 2.4 #31): lists
completed EvaluationInstances with drill-down pages; HTML + a JSON API.
"""

from __future__ import annotations

import html

from predictionio_tpu.data import storage
from predictionio_tpu.utils.http import (
    Request,
    Response,
    ServiceThread,
    instrumented_router,
    make_server,
)

DEFAULT_PORT = 9000

_PAGE = """<!DOCTYPE html>
<html><head><title>predictionio_tpu dashboard</title>
<style>
 body {{ font-family: sans-serif; margin: 2rem; }}
 table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #ccc; padding: .4rem .8rem; text-align: left; }}
 pre {{ background: #f6f6f6; padding: 1rem; overflow-x: auto; }}
</style></head><body>{body}</body></html>"""


class DashboardService:
    def __init__(self):
        self.router, self.metrics = instrumented_router()
        self.router.add("GET", "/", self.handle_index)
        self.router.add("GET", "/engine_instances", self.handle_engine_instances)
        self.router.add("GET", "/evaluation_instances.json", self.handle_list_json)
        # .json route first: <instance_id> would otherwise swallow the suffix
        self.router.add(
            "GET", "/evaluation_instances/<instance_id>.json", self.handle_detail_json
        )
        self.router.add("GET", "/evaluation_instances/<instance_id>", self.handle_detail)

    def handle_index(self, request: Request) -> Response:
        rows = []
        for inst in storage.get_meta_data_evaluation_instances().get_all():
            rows.append(
                f"<tr><td><a href='/evaluation_instances/{inst.id}'>{inst.id[:12]}</a></td>"
                f"<td>{html.escape(inst.evaluation_class)}</td>"
                f"<td>{inst.status}</td>"
                f"<td>{inst.start_time:%Y-%m-%d %H:%M:%S}</td>"
                f"<td>{inst.end_time:%Y-%m-%d %H:%M:%S}</td></tr>"
                if inst.end_time
                else f"<tr><td>{inst.id[:12]}</td>"
                f"<td>{html.escape(inst.evaluation_class)}</td>"
                f"<td>{inst.status}</td>"
                f"<td>{inst.start_time:%Y-%m-%d %H:%M:%S}</td><td>-</td></tr>"
            )
        body = (
            "<h1>Evaluation Instances</h1>"
            "<p><a href='/engine_instances'>engine instances</a></p>"
            "<table><tr><th>ID</th><th>Evaluation</th><th>Status</th>"
            "<th>Start</th><th>End</th></tr>" + "".join(rows) + "</table>"
        )
        return Response(200, _PAGE.format(body=body), content_type="text/html; charset=utf-8")

    def handle_engine_instances(self, request: Request) -> Response:
        rows = [
            f"<tr><td>{inst.id[:12]}</td><td>{html.escape(inst.engine_factory)}</td>"
            f"<td>{inst.status}</td><td>{inst.start_time:%Y-%m-%d %H:%M:%S}</td></tr>"
            for inst in storage.get_meta_data_engine_instances().get_all()
        ]
        body = (
            "<h1>Engine Instances</h1><p><a href='/'>back</a></p>"
            "<table><tr><th>ID</th><th>Factory</th><th>Status</th><th>Start</th></tr>"
            + "".join(rows)
            + "</table>"
        )
        return Response(200, _PAGE.format(body=body), content_type="text/html; charset=utf-8")

    def handle_list_json(self, request: Request) -> Response:
        out = [
            {
                "id": inst.id,
                "evaluationClass": inst.evaluation_class,
                "status": inst.status,
                "startTime": inst.start_time.isoformat(),
                "endTime": inst.end_time.isoformat() if inst.end_time else None,
            }
            for inst in storage.get_meta_data_evaluation_instances().get_all()
        ]
        return Response(200, out)

    def _get(self, instance_id: str):
        return storage.get_meta_data_evaluation_instances().get(instance_id)

    def handle_detail(self, request: Request) -> Response:
        inst = self._get(request.path_params["instance_id"])
        if inst is None:
            return Response(404, _PAGE.format(body="<h1>not found</h1>"),
                            content_type="text/html; charset=utf-8")
        body = (
            f"<h1>Evaluation {inst.id[:12]}</h1><p><a href='/'>back</a></p>"
            f"<p>class: {html.escape(inst.evaluation_class)} | status: {inst.status}</p>"
            + (inst.evaluator_results_html or "<p>(no results)</p>")
        )
        return Response(200, _PAGE.format(body=body), content_type="text/html; charset=utf-8")

    def handle_detail_json(self, request: Request) -> Response:
        inst = self._get(request.path_params["instance_id"])
        if inst is None:
            return Response(404, {"message": "not found"})
        return Response(
            200,
            {
                "id": inst.id,
                "status": inst.status,
                "results": inst.evaluator_results,
                "resultsJson": inst.evaluator_results_json,
            },
        )


def create_dashboard(host: str = "0.0.0.0", port: int = DEFAULT_PORT) -> ServiceThread:
    service = DashboardService()
    return ServiceThread(make_server(service.router, host, port, "pio-dashboard"))


def run_dashboard(host: str = "0.0.0.0", port: int = DEFAULT_PORT) -> None:
    thread = create_dashboard(host, port)
    print(f"Dashboard listening on http://{host}:{port}")
    thread.server.serve_forever()
