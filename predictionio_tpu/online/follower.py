"""WAL tail follower: detect new interactions without rescanning SQL.

The ingest WAL (``data/wal``) already knows exactly what is new -- every
acknowledged event is a framed record with a monotonic seqno. The follower
keeps its OWN durable cursor (independent of the WAL's storage checkpoint,
which tracks the event-store flush) and, each poll, reads only the frames
in ``(cursor, storage-checkpoint]``:

- the upper bound is the WAL's storage high-water mark, NOT the append
  head: a record is acked at WAL durability but the snapshot refresh scans
  SQL, so acting on a record before its storage flush could fold in an
  event the refresh cannot see yet (it waits one poll instead);
- the lower bound is this follower's cursor, which the retrain loop
  advances only after the model reflecting those records was published
  AND swapped -- a crash at any stage replays the same window, and
  fold-in is insensitive to replay (it re-solves from full history).

Segment GC can outrun a follower that was down for a long time (the WAL
only retains segments past ITS checkpoint). That is reported as a ``gap``:
the loop then resynchronizes by refreshing the snapshot to "now" -- the
events are all in the store, only the cheap change detection was lost.

Against a PARTITIONED WAL (``data/wal.PartitionedWal``) the retrain loop
runs one tail + one durable cursor per partition (:func:`partition_tails`
discovers the layout off disk). Every invariant above -- storage-bounded
upper end, advance-after-swap, R003's fsync-before-rename cursor write --
holds independently in each partition; :func:`merge_batches` unions the
per-partition deltas (touched rows, vocab, event-time bounds) into the
single fold-in the loop publishes.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import time
from dataclasses import dataclass, field

from predictionio_tpu.data import wal as wal_mod
from predictionio_tpu.data.ingest import wal_parse

logger = logging.getLogger("pio.online.follower")


class TailCursor:
    """Durable follower position: one JSON file, atomically replaced.

    Holds the last WAL seqno whose effects are reflected in a SWAPPED
    model, plus the snapshot bound (``until_ms``) and row count that model
    was folded against -- the three facts recovery needs. ``advance`` is
    tmp+fsync+rename (the ``data/snapshot`` manifest discipline): a torn
    write can only leave the previous value, which merely re-replays.
    """

    def __init__(self, path: str):
        self.path = path
        self.seqno = 0
        self.until_ms = 0
        self.snapshot_rows = 0
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                obj = json.load(f)
            self.seqno = int(obj.get("seqno", 0))
            self.until_ms = int(obj.get("until_ms", 0))
            self.snapshot_rows = int(obj.get("snapshot_rows", 0))
        except (OSError, ValueError, json.JSONDecodeError):
            pass  # fresh cursor: everything replays, which is safe

    def advance(self, seqno: int, until_ms: int, snapshot_rows: int) -> None:
        self.seqno = max(self.seqno, int(seqno))
        self.until_ms = max(self.until_ms, int(until_ms))
        self.snapshot_rows = int(snapshot_rows)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = f"{self.path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "seqno": self.seqno,
                    "until_ms": self.until_ms,
                    "snapshot_rows": self.snapshot_rows,
                    "updated_at": _dt.datetime.now(
                        _dt.timezone.utc
                    ).isoformat(),
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)


@dataclass
class TailBatch:
    """One poll's worth of newly-flushed interactions (already filtered to
    the followed app/channel/event-name set)."""

    last_seqno: int = 0            # highest seqno examined (filtered or not)
    records: int = 0               # matching interaction records
    touched_users: set = field(default_factory=set)   # entity ids (strings)
    touched_items: set = field(default_factory=set)   # target ids (strings)
    #: ``$set``/``$unset``/``$delete`` property records on the followed
    #: app/channel, by entity TYPE. Property events are not interactions
    #: (they never enter the snapshot window or the lag clock -- the
    #: aggregate they change is read LIVE), but a fold-in must know they
    #: happened: the e-commerce category index comes from the item ``$set``
    #: aggregate and served stale until the next full retrain before this.
    set_records: int = 0
    touched_set_types: set = field(default_factory=set)
    min_event_ms: int | None = None
    max_event_ms: int | None = None
    #: cursor trails the oldest retained segment: records were GC'd before
    #: this follower saw them -- resync from the store, don't trust counts
    gap: bool = False

    @property
    def empty(self) -> bool:
        return self.records == 0 and self.set_records == 0 and not self.gap

    def lag_seconds(self, now: float | None = None) -> float:
        """Age of the OLDEST event in this unreflected window -- the
        ``pio_foldin_lag_seconds`` number (0 when nothing is pending)."""
        if self.min_event_ms is None:
            return 0.0
        now = time.time() if now is None else now
        return max(0.0, now - self.min_event_ms / 1000.0)


class WalTail:
    """Read-only view over another process's WAL directory.

    ``event_names``/``app_id``/``channel_id`` filter the followed scan the
    same way the snapshot spec does, so the tail's touched-user set and
    the refresh's appended rows describe the same events. ``channel_id``
    None follows the default channel (matching the scan semantics where a
    None channel filter means default-channel rows).
    """

    def __init__(
        self,
        directory: str,
        app_id: int,
        channel_id: int | None = None,
        event_names: list[str] | None = None,
    ):
        self.directory = directory
        self.app_id = int(app_id)
        self.channel_id = channel_id
        self.event_names = set(event_names) if event_names else None

    def committed_seqno(self) -> int:
        return wal_mod.read_checkpoint(self.directory)

    def poll(self, after_seqno: int, upto_seqno: int | None = None) -> TailBatch:
        """Scan ``(after_seqno, upto_seqno]`` (default: the storage
        checkpoint) and summarize the matching interactions. Torn or
        unparseable payloads are skipped with a warning -- the snapshot
        refresh (SQL-exact) is the correctness layer; the tail is the
        change detector."""
        batch = TailBatch(last_seqno=after_seqno)
        if upto_seqno is None:
            upto_seqno = self.committed_seqno()
        oldest = wal_mod.oldest_seqno(self.directory)
        if oldest is not None and after_seqno + 1 < oldest:
            # seqnos in (after_seqno, oldest) were GC'd unseen
            batch.gap = True
        for seqno, payload in wal_mod.iter_log_records(
            self.directory, after_seqno=after_seqno, upto_seqno=upto_seqno
        ):
            batch.last_seqno = max(batch.last_seqno, seqno)
            try:
                event, app_id, channel_id, _trace = wal_parse(payload)
            except Exception:
                logger.warning(
                    "skipping unparseable WAL record %d", seqno, exc_info=True
                )
                continue
            if app_id != self.app_id or channel_id != self.channel_id:
                continue
            if event.event.startswith("$"):
                # property records ($set/$unset/$delete): tracked by
                # entity type so fold-in can refresh property-derived
                # indexes (e.g. e-commerce categories); never counted as
                # interactions and never part of the snapshot window
                batch.set_records += 1
                batch.touched_set_types.add(event.entity_type)
                continue
            if self.event_names is not None and event.event not in self.event_names:
                continue
            batch.records += 1
            batch.touched_users.add(event.entity_id)
            if event.target_entity_id is not None:
                batch.touched_items.add(event.target_entity_id)
            ms = int(event.event_time.timestamp() * 1000)
            if batch.min_event_ms is None or ms < batch.min_event_ms:
                batch.min_event_ms = ms
            if batch.max_event_ms is None or ms > batch.max_event_ms:
                batch.max_event_ms = ms
        return batch


def partition_tails(
    directory: str,
    app_id: int,
    channel_id: int | None = None,
    event_names: list[str] | None = None,
) -> list[WalTail]:
    """One :class:`WalTail` per WAL partition, in partition order. The
    layout is read off disk (``data/wal.partition_count``), NOT configured:
    the follower runs in a different process than the ingest writer, and
    trusting a flag over the marker file would tail directories the writer
    never fills. A flat P=1 log yields a single tail on the root."""
    return [
        WalTail(part_dir, app_id, channel_id, event_names)
        for part_dir in wal_mod.partition_dirs(directory)
    ]


def merge_batches(batches: list[TailBatch]) -> TailBatch:
    """Union per-partition poll results into the ONE delta the loop folds:
    touched users/items/set-types union, record counts sum, event-time
    window spans the widest bounds, and any partition's GC gap poisons the
    merge (lost records may touch anything). ``last_seqno`` is the max
    across INDEPENDENT per-partition seqno spaces -- diagnostic only
    (registry metadata); cursor advancement is always per-partition."""
    merged = TailBatch()
    for b in batches:
        merged.last_seqno = max(merged.last_seqno, b.last_seqno)
        merged.records += b.records
        merged.set_records += b.set_records
        merged.touched_users |= b.touched_users
        merged.touched_items |= b.touched_items
        merged.touched_set_types |= b.touched_set_types
        merged.gap = merged.gap or b.gap
        for bound in ("min_event_ms", "max_event_ms"):
            val = getattr(b, bound)
            if val is None:
                continue
            cur = getattr(merged, bound)
            if cur is None:
                setattr(merged, bound, val)
            elif bound == "min_event_ms":
                setattr(merged, bound, min(cur, val))
            else:
                setattr(merged, bound, max(cur, val))
    return merged
