"""ALS fold-in: solve only new/touched user rows against frozen item factors.

ALX (arxiv 2112.02194) makes the point that the per-row ALS solve is
cheap: one K x K normal-equation system per row. Between full retrains,
that is exactly enough to keep a deployed factor model fresh -- a user who
just rated something gets their row re-solved against the CURRENT item
factors (one fused gather->Gram half-step over a delta CSR block, the
``ops/als_gram`` kernel), while every untouched row keeps its trained
factors bit-for-bit. New users append rows; new items append zero factors
(they score 0 until the next full retrain -- which the staleness budget
triggers once item-vocab growth makes zero rows matter).

Correctness contract (the parity test pins it): a folded user row equals
the exact ridge solution of that user's normal equations against the
frozen item factors -- which is precisely what a full retrain's final
user half-step computes, given the same item factors. Fold-in is therefore
idempotent over replayed windows (it re-solves from the user's FULL
history, not incrementally), which is what makes the loop's crash
recovery safe: re-running a window after a SIGKILL converges to the same
factors.
"""

from __future__ import annotations

import functools
import logging
from dataclasses import dataclass, field

import numpy as np

logger = logging.getLogger("pio.online.foldin")


class StalenessExceeded(Exception):
    """The delta outgrew the fold-in budget; escalate to a full retrain."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


@dataclass
class StalenessBudget:
    """When incremental fold-in stops being a good approximation.

    - ``max_touched_frac``: once this fraction of known users was touched
      since the last full retrain, the frozen item factors are stale for a
      large share of the matrix -- retrain instead of folding;
    - ``max_item_growth_frac``: new (zero-factor) items as a fraction of
      the known catalog; zero rows never get recommended, so growth here
      is silent quality loss;
    - ``max_user_growth_frac``: same for appended user rows (cheap but
      still an approximation against frozen items).
    """

    max_touched_frac: float = 0.2
    max_item_growth_frac: float = 0.05
    max_user_growth_frac: float = 0.5

    def check(
        self,
        touched_users: int,
        known_users: int,
        new_users: int,
        new_items: int,
        known_items: int,
    ) -> None:
        """Raise :class:`StalenessExceeded` when any threshold trips."""
        users = max(known_users, 1)
        items = max(known_items, 1)
        if touched_users / users > self.max_touched_frac:
            raise StalenessExceeded(
                f"touched-user fraction {touched_users}/{users} exceeds"
                f" {self.max_touched_frac}"
            )
        if new_items / items > self.max_item_growth_frac:
            raise StalenessExceeded(
                f"item-vocab growth {new_items}/{items} exceeds"
                f" {self.max_item_growth_frac}"
            )
        if new_users / users > self.max_user_growth_frac:
            raise StalenessExceeded(
                f"user-vocab growth {new_users}/{users} exceeds"
                f" {self.max_user_growth_frac}"
            )


@dataclass
class FoldinDelta:
    """What the retrain loop hands an algorithm's ``fold_in`` hook.

    ``snapshot`` is the refreshed columnar generation (``data/snapshot``);
    ``window_start_ms`` bounds the NEW rows (``event_time_ms >=``); the
    model must come to reflect everything in the window, and MAY re-reflect
    older rows (fold-in re-solves from full history, so overlap is free).
    ``touched_user_ids`` (entity-id strings, from the WAL tail) widens the
    touched set beyond the window when provided -- e.g. records whose
    client-supplied event time predates the window.
    """

    snapshot: object
    window_start_ms: int
    touched_user_ids: set | None = None
    budget: StalenessBudget = field(default_factory=StalenessBudget)
    #: datasource knobs riding the online handle (e.g. the e-commerce
    #: template's per-event confidence map) -- DASE keeps per-component
    #: params separate, so the loop forwards them here
    extras: dict = field(default_factory=dict)
    #: entity types that received ``$set``/``$unset``/``$delete`` records
    #: in this window (from the WAL tail): algorithms deriving state from
    #: a property aggregate (the e-commerce category index) rescan it
    #: instead of serving the stale index until a full retrain
    set_entity_types: set | None = None


def _pow2_ceil(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


#: id(host array) -> (weakref, device copy). Tiny by construction: the
#: retrain loop holds a handful of live factor tables at once.
_DEVICE_FACTOR_CACHE: dict = {}


def _device_factors(item_factors: np.ndarray):
    """Device copy of the frozen item factors, cached across fold-in
    cycles. Between full retrains the item table is REPLACED, never
    mutated (``fold_in_als_model`` vstacks a new array when items grow,
    else passes the same object through), so object identity is a sound
    cache key -- and without the cache every ``pio retrain --follow``
    cycle re-shipped the model's largest array to the device to solve a
    handful of touched rows (the J006 loop-invariant-transfer shape,
    hoisted here because the "loop" spans run_once calls rather than a
    lexical ``for``). The weakref guards id() reuse after GC."""
    import weakref

    import jax

    key = id(item_factors)
    hit = _DEVICE_FACTOR_CACHE.get(key)
    if hit is not None and hit[0]() is item_factors:
        return hit[1]
    # prune DEAD entries only (host array GC'd): a bulk clear at a count
    # threshold would pin up to N dead device tables until it fired AND
    # evict the live hot entry with them -- on an accelerator that is HBM
    # held by garbage plus a forced full re-ship next cycle
    for k in [k for k, (ref, _) in _DEVICE_FACTOR_CACHE.items() if ref() is None]:
        del _DEVICE_FACTOR_CACHE[k]
    dev = jax.device_put(np.asarray(item_factors, np.float32))
    _DEVICE_FACTOR_CACHE[key] = (weakref.ref(item_factors), dev)
    return dev


@functools.lru_cache(maxsize=16)
def _build_solver(solver: str, implicit: bool, rank: int, platform: str):
    """One jitted delta half-step per (solver, mode, rank, platform) --
    repeated fold-ins reuse the compiled program (shapes are padded to a
    pow2 ladder below for the same reason)."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops.als_gram import gram_rhs
    from predictionio_tpu.parallel.als import (
        _append_zero_row,
        _factors_yty,
        _finish_explicit,
        _finish_implicit,
        _half_step_explicit,
        _half_step_implicit,
    )

    unroll = platform != "cpu"
    interpret = platform == "cpu"

    def step(indices, values, n_obs, factors, reg, alpha):
        full = _append_zero_row(factors)
        if solver == "pallas":
            gram, rhs = gram_rhs(
                indices.astype(jnp.int32), values, full, alpha,
                implicit=implicit, interpret=interpret,
            )
            if implicit:
                return _finish_implicit(
                    gram, rhs, _factors_yty(factors), reg, rank, unroll,
                    factors.dtype,
                )
            return _finish_explicit(
                gram, rhs, n_obs, reg, rank, unroll, factors.dtype
            )
        if implicit:
            return _half_step_implicit(
                indices, values, n_obs, full, _factors_yty(factors), reg,
                alpha, rank, unroll,
            )
        return _half_step_explicit(indices, values, n_obs, full, reg, rank, unroll)

    return jax.jit(step)


def fold_in_users(
    item_factors: np.ndarray,
    rows: np.ndarray,
    cols: np.ndarray,
    values: np.ndarray,
    num_rows: int,
    config,
    times: np.ndarray | None = None,
) -> np.ndarray:
    """Solve ``num_rows`` user rows against frozen ``item_factors``.

    ``(rows, cols, values)`` is the touched users' FULL interaction COO in
    local row order (``rows`` in ``[0, num_rows)``) and MODEL item space
    (``cols`` indexing ``item_factors``). Returns ``[num_rows, K]`` f32 --
    the exact ridge/implicit solution per row, via the same half-step tail
    ``als_fit`` runs (``config.solver`` resolves "auto" like training:
    the fused Pallas kernel on accelerators, XLA einsums on CPU).

    Shapes are padded to a pow2 ladder (rows AND history length) so a
    long-running loop compiles a handful of programs, not one per delta.
    """
    import jax

    from predictionio_tpu.ops.ragged import pack_padded_csr
    from predictionio_tpu.parallel.als import resolve_solver

    if num_rows == 0:
        return np.zeros((0, item_factors.shape[1]), np.float32)
    platform = jax.default_backend()
    solver = resolve_solver(config.solver, platform)
    counts = np.bincount(np.asarray(rows, np.int64), minlength=num_rows)
    longest = int(counts.max()) if counts.size else 1
    if config.max_len:
        longest = min(longest, int(config.max_len))
    csr = pack_padded_csr(
        rows,
        cols,
        np.asarray(values, np.float32),
        num_rows=_pow2_ceil(num_rows),
        num_cols=item_factors.shape[0],
        max_len=config.max_len,
        times=times,
        pad_len=_pow2_ceil(max(longest, 1)),
    )
    step = _build_solver(solver, bool(config.implicit), item_factors.shape[1], platform)
    out = step(
        csr.indices,
        csr.values,
        csr.mask.sum(axis=1).astype(np.float32),
        # hoisted: the frozen table ships once, not once per cycle
        _device_factors(item_factors),
        np.float32(config.reg),
        np.float32(config.alpha),
    )
    return np.asarray(out)[:num_rows].astype(np.float32)


@dataclass
class AlsFoldResult:
    """A folded ALS-family model core plus the vocab/bookkeeping both ALS
    templates share; template-specific carriers wrap this."""

    als: object                       # parallel.als.ALSModel
    user_index: dict
    item_ids: list
    item_index: dict
    touched_users: int
    new_users: int
    new_items: int
    #: (model user row, model item idx) pairs of the WINDOW rows only --
    #: what a trained-in seen map must absorb
    window_pairs: np.ndarray | None = None
    max_window_ms: int = 0


def fold_in_als_model(
    als,
    user_index: dict,
    item_ids: list,
    item_index: dict,
    delta: FoldinDelta,
    config,
    event_values: dict | None = None,
    rating_default: float = 1.0,
) -> AlsFoldResult | None:
    """The shared fold both ALS templates run over a refreshed snapshot.

    Reads the snapshot's columns, finds the users touched inside the
    delta window (unioned with ``delta.touched_user_ids``), maps entities
    by STRING id into the model's spaces (so snapshot rebuilds that
    renumber codes cannot misalign factors), extends vocabularies for new
    users/items, and re-solves the touched rows from their full history.
    Returns None when the window holds no usable interaction. Raises
    :class:`StalenessExceeded` per ``delta.budget`` BEFORE any solve.

    ``event_values`` (e-commerce streaming parity) scores each row by its
    event name; otherwise the rating column is used with NaN ->
    ``rating_default`` (the recommendation template's implicit-event
    convention).
    """
    snap = delta.snapshot
    users_c = np.asarray(snap.column("users"))
    items_c = np.asarray(snap.column("items"))
    names_c = np.asarray(snap.column("names"))
    times = np.asarray(snap.column("times"))
    ratings = np.asarray(snap.column("ratings"))
    uvocab = snap.vocab("users")
    ivocab = snap.vocab("items")
    nvocab = snap.vocab("names")

    valid = items_c >= 0
    times_ms = (times * 1000.0).astype(np.int64)
    window = valid & (times_ms >= delta.window_start_ms)
    touched_codes = np.unique(users_c[window])
    if delta.touched_user_ids:
        # WAL-reported users whose event times predate the window (client
        # timestamps): widen by string id. One C-speed dict build, not a
        # per-element python membership scan over the vocab.
        code_of = {uid: code for code, uid in enumerate(uvocab)}
        extra = {
            code_of[uid]
            for uid in delta.touched_user_ids
            if uid in code_of
        }
        extra -= set(touched_codes.tolist())
        if extra:
            touched_codes = np.sort(
                np.concatenate([touched_codes, np.fromiter(extra, np.int64)])
            )
    if touched_codes.size == 0:
        return None

    history = valid & np.isin(users_c, touched_codes)
    h_users = users_c[history]
    h_items = items_c[history]
    h_names = names_c[history]
    h_times = times[history]
    h_ratings = ratings[history]

    # -- map entities into MODEL space, extending for new ones -------------
    user_index = dict(user_index)
    item_index = dict(item_index)
    item_ids = list(item_ids)
    known_users = len(user_index)
    known_items = len(item_index)
    local_of_code: dict[int, int] = {}
    model_row_of_local: list[int] = []
    new_users = 0
    for code in touched_codes.tolist():
        uid = uvocab[code]
        row = user_index.get(uid)
        if row is None:
            row = len(user_index)
            user_index[uid] = row
            new_users += 1
        local_of_code[code] = len(model_row_of_local)
        model_row_of_local.append(row)
    item_model_of_code: dict[int, int] = {}
    new_items = 0
    for code in np.unique(h_items).tolist():
        iid = ivocab[code]
        idx = item_index.get(iid)
        if idx is None:
            idx = len(item_index)
            item_index[iid] = idx
            item_ids.append(iid)
            new_items += 1
        item_model_of_code[code] = idx

    delta.budget.check(
        touched_users=int(touched_codes.size),
        known_users=known_users,
        new_users=new_users,
        new_items=new_items,
        known_items=known_items,
    )

    rank = als.item_factors.shape[1]
    item_factors = als.item_factors
    if new_items:
        item_factors = np.vstack(
            [item_factors, np.zeros((new_items, rank), item_factors.dtype)]
        )

    rows_local = np.fromiter(
        (local_of_code[c] for c in h_users.tolist()), np.int64,
        count=h_users.size,
    )
    cols_model = np.fromiter(
        (item_model_of_code[c] for c in h_items.tolist()), np.int64,
        count=h_items.size,
    )
    if event_values is not None:
        by_code = np.asarray(
            [float(event_values.get(n, 1.0)) for n in nvocab], np.float32
        )
        vals = by_code[h_names]
    else:
        vals = np.where(
            np.isnan(h_ratings), rating_default, h_ratings
        ).astype(np.float32)

    solved = fold_in_users(
        item_factors, rows_local, cols_model, vals,
        num_rows=len(model_row_of_local), config=config, times=h_times,
    )
    user_factors = als.user_factors
    if new_users:
        user_factors = np.vstack(
            [user_factors, np.zeros((new_users, rank), user_factors.dtype)]
        )
    else:
        user_factors = user_factors.copy()
    user_factors[np.asarray(model_row_of_local, np.int64)] = solved

    from predictionio_tpu.parallel.als import ALSModel

    w_users = users_c[window]
    w_items = items_c[window]
    window_pairs = np.stack(
        [
            np.fromiter(
                (user_index[uvocab[c]] for c in w_users.tolist()), np.int64,
                count=w_users.size,
            ),
            np.fromiter(
                (item_index[ivocab[c]] for c in w_items.tolist()), np.int64,
                count=w_items.size,
            ),
        ],
        axis=1,
    ) if w_users.size else None
    return AlsFoldResult(
        als=ALSModel(user_factors=user_factors, item_factors=item_factors),
        user_index=user_index,
        item_ids=item_ids,
        item_index=item_index,
        touched_users=int(touched_codes.size),
        new_users=new_users,
        new_items=new_items,
        window_pairs=window_pairs,
        max_window_ms=int(times_ms[window].max()) if window.any() else 0,
    )
